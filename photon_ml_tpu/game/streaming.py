"""Out-of-core GAME training: streamed coordinate descent over spilled
chunks under an explicit host-memory budget.

Reference: the whole reference pipeline is passes over RDDs — prepare
(DataProcessingUtils.scala), per-entity grouping
(RandomEffectDataSet.scala:169-369), coordinate updates
(CoordinateDescent.scala:130-262) — with Spark spilling anything larger
than executor memory to disk. The in-memory path here
(game/coordinate_descent.py) instead stages the WHOLE train set in host
RAM, which caps GAME at dataset <= RAM. This module restores the
out-of-core shape on one host:

- **Scan pass** (:func:`scan_game_stream`): one bounded pass over the
  Avro files collecting per-shard vocabularies, entity indexes, row
  counts and staging widths — O(model) memory, never O(dataset).
- **Stage pass** (:func:`stage_game_stream`): rows stream once into
  fixed-shape chunks spilled to scratch (:class:`GameChunkStore`, the
  GAME analog of io.streaming's _DiskChunkStore) whose row budget comes
  from ``--stream-memory-budget``.
- **Streamed CD** (:class:`StreamingCoordinateDescent`): the fixed
  effect trains through a StreamingGLMObjective-shaped chunk objective
  with the residual folded into offsets chunk by chunk; random effects
  solve bucket-SEGMENT by segment from a disk spill of the per-entity
  grouping (:class:`SpilledREBuckets` — the groupByKey shuffle as a
  budget-bounded scatter into disk-backed blocks, no sort); scores and
  residuals live on disk per chunk (:class:`ScoreStore`) — the
  KeyValueScore currency never needs an [n]-resident host array.

Peak host memory is bounded by one staged chunk + one bucket segment +
the models themselves (coefficients, banks, vocabularies — the parts
that must be resident to be trained at all).

Scope gates (validated up front, mirrored in the driver): IDENTITY
random-effect projector (the local space IS the shard space, so chunk
rows need no per-entity re-indexing pass), no reservoir cap on active
data (the cap's sampling would need a second grouped pass), single
process, plain (non-factored) coordinates.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.game.config import (
    FeatureShardConfiguration,
    FixedEffectDataConfiguration,
    ProjectorType,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.game.data import (
    EntityIndex,
    _padded_width,
    record_entity_id,
    record_response,
)
from photon_ml_tpu.obs.trace import start_span
from photon_ml_tpu.obs.trace import traced as obs_traced
from photon_ml_tpu.io.streaming import (
    make_spill_dir,
    sparse_row_bytes,
    stream_budget_rows,
    unregister_spill_dir,
)
from photon_ml_tpu.utils.index_map import IndexMap, feature_key, intercept_key
from photon_ml_tpu.utils.logging_util import PhotonLogger


# ---------------------------------------------------------------------------
# scan pass
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class GameStreamStats:
    """One-scan results fixing every later pass's shapes."""

    num_rows: int
    shard_nnz: Dict[str, int]  # padded per-row width per shard (incl icept)
    # ACTIVE (weight > 0) row count per entity code, per RE type — fixes
    # the bucket capacities without a grouping pass
    entity_counts: Dict[str, np.ndarray]


def _game_files(paths) -> List[str]:
    from photon_ml_tpu.io.paths import expand_input_paths

    files = sorted(
        expand_input_paths(list(paths), lambda fn: fn.endswith(".avro"))
    )
    if not files:
        raise ValueError(f"no .avro inputs under {paths!r}")
    return files


def _stream_records(paths):
    """Record stream, ONE file resident at a time (python codec; the
    native column decoder holds whole-file columns either way, so the
    bounded unit is identical). Each file's decode runs behind the
    ``chunk_read`` seam — the whole-file read is the idempotent retry
    unit, exactly like io.streaming._file_rows."""
    from photon_ml_tpu.io.avro_codec import read_avro_records
    from photon_ml_tpu.reliability.retry import io_call

    for path in _game_files(paths):
        yield from io_call(
            "chunk_read",
            lambda path=path: list(read_avro_records([path])),
            detail=path,
        )


@obs_traced("streaming.scan")
def scan_game_stream(
    paths,
    shard_configs: Sequence[FeatureShardConfiguration],
    re_types: Sequence[str],
    *,
    index_maps: Optional[Mapping[str, IndexMap]] = None,
) -> Tuple[Dict[str, IndexMap], Dict[str, EntityIndex], GameStreamStats]:
    """One bounded pass: per-shard vocabularies (skipped per shard when a
    prebuilt map is given), entity id sets + active counts, row count and
    per-shard max nnz. Entity codes come out IDENTICAL to the in-memory
    builder's (EntityIndex.build sorts distinct ids), and IndexMap.build
    sorts keys, so the streamed fit trains in the same index space as the
    in-memory fit over the same files."""
    key_sets: Dict[str, set] = {
        cfg.shard_id: set()
        for cfg in shard_configs
        if index_maps is None or cfg.shard_id not in index_maps
    }
    max_live: Dict[str, int] = {cfg.shard_id: 0 for cfg in shard_configs}
    id_counts: Dict[str, Dict[str, int]] = {t: {} for t in re_types}
    num_rows = 0
    for r in _stream_records(paths):
        wgt_v = r.get("weight")
        w = 1.0 if wgt_v is None else float(wgt_v)
        for cfg in shard_configs:
            live = 0
            for bag in cfg.feature_bags:
                for f in r.get(bag) or []:
                    live += 1
                    if cfg.shard_id in key_sets:
                        key_sets[cfg.shard_id].add(
                            feature_key(f["name"], f["term"])
                        )
            max_live[cfg.shard_id] = max(max_live[cfg.shard_id], live)
        for t in re_types:
            rid = record_entity_id(r, t)
            c = id_counts[t]
            c[rid] = c.get(rid, 0) + (1 if w > 0 else 0)
        num_rows += 1
    if num_rows == 0:
        raise ValueError("empty GAME dataset")
    imaps: Dict[str, IndexMap] = {}
    for cfg in shard_configs:
        if index_maps is not None and cfg.shard_id in index_maps:
            imaps[cfg.shard_id] = index_maps[cfg.shard_id]
        else:
            imaps[cfg.shard_id] = IndexMap.build(
                iter(key_sets[cfg.shard_id]), add_intercept=cfg.add_intercept
            )
    entity_indexes: Dict[str, EntityIndex] = {}
    entity_counts: Dict[str, np.ndarray] = {}
    for t in re_types:
        eidx = EntityIndex.build(t, id_counts[t].keys())
        entity_indexes[t] = eidx
        entity_counts[t] = np.asarray(
            [id_counts[t][rid] for rid in eidx.ids], np.int64
        )
    shard_nnz = {
        cfg.shard_id: _padded_width(
            max_live[cfg.shard_id] + (1 if cfg.add_intercept else 0), 8
        )
        for cfg in shard_configs
    }
    return imaps, entity_indexes, GameStreamStats(
        num_rows=num_rows, shard_nnz=shard_nnz, entity_counts=entity_counts
    )


def game_row_bytes(
    shard_nnz: Mapping[str, int], num_re_types: int
) -> int:
    """Staged bytes per row of one GAME chunk: every shard's padded
    sparse slots + label/offset/weight + one int32 code per RE type."""
    return (
        sum(sparse_row_bytes(k) - 12 for k in shard_nnz.values())
        + 12
        + 4 * num_re_types
    )


# ---------------------------------------------------------------------------
# spilled stores
# ---------------------------------------------------------------------------


class GameChunkStore:
    """Fixed-shape staged GAME chunks spilled to scratch: labels/offsets/
    weights [R], one int32 entity-code column per RE type, one padded
    sparse (ix, v) pair per feature shard. The final chunk pads with
    weight-0 rows (inert in every consumer); global row id of chunk i's
    row j is ``i * R + j`` — the join key between chunks, score stores
    and bucket row indexes.

    ``persist_dir``: crash-safe mode — the store lives in a NAMED
    directory (under the driver's --checkpoint-dir) instead of swept
    scratch, with a manifest updated atomically after every appended
    chunk. A killed stage pass resumes from the completed chunks: the
    constructor truncates any torn trailing partial chunk and reopens
    the field files for append, and ``stage_game_stream`` skips the
    records already staged instead of restaging everything."""

    def __init__(
        self,
        rows_per_chunk: int,
        shard_nnz: Mapping[str, int],
        re_types: Sequence[str],
        spill_dir: Optional[str] = None,
        *,
        persist_dir: Optional[str] = None,
    ):
        self.R = int(rows_per_chunk)
        self.shard_nnz = dict(shard_nnz)
        self.re_types = list(re_types)
        self.persistent = persist_dir is not None
        self._manifest: Dict[str, object] = {}
        self.count = 0
        self.num_real_rows = 0
        self._fields = (
            ["lab", "off", "wgt"]
            + [f"code__{t}" for t in self.re_types]
            + [x for s in self.shard_nnz for x in (f"ix__{s}", f"v__{s}")]
        )
        if not self.persistent:
            self.dir = make_spill_dir("photon-game-spill-", spill_dir)
            self._writers = {
                f: open(os.path.join(self.dir, f + ".bin"), "wb")
                for f in self._fields
            }
        else:
            self.dir = os.path.abspath(persist_dir)
            self._open_persistent()
        self._mm: Optional[Dict[str, np.memmap]] = None

    # -- crash-safe persistence --------------------------------------------

    def _config(self) -> Dict[str, object]:
        return {
            "rows_per_chunk": self.R,
            "shard_nnz": dict(sorted(self.shard_nnz.items())),
            "re_types": list(self.re_types),
        }

    def _open_persistent(self) -> None:
        from photon_ml_tpu.reliability.manifest import ensure_run_manifest

        manifest = ensure_run_manifest(
            self.dir, self._config(), kind="game-chunk-store"
        )
        self._manifest = manifest
        self.count = int(manifest.get("chunks", 0))
        self.num_real_rows = int(manifest.get("real_rows", 0))
        # reopen for append; truncate each field file to exactly the
        # manifest's completed chunks — a torn trailing partial chunk
        # (killed mid-append) is dropped and restaged
        self._writers = {}
        for f in self._fields:
            path = os.path.join(self.dir, f + ".bin")
            if not os.path.exists(path):
                open(path, "wb").close()
            fh = open(path, "r+b")
            shape = self._shape(f)
            per_chunk = int(
                np.dtype(self._dtype(f)).itemsize * int(np.prod(shape))
            )
            fh.truncate(self.count * per_chunk)
            self._writers[f] = fh

    def _sync_manifest(self, **extra) -> None:
        """Publish progress atomically (persistent stores only)."""
        if not self.persistent:
            return
        from photon_ml_tpu.reliability.manifest import write_manifest

        self._manifest.update(
            chunks=self.count, real_rows=self.num_real_rows, **extra
        )
        write_manifest(self.dir, self._manifest)

    @property
    def rows_consumed(self) -> int:
        """Records consumed from the input stream by the appended chunks
        — ``real_rows`` counts every staged record (weight-0 included;
        padding rows are not records), so it doubles as the resume skip
        count for an interrupted stage pass."""
        return self.num_real_rows

    @property
    def staged(self) -> bool:
        return bool(self._manifest.get("staged"))

    def mark_staged(self) -> None:
        self._sync_manifest(staged=True)

    def fill_done(self, tag: str) -> bool:
        return bool(self._manifest.get(f"fill__{tag}"))

    def mark_fill_done(self, tag: str) -> None:
        self._sync_manifest(**{f"fill__{tag}": True})

    def _shape(self, field: str) -> Tuple[int, ...]:
        if field.startswith(("ix__", "v__")):
            return (self.R, self.shard_nnz[field.split("__", 1)[1]])
        return (self.R,)

    def _dtype(self, field: str):
        return (
            np.int32
            if field.startswith(("ix__", "code__"))
            else np.float32
        )

    def append(self, arrays: Mapping[str, np.ndarray], real_rows: int) -> None:
        from photon_ml_tpu.reliability.retry import io_call

        for f in self._fields:
            a = np.ascontiguousarray(arrays[f], self._dtype(f))
            assert a.shape == self._shape(f), (f, a.shape)
            data = a.tobytes()
            w = self._writers[f]
            off = self.count * len(data)

            def _write(w=w, data=data, off=off):
                # fixed per-chunk offset: a retried attempt overwrites in
                # place, so a partial write can never shift later chunks
                w.seek(off)
                w.write(data)

            io_call(
                "spill_write", _write,
                detail=f"{self.dir}/{f}.bin[{self.count}]",
            )
        self.count += 1
        self.num_real_rows += int(real_rows)
        if self.persistent:
            for w in self._writers.values():
                w.flush()
            self._sync_manifest()

    def finalize(self) -> None:
        for w in self._writers.values():
            w.close()
        self._mm = {
            f: np.memmap(
                os.path.join(self.dir, f + ".bin"),
                self._dtype(f), "r", shape=(self.count,) + self._shape(f),
            )
            for f in self._fields
        }

    def chunk(self, i: int) -> Dict[str, np.ndarray]:
        """Materialize ONE chunk's arrays (copies — bounded by R rows),
        behind the spill_read seam (idempotent, so transient errors
        retry in place)."""
        from photon_ml_tpu.reliability.retry import io_call

        assert self._mm is not None, "finalize() the store before reading"
        return io_call(
            "spill_read",
            lambda: {f: np.array(self._mm[f][i]) for f in self._fields},
            detail=f"{self.dir}[{i}]",
        )

    @property
    def num_rows_padded(self) -> int:
        return self.count * self.R

    def score_store(self, name: str) -> "ScoreStore":
        return ScoreStore(self.dir, name, self.count, self.R)

    def close(self) -> None:
        import shutil

        for w in self._writers.values():
            if not w.closed:
                w.close()
        self._mm = None
        if self.persistent:
            # a crash-safe store is the RESUME currency — it outlives the
            # process on purpose; the driver removes it after a completed
            # run publishes its model
            return
        unregister_spill_dir(self.dir)
        shutil.rmtree(self.dir, ignore_errors=True)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class ScoreStore:
    """One coordinate's row-aligned scores as a [num_chunks, R] float32
    disk file — the KeyValueScore currency spilled per chunk. Random
    access by global row id goes through the flat memmap view (the RE
    bucket residual gather), sequential access per chunk through
    get/set_chunk (both behind the spill seams). Lives inside its
    GameChunkStore's scratch dir, so the atexit sweep covers it too.
    Scores are always recomputed from coordinate states, so a resumed
    run simply re-creates the files — no manifest needed."""

    def __init__(self, base_dir: str, name: str, num_chunks: int, R: int):
        self.path = os.path.join(base_dir, f"score__{name}.bin")
        self.num_chunks, self.R = num_chunks, R
        self._mm = np.memmap(
            self.path, np.float32, "w+", shape=(num_chunks, R)
        )  # zero-initialized: matches score(initial zero models) exactly

    def get_chunk(self, i: int) -> np.ndarray:
        from photon_ml_tpu.reliability.retry import io_call

        return io_call(
            "spill_read", lambda: np.array(self._mm[i]),
            detail=f"{self.path}[{i}]",
        )

    def set_chunk(self, i: int, scores) -> None:
        from photon_ml_tpu.reliability.retry import io_call

        data = np.asarray(scores, np.float32)

        def _write():
            self._mm[i] = data

        io_call("spill_write", _write, detail=f"{self.path}[{i}]")

    def flat(self) -> np.ndarray:
        """[num_chunks * R] memmap view for global-row-id gathers."""
        return self._mm.reshape(-1)


@obs_traced("streaming.stage")
def stage_game_stream(
    paths,
    shard_configs: Sequence[FeatureShardConfiguration],
    re_types: Sequence[str],
    index_maps: Mapping[str, IndexMap],
    entity_indexes: Mapping[str, EntityIndex],
    stats: GameStreamStats,
    *,
    rows_per_chunk: int,
    spill_dir: Optional[str] = None,
    strict_ids: bool = True,
    reservoir_rows: int = 0,
    seed: int = 0,
    persist_dir: Optional[str] = None,
) -> Tuple[GameChunkStore, Optional[Dict[str, np.ndarray]]]:
    """Stream rows once into a spilled GameChunkStore. ``strict_ids``
    False maps entity ids absent from ``entity_indexes`` to code -1
    instead of raising — the validation staging mode, where unseen
    entities score 0 (the reference's outer join on idTypeToValueMap).

    ``reservoir_rows``: optional algorithm-R uniform sample of REAL rows
    (labels/offsets/weights + every shard's padded features — the GAME
    diagnostics reservoir). The caller byte-budgets the row count with
    io.streaming.budgeted_rows over :func:`game_row_bytes`, so wide
    multi-shard rows scale the sample DOWN exactly like the GLM driver's
    reservoir.

    ``persist_dir``: crash-safe staging — the store persists there with
    a progress manifest, and an interrupted stage pass RESUMES: records
    already staged (``store.rows_consumed``) are skipped from the input
    stream instead of restaged, and a store already marked staged
    returns immediately. The staged bytes are bitwise identical to an
    uninterrupted pass (records stream deterministically); only the
    diagnostics reservoir differs on resume (it samples the remaining
    tail — diagnostics-only, never model-affecting)."""
    R = int(rows_per_chunk)
    store = GameChunkStore(
        R, stats.shard_nnz, re_types, spill_dir, persist_dir=persist_dir
    )
    if store.staged:
        store.finalize()
        return store, None
    skip_records = store.rows_consumed
    icepts = {}
    for cfg in shard_configs:
        imap = index_maps[cfg.shard_id]
        icepts[cfg.shard_id] = (
            imap.get_index(intercept_key()) if cfg.add_intercept else -1
        )
    rng = np.random.default_rng(seed)
    K = int(reservoir_rows)
    res = None
    if K:
        res = {
            "lab": np.zeros(K, np.float32),
            "off": np.zeros(K, np.float32),
            "wgt": np.zeros(K, np.float32),
        }
        for sid, k in stats.shard_nnz.items():
            res[f"ix__{sid}"] = np.zeros((K, k), np.int32)
            res[f"v__{sid}"] = np.zeros((K, k), np.float32)
    seen_real = 0

    def new_bufs():
        bufs = {
            "lab": np.zeros(R, np.float32),
            "off": np.zeros(R, np.float32),
            "wgt": np.zeros(R, np.float32),
        }
        for t in re_types:
            bufs[f"code__{t}"] = np.full(R, -1, np.int32)
        for sid, k in stats.shard_nnz.items():
            bufs[f"ix__{sid}"] = np.zeros((R, k), np.int32)
            bufs[f"v__{sid}"] = np.zeros((R, k), np.float32)
        return bufs

    bufs = new_bufs()
    fill = 0
    records = _stream_records(paths)
    if skip_records:
        # resume: fast-forward past the records the completed chunks
        # already staged (the decode cost of the skip is unavoidable;
        # the staging/scatter cost is not)
        import itertools

        records = itertools.islice(records, skip_records, None)
    from photon_ml_tpu.io.streaming import _prefetched
    from photon_ml_tpu.parallel.overlap import overlap_enabled

    if overlap_enabled() and (os.cpu_count() or 1) > 1:
        # decode-ahead through the existing prefetch pipeline: the worker
        # decodes/normalizes ahead while this thread scatters into the
        # staging buffers (multicore-gated exactly like iter_chunks)
        records = _prefetched(records, depth=2 * R)
    for r in records:
        bufs["lab"][fill] = record_response(r)
        off_v = r.get("offset")
        wgt_v = r.get("weight")
        bufs["off"][fill] = 0.0 if off_v is None else float(off_v)
        w = 1.0 if wgt_v is None else float(wgt_v)
        bufs["wgt"][fill] = w
        for t in re_types:
            rid = record_entity_id(r, t)
            code = entity_indexes[t].code_of.get(rid, -1)
            if code < 0 and strict_ids:
                raise ValueError(
                    f"entity id {rid!r} of type {t!r} missing from the "
                    "scan-pass index (inputs changed between passes?)"
                )
            bufs[f"code__{t}"][fill] = code
        for cfg in shard_configs:
            imap = index_maps[cfg.shard_id]
            s = 0
            ix_row = bufs[f"ix__{cfg.shard_id}"][fill]
            v_row = bufs[f"v__{cfg.shard_id}"][fill]
            ix_row[:] = 0
            v_row[:] = 0.0
            for bag in cfg.feature_bags:
                for f in r.get(bag) or []:
                    j = imap.get_index(feature_key(f["name"], f["term"]))
                    if j >= 0:
                        ix_row[s] = j
                        v_row[s] = float(f["value"])
                        s += 1
            ic = icepts[cfg.shard_id]
            if ic >= 0:
                ix_row[s] = ic
                v_row[s] = 1.0
        if res is not None and w > 0:
            # sequential algorithm R over real rows
            seen_real += 1
            if seen_real <= K:
                slot = seen_real - 1
            else:
                slot = int(rng.integers(0, seen_real))
                slot = slot if slot < K else -1
            if slot >= 0:
                res["lab"][slot] = bufs["lab"][fill]
                res["off"][slot] = bufs["off"][fill]
                res["wgt"][slot] = w
                for sid in stats.shard_nnz:
                    res[f"ix__{sid}"][slot] = bufs[f"ix__{sid}"][fill]
                    res[f"v__{sid}"][slot] = bufs[f"v__{sid}"][fill]
        fill += 1
        if fill == R:
            store.append(bufs, real_rows=R)
            bufs = new_bufs()
            fill = 0
    if fill:
        store.append(bufs, real_rows=fill)
    store.mark_staged()
    store.finalize()
    if res is not None:
        k_eff = min(seen_real, K)
        res = {k: a[:k_eff] for k, a in res.items()}
    return store, res


# ---------------------------------------------------------------------------
# spilled random-effect grouping
# ---------------------------------------------------------------------------


@dataclass
class _REBucketSegment:
    """One disk-backed slice of a capacity class: at most
    ``max_entities`` consecutive entity codes sharing sample capacity S."""

    entity_codes: np.ndarray  # int32 [E_seg], ascending
    capacity: int
    dir: str

    def arrays(self, k: int, mode: str = "r+") -> Dict[str, np.ndarray]:
        E, S = len(self.entity_codes), self.capacity
        shapes = {
            "rows": ((E, S), np.int32),
            "ix": ((E, S, k), np.int32),
            "v": ((E, S, k), np.float32),
            "lab": ((E, S), np.float32),
            "off": ((E, S), np.float32),
            "wgt": ((E, S), np.float32),
        }
        return {
            f: np.memmap(
                os.path.join(self.dir, f + ".bin"), dt, mode, shape=shp
            )
            for f, (shp, dt) in shapes.items()
        }


class SpilledREBuckets:
    """Per-entity grouped active data for ONE random-effect coordinate,
    spilled to disk in budget-bounded segments.

    The in-memory builder's groupByKey (stable sort + flat scatter,
    random_effect_data.py) becomes a direct scatter into disk-backed
    [E_seg, S, k] blocks: entity counts are known from the scan pass, so
    each entity's (segment, slot) is precomputed and one pass over the
    staged chunks writes every sample into place — no sort, no resident
    [n, k] table. Entity order inside a capacity class is ascending code,
    identical to the in-memory buckets, and rows fill in ascending global
    row id (chunks stream in order), identical to the stable sort.

    ``segment_budget_bytes`` caps the bytes any one segment materializes
    when solved (the in-memory path's single [E_b, S, k] class block can
    exceed host RAM at out-of-core scale); a segment always holds at
    least one entity.

    Crash-safe resume: on a PERSISTENT store, a completed fill pass is
    recorded in the store manifest (keyed by re_type + shard). A
    restarted run whose manifest carries the flag reopens the segment
    files as-is and skips the fill; an INTERRUPTED fill restarts from
    scratch — the scatter is idempotent (every (segment, slot, rank)
    write lands the same value), so re-running it converges without
    restaging anything.
    """

    def __init__(
        self,
        store: GameChunkStore,
        re_type: str,
        shard_id: str,
        counts: np.ndarray,
        *,
        segment_budget_bytes: int = 1 << 30,
    ):
        self.store = store
        self.re_type = re_type
        self.shard_id = shard_id
        self.k = store.shard_nnz[shard_id]
        self.num_entities = len(counts)
        E = self.num_entities
        caps = np.zeros(E, np.int64)
        nz = counts > 0
        caps[nz] = 1 << np.ceil(
            np.log2(np.maximum(counts[nz], 1))
        ).astype(np.int64)
        self.num_active_rows = int(counts.sum())
        fill_tag = f"re__{re_type}__{shard_id}"
        resume = store.fill_done(fill_tag)
        seg_of = np.full(E, -1, np.int64)
        slot_of = np.zeros(E, np.int64)
        self.segments: List[_REBucketSegment] = []
        per_entity = lambda S: S * (self.k * 8 + 16)  # noqa: E731
        for S in sorted(set(caps[nz].tolist())):
            members = np.nonzero(caps == S)[0]
            max_e = max(1, int(segment_budget_bytes // per_entity(int(S))))
            for lo in range(0, len(members), max_e):
                seg_members = members[lo:lo + max_e]
                seg_dir = os.path.join(
                    store.dir,
                    f"re__{re_type}__{shard_id}__seg{len(self.segments)}",
                )
                os.makedirs(seg_dir, exist_ok=True)
                seg = _REBucketSegment(
                    entity_codes=seg_members.astype(np.int32),
                    capacity=int(S),
                    dir=seg_dir,
                )
                if not resume:
                    arrs = seg.arrays(self.k, mode="w+")
                    arrs["rows"][:] = -1  # memmaps zero; rows pad -1
                    for a in arrs.values():
                        a.flush()
                seg_of[seg_members] = len(self.segments)
                slot_of[seg_members] = np.arange(len(seg_members))
                self.segments.append(seg)
        self._seg_of, self._slot_of = seg_of, slot_of
        if not resume:
            self._fill_pass()
            store.mark_fill_done(fill_tag)

    def _fill_pass(self) -> None:
        """Scatter every valid staged row into its entity's (segment,
        slot, rank) — one chunk resident at a time, writes through the
        segment memmaps."""
        st = self.store
        fill = np.zeros(self.num_entities, np.int64)
        handles = [seg.arrays(self.k) for seg in self.segments]
        for ci in range(st.count):
            c = st.chunk(ci)
            codes = c[f"code__{self.re_type}"]
            valid = (codes >= 0) & (c["wgt"] > 0)
            rows = np.nonzero(valid)[0]
            if not len(rows):
                continue
            e = codes[rows].astype(np.int64)
            # within-chunk occurrence rank per entity (rows ascend, so
            # fill order == ascending global row id)
            order = np.argsort(e, kind="stable")
            e_s = e[order]
            first = np.searchsorted(e_s, e_s, side="left")
            occ = np.empty(len(rows), np.int64)
            occ[order] = np.arange(len(rows)) - first
            rank = fill[e] + occ
            np.add.at(fill, e, 1)
            gids = (ci * st.R + rows).astype(np.int32)
            ix = c[f"ix__{self.shard_id}"][rows]
            v = c[f"v__{self.shard_id}"][rows]

            def _scatter():
                # spill_write seam; the slot assignments are idempotent,
                # so a retried attempt rewrites the same values in place
                for si in np.unique(self._seg_of[e]):
                    m = self._seg_of[e] == si
                    sl = self._slot_of[e[m]]
                    rk = rank[m]
                    h = handles[si]
                    h["rows"][sl, rk] = gids[m]
                    h["ix"][sl, rk] = ix[m]
                    h["v"][sl, rk] = v[m]
                    h["lab"][sl, rk] = c["lab"][rows[m]]
                    h["off"][sl, rk] = c["off"][rows[m]]
                    h["wgt"][sl, rk] = c["wgt"][rows[m]]

            from photon_ml_tpu.reliability.retry import io_call

            io_call(
                "spill_write", _scatter,
                detail=f"re__{self.re_type}__{self.shard_id} fill[{ci}]",
            )
        for h in handles:
            for a in h.values():
                a.flush()

    def iter_segments(self):
        """Yield (entity_codes, arrays) with arrays MATERIALIZED (one
        segment resident at a time), behind the spill_read seam."""
        from photon_ml_tpu.reliability.retry import io_call

        for seg in self.segments:
            arrs = io_call(
                "spill_read",
                lambda seg=seg: {
                    f: np.array(a) for f, a in seg.arrays(self.k).items()
                },
                detail=seg.dir,
            )
            yield seg.entity_codes, arrs


# ---------------------------------------------------------------------------
# streaming coordinates
# ---------------------------------------------------------------------------


# -- shared chunk scoring / loss programs ------------------------------------
#
# Module-level jits shared by every streamed coordinate / CD instance
# (no per-instance jit(lambda): one persistent compile cache per shape).

_CHUNK_JITS = {}


def _chunk_jit(which: str):
    global _CHUNK_JITS
    if which in _CHUNK_JITS:
        return _CHUNK_JITS[which]
    import jax
    import jax.numpy as jnp

    if which == "score_rows":

        @jax.jit
        def fn(w, ix, v):
            return (v * w[ix]).sum(axis=-1)
    elif which == "score_bank":

        @jax.jit
        def fn(bank, codes, ix, v, valid):
            return jnp.where(
                valid,
                (
                    v
                    * jnp.take_along_axis(
                        jnp.take(bank, jnp.maximum(codes, 0), axis=0),
                        ix, axis=1,
                    )
                ).sum(axis=-1),
                0.0,
            )
    else:  # weighted pointwise chunk loss, loss kernel static

        def _chunk_loss(loss, z, lab, w):
            return (w * loss.value(z, lab)).sum()

        fn = jax.jit(_chunk_loss, static_argnums=(0,))
    _CHUNK_JITS[which] = fn
    return fn


class _StoreChunkObjective:
    """GLM objective over one shard's staged chunks, residual folded into
    offsets per chunk — the StreamingGLMObjective contract with the
    GameChunkStore as the chunk source (the FE coordinate's residual is
    dataSet.addScoresToOffsets, applied chunk-wise from disk)."""

    def __init__(self, store: GameChunkStore, shard_id: str, dim: int, loss):
        from photon_ml_tpu.ops.normalization import identity_context
        from photon_ml_tpu.ops.objective import GLMObjective

        self.store = store
        self.shard_id = shard_id
        self.dim = dim
        # chunk partials run the SHARED module-level jits (the objective
        # is a pytree argument — one persistent compile cache across
        # every streamed coordinate instead of per-instance jit(lambda)s)
        self._objective = GLMObjective(loss, dim, identity_context())
        self.residual: Optional[ScoreStore] = None

    def _batches(self):
        import jax.numpy as jnp

        from photon_ml_tpu.data.batch import SparseBatch

        st = self.store
        for i in range(st.count):
            c = st.chunk(i)
            off = c["off"]
            if self.residual is not None:
                off = off + self.residual.get_chunk(i)
            yield SparseBatch(
                indices=jnp.asarray(c[f"ix__{self.shard_id}"]),
                values=jnp.asarray(c[f"v__{self.shard_id}"]),
                labels=jnp.asarray(c["lab"]),
                offsets=jnp.asarray(off),
                weights=jnp.asarray(c["wgt"]),
            )

    def value_and_gradient(self, w, l2_weight=0.0):
        import jax.numpy as jnp

        value = jnp.float32(0.0)
        grad = jnp.zeros((self.dim,), jnp.float32)
        from photon_ml_tpu.ops.objective import partial_value_and_gradient

        for b in self._batches():
            v, g = partial_value_and_gradient(self._objective, w, b)
            value = value + v
            grad = grad + g
        value = value + 0.5 * l2_weight * jnp.vdot(w, w)
        return value, grad + l2_weight * w

    def hessian_vector(self, w, direction, l2_weight=0.0):
        import jax.numpy as jnp

        hv = jnp.zeros((self.dim,), jnp.float32)
        from photon_ml_tpu.ops.objective import partial_hessian_vector

        for b in self._batches():
            hv = hv + partial_hessian_vector(self._objective, w, direction, b)
        return hv + l2_weight * direction

    def hessian_diagonal(self, w, l2_weight=0.0):
        import jax.numpy as jnp

        diag = jnp.zeros((self.dim,), jnp.float32)
        from photon_ml_tpu.ops.objective import partial_hessian_diagonal

        for b in self._batches():
            diag = diag + partial_hessian_diagonal(self._objective, w, b)
        return diag + l2_weight


@dataclass
class StreamingFixedEffectCoordinate:
    """FixedEffectCoordinate with a streamed chunk objective: the global
    GLM solve walks the host-driven optimizers (one disk pass per
    evaluation over the staged chunks), matching the in-memory in-jit
    iterate sequence."""

    name: str
    store: GameChunkStore
    problem: object  # GLMOptimizationProblem
    feature_shard_id: str
    reg_weight: float = 0.0

    def __post_init__(self):
        self._chunk_obj = _StoreChunkObjective(
            self.store, self.feature_shard_id,
            self.problem.objective.dim, self.problem.objective.loss,
        )

    @property
    def dim(self) -> int:
        return self.problem.objective.dim

    def initialize_coefficients(self):
        import jax.numpy as jnp

        return jnp.zeros((self.dim,), jnp.float32)

    def update(self, means, residual: Optional[ScoreStore]):
        import jax.numpy as jnp

        from photon_ml_tpu.optim.config import OptimizerType
        from photon_ml_tpu.optim.host_lbfgs import (
            minimize_lbfgs_host,
            minimize_owlqn_host,
        )
        from photon_ml_tpu.optim.host_tron import minimize_tron_host

        obj = self._chunk_obj
        obj.residual = residual
        p = self.problem
        l1, l2 = p.regularization.split(self.reg_weight)
        w0 = (
            jnp.asarray(means)
            if means is not None
            else self.initialize_coefficients()
        )
        cfg = p.config
        try:
            if cfg.optimizer_type == OptimizerType.TRON:
                result = minimize_tron_host(
                    lambda w: obj.value_and_gradient(w, l2),
                    lambda w, d: obj.hessian_vector(w, d, l2),
                    w0, max_iter=cfg.max_iter, tol=cfg.tolerance,
                    max_cg=cfg.tron_max_cg, box=p.box,
                )
            elif l1:
                l1_mask = p._l1_mask()
                result = minimize_owlqn_host(
                    lambda w: obj.value_and_gradient(w, l2),
                    w0, l1, max_iter=cfg.max_iter, tol=cfg.tolerance,
                    history=cfg.lbfgs_history, l1_mask=l1_mask, box=p.box,
                )
            else:
                result = minimize_lbfgs_host(
                    lambda w: obj.value_and_gradient(w, l2),
                    w0, max_iter=cfg.max_iter, tol=cfg.tolerance,
                    history=cfg.lbfgs_history, box=p.box,
                )
            variances = None
            if p.compute_variances:
                from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

                hd = obj.hessian_diagonal(result.coefficients, l2)
                variances = 1.0 / (hd + _VARIANCE_EPSILON)
        finally:
            obj.residual = None
        return result.coefficients, variances, result

    def score_chunk(self, means, chunk: Dict[str, np.ndarray]):
        import jax.numpy as jnp

        return _chunk_jit("score_rows")(
            jnp.asarray(means),
            jnp.asarray(chunk[f"ix__{self.feature_shard_id}"]),
            jnp.asarray(chunk[f"v__{self.feature_shard_id}"]),
        )

    def regularization_term(self, means) -> float:
        import jax.numpy as jnp

        from photon_ml_tpu.parallel import overlap

        l1, l2 = self.problem.regularization.split(self.reg_weight)
        term = 0.5 * l2 * jnp.vdot(means, means)
        if l1:
            term = term + l1 * jnp.sum(jnp.abs(means))
        # ONE counted fetch for the whole term, not one float() per part
        return float(overlap.device_get(term))


@dataclass
class StreamingRandomEffectCoordinate:
    """RandomEffectCoordinate whose per-entity grouping lives on disk:
    each update streams the bucket segments through the EXISTING fused
    bucket solvers (RandomEffectOptimizationProblem.update_bank, one
    single-bucket dataset per segment) instead of holding a resident
    bank of [E_b, S, k] blocks; the residual folds into each segment's
    offsets via a global-row-id gather against the on-disk score store.

    ``mesh`` (pod-scale GAME, game/pod.py): with an entity mesh the
    bank lives SHARDED over the mesh by entity hash and each segment's
    solve is a cross-replica sharded step — every device stages and
    solves only its own shard of the segment, so streaming composes
    with entity sharding: disk bounds the host, the hash bounds each
    device."""

    name: str
    store: GameChunkStore
    spilled: SpilledREBuckets
    problem: object  # RandomEffectOptimizationProblem
    config: RandomEffectDataConfiguration
    local_dim: int = 0  # IDENTITY projector: the shard dimension
    mesh: object = None  # optional 1-D entity mesh (sharded banks)

    def __post_init__(self):
        self._pod = None
        if self.mesh is not None:
            if self.problem.compute_variances:
                raise ValueError(
                    "streaming entity-sharded training does not support "
                    "compute_variances yet; drop --entity-shards or the "
                    "variance flag"
                )
            from photon_ml_tpu.game.pod import PodRandomEffectProblem

            self._pod = PodRandomEffectProblem(self.problem, self.mesh)

    @property
    def num_entities(self) -> int:
        return self.spilled.num_entities

    def initialize_bank(self):
        import jax.numpy as jnp

        if self._pod is not None:
            from photon_ml_tpu.game.pod import EntityShardSpec, ShardedREBank

            return ShardedREBank.zeros(
                self.mesh,
                EntityShardSpec(self._pod.num_shards, self.num_entities),
                self.local_dim,
            )
        return jnp.zeros(
            (self.num_entities, self.local_dim), jnp.float32
        )

    def _mini_dataset(self, codes: np.ndarray, arrays, offsets):
        from photon_ml_tpu.game.random_effect_data import (
            RandomEffectBucket,
            RandomEffectDataset,
        )

        bucket = RandomEffectBucket(
            entity_codes=codes,
            row_index=arrays["rows"],
            indices=arrays["ix"],
            values=arrays["v"],
            labels=arrays["lab"],
            offsets=offsets,
            weights=arrays["wgt"],
        )
        D = self.local_dim
        return RandomEffectDataset(
            config=self.config,
            num_entities=self.num_entities,
            local_dim=D,
            # identity projection as a broadcast VIEW — never materialized
            projection=np.broadcast_to(
                np.arange(D, dtype=np.int32), (self.num_entities, D)
            ),
            row_local_indices=np.zeros((0, 1), np.int32),
            row_local_values=np.zeros((0, 1), np.float32),
            row_entity_codes=np.zeros((0,), np.int32),
            buckets=[bucket],
            num_active_rows=self.spilled.num_active_rows,
            num_passive_rows=0,
        )

    def update(self, bank, residual: Optional[ScoreStore]):
        import jax.numpy as jnp

        res_flat = residual.flat() if residual is not None else None
        if self._pod is not None:
            return self._update_sharded(bank, res_flat)
        tracker = None
        var_bank = None
        if self.problem.compute_variances:
            var_bank = getattr(self, "_var_bank", None)
            if var_bank is None:
                var_bank = jnp.zeros_like(bank)
        for codes, arrays in self.spilled.iter_segments():
            off = arrays["off"]
            if res_flat is not None:
                rows = arrays["rows"]
                off = (off + np.where(
                    rows >= 0, res_flat[np.maximum(rows, 0)], 0.0
                )).astype(np.float32)
            ds = self._mini_dataset(codes, arrays, off)
            if var_bank is not None:
                bank, tracker, seg_vars = self.problem.update_bank(
                    bank, ds, with_variances=True
                )
                var_bank = var_bank.at[codes].set(seg_vars[codes])
            else:
                bank, tracker = self.problem.update_bank(bank, ds)
        if var_bank is not None:
            self._var_bank = var_bank
        return bank, tracker

    def _coerce_sharded(self, bank):
        from photon_ml_tpu.game.pod import EntityShardSpec, ShardedREBank

        if isinstance(bank, ShardedREBank):
            return bank
        # replicated [E, d] (checkpoint restore / warm start): shard it
        return ShardedREBank.from_global(
            self.mesh,
            EntityShardSpec(self._pod.num_shards, self.num_entities),
            bank,
        )

    def _update_sharded(self, bank, res_flat):
        """Pod path: every segment solves as a cross-replica sharded
        step — the residual fold stays a host gather against the
        on-disk score store (the out-of-core contract), the solve and
        the bank never leave their shards."""
        bank = self._coerce_sharded(bank)
        from photon_ml_tpu.game.random_effect_data import RandomEffectBucket

        stat_vecs = []
        for codes, arrays in self.spilled.iter_segments():
            off = arrays["off"]
            if res_flat is not None:
                rows = arrays["rows"]
                off = (off + np.where(
                    rows >= 0, res_flat[np.maximum(rows, 0)], 0.0
                )).astype(np.float32)
            bucket = RandomEffectBucket(
                entity_codes=codes,
                row_index=arrays["rows"],
                indices=arrays["ix"],
                values=arrays["v"],
                labels=arrays["lab"],
                offsets=off,
                weights=arrays["wgt"],
            )
            kind = self.problem._bucket_kind(bucket, self.local_dim)
            bank, stat_vec = self._pod.update_segment(
                bank, codes, arrays, off, kind=kind
            )
            stat_vecs.append(stat_vec)
        tracker = (
            self._pod.segment_tracker(stat_vecs, self.num_entities)
            if stat_vecs
            else None
        )
        return bank, tracker

    @property
    def variances(self):
        return getattr(self, "_var_bank", None)

    def score_chunk(self, bank, chunk: Dict[str, np.ndarray]):
        import jax.numpy as jnp

        codes = chunk[f"code__{self.config.random_effect_type}"]
        valid = (codes >= 0) & (chunk["wgt"] > 0)
        sid = self.config.feature_shard_id
        from photon_ml_tpu.game.pod import ShardedREBank

        if isinstance(bank, ShardedREBank):
            return self._pod.score_chunk(
                bank, codes,
                chunk[f"ix__{sid}"], chunk[f"v__{sid}"], valid,
            )
        return _chunk_jit("score_bank")(
            bank,
            jnp.asarray(codes),
            jnp.asarray(chunk[f"ix__{sid}"]),
            jnp.asarray(chunk[f"v__{sid}"]),
            jnp.asarray(valid),
        )

    def regularization_term(self, bank) -> float:
        from photon_ml_tpu.game.pod import ShardedREBank

        if isinstance(bank, ShardedREBank):
            return self._pod.regularization_term(bank)
        return self.problem.regularization_term(bank)


# ---------------------------------------------------------------------------
# streamed coordinate descent
# ---------------------------------------------------------------------------


@dataclass
class StreamingGameResult:
    models: Dict[str, object]  # name -> coefficients / bank (+ meta below)
    game_model: object  # GameModel (FixedEffectModel / RandomEffectModel)
    objective_history: List[float]
    validation_history: List[Dict[str, float]] = field(default_factory=list)
    best_metric: Optional[float] = None
    trackers: Dict[str, List[object]] = field(default_factory=dict)
    # True when the run stopped early on a preemption signal; the last
    # completed iteration is checkpointed, so a restarted job resumes.
    preempted: bool = False


class StreamingCoordinateDescent:
    """Block coordinate descent over streaming coordinates: the
    CoordinateDescent.run loop (residual = total - own, update, rescore,
    objective) with every [n]-sized quantity living on disk per chunk.

    The residual algebra runs chunk-wise: one scratch ScoreStore holds
    ``total - own`` for the coordinate being updated, rebuilt per update
    from the per-coordinate score stores (O(C) chunk passes per
    iteration, same complexity class as the in-memory incremental
    patching — disk-sequential instead of device-resident)."""

    def __init__(
        self,
        coordinates: Dict[str, object],
        store: GameChunkStore,
        task,
        *,
        update_sequence: Optional[List[str]] = None,
        validation_fn=None,
        validation_metric: Optional[str] = None,
        validation_maximize: bool = True,
        logger: Optional[PhotonLogger] = None,
        checkpointer=None,  # reliability.checkpoint.StreamingCDCheckpointer
        preemption_guard=None,  # utils.preemption.PreemptionGuard
    ):
        self.coordinates = coordinates
        self.store = store
        self.task = task
        self.update_sequence = update_sequence or list(coordinates)
        unknown = set(self.update_sequence) - set(coordinates)
        if unknown:
            raise ValueError(
                f"update sequence references unknown coordinates {unknown}"
            )
        self.validation_fn = validation_fn
        self.validation_metric = validation_metric
        self.validation_maximize = validation_maximize
        self.logger = logger or PhotonLogger()
        self.checkpointer = checkpointer
        self.preemption_guard = preemption_guard
        from photon_ml_tpu.ops.losses import loss_for_task

        self._loss = loss_for_task(task)

    def _state(self, name):
        coord = self.coordinates[name]
        if isinstance(coord, StreamingFixedEffectCoordinate):
            return coord.initialize_coefficients()
        return coord.initialize_bank()

    def _preemption_agreed(self) -> bool:
        """Streaming CD is single-process (validated up front), so the
        cooperative stop is just the local guard's flag — the name
        mirrors CoordinateDescent._preemption_agreed, which adds the
        cross-process allgather the multi-host path needs."""
        return (
            self.preemption_guard is not None
            and self.preemption_guard.requested
        )

    def run(self, num_iterations: int) -> StreamingGameResult:
        import jax.numpy as jnp

        seq = self.update_sequence
        states = {name: self._state(name) for name in seq}
        variances: Dict[str, object] = {name: None for name in seq}
        scores = {name: self.store.score_store(name) for name in seq}
        residual = (
            self.store.score_store("__residual__") if len(seq) > 1 else None
        )
        objective_history: List[float] = []
        validation_history: List[Dict[str, float]] = []
        trackers: Dict[str, List[object]] = {name: [] for name in seq}
        best_metric = None
        preempted = False
        start_iteration = 0
        if self.checkpointer is not None:
            latest = self.checkpointer.latest_step()
            if latest is not None:
                st, var, hist = self.checkpointer.load(latest)
                for name in seq:
                    states[name] = jnp.asarray(st[name])
                    v = var.get(name)
                    variances[name] = (
                        jnp.asarray(v) if v is not None else None
                    )
                    coord = self.coordinates[name]
                    if isinstance(coord, StreamingRandomEffectCoordinate):
                        # the RE variance bank accumulates across segment
                        # updates — reseed it so later iterations patch
                        # the restored values instead of zeros
                        if variances[name] is not None:
                            coord._var_bank = variances[name]
                objective_history = list(hist.get("objective") or [])
                validation_history = list(hist.get("validation") or [])
                best_metric = hist.get("best_metric")
                start_iteration = latest
                # rebuild every coordinate's score store from the
                # restored states: score_chunk is deterministic, so the
                # rebuilt scores are bitwise what the interrupted run
                # held after this iteration
                for name in seq:
                    coord = self.coordinates[name]
                    for i in range(self.store.count):
                        scores[name].set_chunk(
                            i,
                            coord.score_chunk(
                                states[name], self.store.chunk(i)
                            ),
                        )
                self.logger.info(
                    "resumed streaming coordinate descent from "
                    "checkpoint step %d", latest,
                )
        for it in range(start_iteration, num_iterations):
            # obs/trace.py: one span per out-of-core CD iteration (the
            # in-memory loop has its twin in game/coordinate_descent.py)
            it_span = start_span(
                "cd.iteration", iteration=it + 1, streaming=True
            )
            for name in seq:
                coord = self.coordinates[name]
                if residual is not None:
                    for i in range(self.store.count):
                        acc = np.zeros(self.store.R, np.float32)
                        for other in seq:
                            if other != name:
                                acc += scores[other].get_chunk(i)
                        residual.set_chunk(i, acc)
                if isinstance(coord, StreamingFixedEffectCoordinate):
                    means, var, tracker = coord.update(
                        states[name], residual
                    )
                    states[name] = means
                    variances[name] = var
                else:
                    states[name], tracker = coord.update(
                        states[name], residual
                    )
                    variances[name] = coord.variances
                trackers[name].append(tracker)
                for i in range(self.store.count):
                    scores[name].set_chunk(
                        i, coord.score_chunk(states[name], self.store.chunk(i))
                    )
            objective = 0.0
            for i in range(self.store.count):
                c = self.store.chunk(i)
                z = c["off"].astype(np.float64)
                for name in seq:
                    z = z + np.asarray(scores[name].get_chunk(i), np.float64)
                objective += float(
                    _chunk_jit("loss")(
                        self._loss,
                        jnp.asarray(z, jnp.float32),
                        jnp.asarray(c["lab"]),
                        jnp.asarray(c["wgt"]),
                    )
                )
            for name in seq:
                objective += self.coordinates[name].regularization_term(
                    states[name]
                )
            it_span.end(objective=objective)
            objective_history.append(objective)
            self.logger.info(
                "streaming coordinate descent iter %d: objective=%g",
                it + 1, objective,
            )
            if self.validation_fn is not None:
                metrics = self.validation_fn(self.coordinates, states)
                validation_history.append(metrics)
                self.logger.info("iter %d validation: %s", it + 1, metrics)
                if self.validation_metric is not None:
                    m = metrics[self.validation_metric]
                    if (
                        best_metric is None
                        or (self.validation_maximize and m > best_metric)
                        or (not self.validation_maximize and m < best_metric)
                    ):
                        best_metric = m
            if self.checkpointer is not None:
                # iteration it+1 is a complete resume point: states (+
                # variances) are everything iteration it+2 depends on —
                # scores/residuals recompute deterministically from them
                self.checkpointer.save(
                    it + 1,
                    {name: np.asarray(states[name]) for name in seq},
                    {
                        name: (
                            np.asarray(variances[name])
                            if variances[name] is not None
                            else None
                        )
                        for name in seq
                    },
                    {
                        "objective": objective_history,
                        "validation": validation_history,
                        "best_metric": best_metric,
                    },
                )
            if self._preemption_agreed():
                preempted = True
                self.logger.warning(
                    "preemption requested: stopping after iteration %d/%d",
                    it + 1, num_iterations,
                )
                break
        game_model = self._export_model(states, variances)
        return StreamingGameResult(
            models=dict(states),
            game_model=game_model,
            objective_history=objective_history,
            validation_history=validation_history,
            best_metric=best_metric,
            trackers=trackers,
            preempted=preempted,
        )

    @staticmethod
    def score_states_chunk(coordinates, states, chunk) -> np.ndarray:
        """Total model score of one (train or validation) chunk."""
        total = np.zeros(len(chunk["lab"]), np.float32)
        for name, coord in coordinates.items():
            total = total + np.asarray(
                coord.score_chunk(states[name], chunk), np.float32
            )
        return total

    # photon: sharding(export)
    def _export_model(self, states, variances):
        """States -> a GameModel of the standard model classes, so
        save_game_model and the scoring driver work unchanged on a
        streamed fit."""
        from photon_ml_tpu.game.model import (
            FixedEffectModel,
            GameModel,
            RandomEffectModel,
        )
        from photon_ml_tpu.models.coefficients import Coefficients

        from photon_ml_tpu.game.pod import ShardedREBank

        models = {}
        for name, coord in self.coordinates.items():
            if isinstance(coord, StreamingFixedEffectCoordinate):
                models[name] = FixedEffectModel(
                    coord.problem.create_model(
                        Coefficients(states[name], variances.get(name))
                    ),
                    coord.feature_shard_id,
                )
            else:
                state = states[name]
                if isinstance(state, ShardedREBank):
                    # export materializes the replicated view once — the
                    # model artifact is host-side by definition
                    state = state.to_global()
                models[name] = RandomEffectModel(
                    state,
                    coord._mini_dataset(
                        np.zeros(0, np.int32),
                        {
                            "rows": np.full((0, 1), -1, np.int32),
                            "ix": np.zeros((0, 1, 1), np.int32),
                            "v": np.zeros((0, 1, 1), np.float32),
                            "lab": np.zeros((0, 1), np.float32),
                            "wgt": np.zeros((0, 1), np.float32),
                        },
                        np.zeros((0, 1), np.float32),
                    ),
                    coord.config.random_effect_type,
                    coord.config.feature_shard_id,
                    variances=variances.get(name),
                )
        return GameModel(models, self.task)


# ---------------------------------------------------------------------------
# end-to-end streamed GAME training
# ---------------------------------------------------------------------------


def validate_streaming_game_configs(
    re_data_configs: Mapping[str, RandomEffectDataConfiguration],
) -> None:
    """The streaming scope gates, raised with actionable messages (the
    driver calls this at parse/validate time, tests directly)."""
    import jax

    if jax.process_count() > 1:
        raise ValueError("streaming GAME training is single-process")
    for name, cfg in re_data_configs.items():
        if cfg.projector_type != ProjectorType.IDENTITY:
            raise ValueError(
                "streaming GAME training supports the IDENTITY projector "
                f"only (coordinate {name!r} uses {cfg.projector_type}); "
                "INDEX_MAP/RANDOM projections need a per-entity re-index "
                "pass over the grouped data"
            )
        if cfg.active_data_upper_bound is not None:
            raise ValueError(
                "streaming GAME training does not support "
                f"active-data-upper-bound (coordinate {name!r}): the "
                "reservoir cap's without-replacement draw needs a second "
                "grouped pass"
            )


def train_streaming_game(
    paths,
    shard_configs: Sequence[FeatureShardConfiguration],
    fe_data_configs: Mapping[str, FixedEffectDataConfiguration],
    re_data_configs: Mapping[str, RandomEffectDataConfiguration],
    opt_combo: Mapping[str, object],  # name -> GLMOptimizationConfiguration
    task,
    *,
    num_iterations: int = 1,
    update_sequence: Optional[List[str]] = None,
    memory_budget_bytes: int = 0,
    spill_dir: Optional[str] = None,
    index_maps: Optional[Mapping[str, IndexMap]] = None,
    validate_paths=None,
    evaluator_types=None,
    compute_variance: bool = False,
    diagnostic_reservoir_rows: int = 0,
    diagnostic_reservoir_bytes: int = 256 << 20,
    logger: Optional[PhotonLogger] = None,
    checkpoint_dir: Optional[str] = None,
    preemption_guard=None,
    entity_mesh=None,
):
    """End-to-end streamed GAME fit: scan -> stage -> streamed CD
    [-> streamed validation]. Returns (StreamingGameResult, extras) where
    extras carries the index maps / entity indexes / stats / stores the
    driver needs for model output and metrics.

    ``memory_budget_bytes`` (--stream-memory-budget) fixes BOTH the
    staged-chunk row count and the random-effect segment byte cap; 0
    keeps the default 65536-row chunks with 1 GiB segments.

    ``checkpoint_dir``: crash-safe resume for the WHOLE pipeline — the
    staged chunk stores persist there with progress manifests (an
    interrupted stage pass resumes from completed chunks, an interrupted
    RE fill pass re-scatters from the staged chunks without restaging),
    and the CD loop snapshots every iteration
    (reliability.StreamingCDCheckpointer). A restarted run with the same
    args produces a bitwise-identical final model. ``preemption_guard``
    stops at the next iteration boundary on SIGTERM, mirroring the
    in-memory CoordinateDescent.

    ``entity_mesh`` (pod-scale GAME): a 1-D ``entity`` mesh shards
    every random-effect bank — and each staged segment's solve — over
    the mesh by entity hash (game/pod.py), composing out-of-core
    streaming with entity sharding.
    """
    logger = logger or PhotonLogger()
    validate_streaming_game_configs(re_data_configs)
    stage_train_dir = stage_validate_dir = cd_dir = None
    if checkpoint_dir is not None:
        from photon_ml_tpu.reliability.manifest import ensure_run_manifest

        ensure_run_manifest(
            os.path.abspath(checkpoint_dir),
            {
                "paths": [str(p) for p in paths],
                "shards": [repr(s) for s in shard_configs],
                "fe": {k: repr(v) for k, v in sorted(fe_data_configs.items())},
                "re": {k: repr(v) for k, v in sorted(re_data_configs.items())},
                "combo": {
                    k: getattr(v, "render", lambda: repr(v))()
                    for k, v in sorted(opt_combo.items())
                },
                "task": getattr(task, "name", str(task)),
                "num_iterations": int(num_iterations),
                "update_sequence": list(update_sequence or []),
                "memory_budget_bytes": int(memory_budget_bytes),
                "validate_paths": [str(p) for p in (validate_paths or [])],
            },
            kind="game-streaming-run",
        )
        stage_train_dir = os.path.join(checkpoint_dir, "stage-train")
        if validate_paths:
            stage_validate_dir = os.path.join(checkpoint_dir, "stage-validate")
        cd_dir = os.path.join(checkpoint_dir, "cd")
    re_types = sorted(
        {c.random_effect_type for c in re_data_configs.values()}
    )
    imaps, entity_indexes, stats = scan_game_stream(
        paths, shard_configs, re_types, index_maps=index_maps
    )
    row_bytes = game_row_bytes(stats.shard_nnz, len(re_types))
    rows_per_chunk = stream_budget_rows(
        memory_budget_bytes, row_bytes, default_rows=65536
    )
    rows_per_chunk = int(min(rows_per_chunk, max(stats.num_rows, 8)))
    seg_budget = memory_budget_bytes if memory_budget_bytes > 0 else (1 << 30)
    logger.info(
        "streaming GAME: %d rows, %d B/row -> %d rows/chunk, "
        "%d B RE-segment budget",
        stats.num_rows, row_bytes, rows_per_chunk, seg_budget,
    )
    reservoir_rows = 0
    if diagnostic_reservoir_rows > 0:
        from photon_ml_tpu.io.streaming import budgeted_rows

        # the GLM driver's byte-budgeted reservoir, with the (multi-shard
        # wide) staged GAME row as the unit
        reservoir_rows = budgeted_rows(
            diagnostic_reservoir_rows, diagnostic_reservoir_bytes, row_bytes
        )
        if reservoir_rows < diagnostic_reservoir_rows:
            logger.info(
                "GAME diagnostics reservoir scaled to %d rows "
                "(%d B budget at %d B/row)",
                reservoir_rows, diagnostic_reservoir_bytes, row_bytes,
            )
    store, sample = stage_game_stream(
        paths, shard_configs, re_types, imaps, entity_indexes, stats,
        rows_per_chunk=rows_per_chunk, spill_dir=spill_dir,
        reservoir_rows=reservoir_rows, persist_dir=stage_train_dir,
    )
    from photon_ml_tpu.game.random_effect import (
        RandomEffectOptimizationProblem,
    )
    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.optim.problem import create_glm_problem
    from photon_ml_tpu.utils.index_map import intercept_key as _ik

    loss = loss_for_task(task)
    coordinates: Dict[str, object] = {}
    for name, dcfg in fe_data_configs.items():
        ocfg = opt_combo[name]
        imap = imaps[dcfg.feature_shard_id]
        icept = imap.get_index(_ik())
        coordinates[name] = StreamingFixedEffectCoordinate(
            name=name,
            store=store,
            problem=create_glm_problem(
                task, imap.size,
                config=ocfg.optimizer_config,
                regularization=ocfg.regularization,
                compute_variances=compute_variance,
                intercept_index=icept if icept >= 0 else None,
            ),
            feature_shard_id=dcfg.feature_shard_id,
            reg_weight=ocfg.reg_weight,
        )
    for name, dcfg in re_data_configs.items():
        ocfg = opt_combo[name]
        spilled = SpilledREBuckets(
            store, dcfg.random_effect_type, dcfg.feature_shard_id,
            stats.entity_counts[dcfg.random_effect_type],
            segment_budget_bytes=seg_budget,
        )
        coordinates[name] = StreamingRandomEffectCoordinate(
            name=name,
            store=store,
            spilled=spilled,
            problem=RandomEffectOptimizationProblem(
                loss,
                ocfg.optimizer_config,
                ocfg.regularization,
                reg_weight=ocfg.reg_weight,
                compute_variances=compute_variance,
            ),
            config=dcfg,
            local_dim=imaps[dcfg.feature_shard_id].size,
            mesh=entity_mesh,
        )

    validation_fn = None
    metric_name = None
    vstore = None
    maximize = True
    if validate_paths:
        vstore, _ = stage_game_stream(
            validate_paths, shard_configs, re_types, imaps, entity_indexes,
            stats, rows_per_chunk=rows_per_chunk, spill_dir=spill_dir,
            strict_ids=False, persist_dir=stage_validate_dir,
        )
        from photon_ml_tpu.evaluation import EvaluatorType
        from photon_ml_tpu.evaluation.streaming import (
            StreamingAUC,
            StreamingMeanLoss,
            StreamingRMSE,
        )
        from photon_ml_tpu.task import TaskType

        evaluators = evaluator_types or [
            EvaluatorType.parse(
                "AUC" if task == TaskType.LOGISTIC_REGRESSION else "RMSE"
            )
        ]
        for et in evaluators:
            if et.is_sharded:
                raise ValueError(
                    f"streamed GAME validation does not support the "
                    f"sharded evaluator {et.render()} (per-group metrics "
                    "need a grouped pass over the validation stream)"
                )
        metric_name = evaluators[0].render()
        maximize = evaluators[0].maximize
        _LOSS_BY_NAME = {
            "LOGISTIC_LOSS": "logistic", "SQUARED_LOSS": "squared",
            "POISSON_LOSS": "poisson", "SMOOTHED_HINGE_LOSS": "hinge",
        }

        def validation_fn(coords, states):
            accs = {}
            for et in evaluators:
                key = et.render()
                if et.name == "AUC":
                    accs[key] = ("margin", StreamingAUC())
                elif et.name == "RMSE":
                    accs[key] = ("mean", StreamingRMSE())
                else:
                    from photon_ml_tpu.evaluation.evaluator import (
                        _LOSS_BY_NAME as _LOSSES,
                    )

                    accs[key] = ("margin", StreamingMeanLoss(_LOSSES[et.name]))
            import jax.numpy as jnp

            for i in range(vstore.count):
                c = vstore.chunk(i)
                z = (
                    StreamingCoordinateDescent.score_states_chunk(
                        coords, states, c
                    )
                    + c["off"]
                )
                for key, (space, acc) in accs.items():
                    vals = (
                        np.asarray(loss.mean(jnp.asarray(z)))
                        if space == "mean"
                        else z
                    )
                    acc.update(vals, c["lab"], c["wgt"])
            return {key: acc.result() for key, (_, acc) in accs.items()}

    cd_checkpointer = None
    if cd_dir is not None:
        from photon_ml_tpu.reliability.checkpoint import (
            StreamingCDCheckpointer,
        )

        cd_checkpointer = StreamingCDCheckpointer(cd_dir)
    cd = StreamingCoordinateDescent(
        coordinates, store, task,
        update_sequence=update_sequence,
        validation_fn=validation_fn,
        validation_metric=metric_name,
        validation_maximize=maximize,
        logger=logger,
        checkpointer=cd_checkpointer,
        preemption_guard=preemption_guard,
    )
    result = cd.run(num_iterations)
    extras = dict(
        index_maps=imaps,
        entity_indexes=entity_indexes,
        stats=stats,
        store=store,
        validate_store=vstore,
        rows_per_chunk=rows_per_chunk,
        diagnostics_sample=sample,
    )
    return result, extras
