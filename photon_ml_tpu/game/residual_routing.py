"""Per-iteration residual routing over ICI: the consumer of
parallel.shuffle.entity_all_to_all.

Reference: every coordinate-descent sweep re-keys the residual scores from
rows to entity groups with a Spark shuffle
(RandomEffectDataSet.addScoresToOffsets, data/RandomEffectDataSet.scala:
55-74; KeyValueScore joins). Round 2 replaced that per iteration with a
full replicated broadcast of the [n] residual vector + a device-side
gather. Here the re-key is the real ICI collective: rows live sharded
over the mesh's data axis, ONE ``lax.all_to_all`` routes each row's
residual to the device that owns its entity's bucket slot, and a local
scatter lands it at the exact (entity row, sample column) the solver
reads — per-row traffic moves each value once instead of replicating the
whole vector to every device.

All routing metadata (owner device, destination slot, send capacities) is
STATIC per (dataset, mesh): computed host-side once from the bucket
layout and reused every iteration.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu import ownership
from photon_ml_tpu.game.random_effect_data import RandomEffectDataset
from photon_ml_tpu.parallel.shuffle import entity_all_to_all

Array = jnp.ndarray


class ResidualRouter:
    """Routes a row-aligned offsets vector to per-bucket entity slabs.

    The destination layout matches RandomEffectOptimizationProblem's
    entity sharding (``_shard_entity_axis``): bucket ``b``'s entities are
    padded to ``n_dev * E_loc_b`` and split contiguously, so entity
    position ``p`` lives on device ``p // E_loc_b`` at local row
    ``p % E_loc_b``. Each device holds one flat buffer of
    ``sum_b E_loc_b * S_b`` offset slots; bucket ``b``'s slab is the
    contiguous slice starting at ``self.starts[b]``.
    """

    def __init__(self, mesh, dataset: RandomEffectDataset, axis: Optional[str] = None):
        self.mesh = mesh
        self.axis = axis or mesh.axis_names[0]
        n_dev = int(mesh.shape[self.axis])
        self.n_dev = n_dev

        n = dataset.row_entity_codes.shape[0]
        self.num_rows = n
        n_pad = ((n + n_dev - 1) // n_dev) * n_dev
        self.num_rows_padded = n_pad

        dest_dev = np.full(n_pad, -1, np.int32)
        flat_pos = np.zeros(n_pad, np.int32)
        self.starts: List[int] = []
        self.e_locs: List[int] = []
        flat_len = 0
        for b in dataset.buckets:
            e_b, s_b = b.num_entities, b.capacity
            e_loc = -(-e_b // n_dev)
            self.starts.append(flat_len)
            self.e_locs.append(e_loc)
            ent, col = np.nonzero(b.row_index >= 0)
            rows = b.row_index[ent, col]
            dest_dev[rows] = (ent // e_loc).astype(np.int32)
            flat_pos[rows] = (
                flat_len + (ent % e_loc) * s_b + col
            ).astype(np.int32)
            flat_len += e_loc * s_b
        self.flat_len = flat_len

        # exact static send capacity: worst (source shard -> owner) count
        per_src = n_pad // n_dev
        worst = 1
        for s in range(n_dev):
            local = dest_dev[s * per_src:(s + 1) * per_src]
            local = local[local >= 0]
            if local.size:
                worst = max(
                    worst, int(np.bincount(local, minlength=n_dev).max())
                )
        self.cap = ((worst + 7) // 8) * 8

        row_sharding = NamedSharding(mesh, P(self.axis))
        self._dest_dev = jax.device_put(jnp.asarray(dest_dev), row_sharding)
        self._flat_pos = jax.device_put(jnp.asarray(flat_pos), row_sharding)
        self._row_sharding = row_sharding

        flat_len_ = flat_len
        axis_ = self.axis

        # photon: sharding(axes=[data], in=[data,data,data], out=[data])
        @jax.jit
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(axis_), P(axis_), P(axis_)),
            out_specs=P(axis_),
            check_vma=False,
        )
        def _scatter_local(codes, vals, pos):
            valid = codes >= 0
            idx = jnp.where(valid, pos, flat_len_)  # trash slot
            buf = jnp.zeros((flat_len_ + 1,), jnp.float32)
            buf = buf.at[idx].set(
                jnp.where(valid, vals, 0.0), mode="drop"
            )
            return buf[:flat_len_]

        self._scatter_local = _scatter_local

    def route(self, offsets: Array) -> Array:
        """[n] row offsets -> [n_dev * flat_len] per-device slab buffers
        (sharded over the data axis). One all_to_all + one local scatter;
        overflow is impossible (capacities are exact static counts)."""
        off = jnp.asarray(offsets, jnp.float32)
        if off.shape[0] != self.num_rows_padded:
            off = jnp.concatenate([
                off, jnp.zeros((self.num_rows_padded - off.shape[0],), jnp.float32)
            ])
        off = jax.device_put(off, self._row_sharding)
        shuffled = entity_all_to_all(
            self.mesh, self._dest_dev,
            (off, self._flat_pos),
            cap=self.cap, axis=self.axis,
        )
        vals, pos = shuffled.payload
        return self._scatter_local(shuffled.entity_codes, vals, pos)

    def bucket_slab(self, flat: Array, bucket_index: int, capacity: int) -> Array:
        """Slice bucket ``bucket_index``'s offsets slab out of a routed
        buffer -> [n_dev * E_loc, S] (entity-sharded like the solver's
        bucket arrays)."""
        s = self.starts[bucket_index]
        e_loc = self.e_locs[bucket_index]
        per_dev = flat.reshape(self.n_dev, self.flat_len)
        slab = per_dev[:, s:s + e_loc * capacity]
        return slab.reshape(self.n_dev * e_loc, capacity)


class PodResidualRouter:
    """Two-hop residual exchange for HASH-sharded entity banks
    (game/pod.py): rows live row-sharded over the mesh axis, entity
    ``e`` lives on shard ``e % n_dev`` — the LongHashPartitioner analog,
    matching ``parallel.shuffle``'s ownership rule.

    Hop 1 (:meth:`route_in`): ONE ``lax.all_to_all`` carries each row's
    residual to its entity's owner shard, landing in a static per-owner
    SLOT layout. Hop 2 (fused into the pod scoring program): the owner
    scores its slots against its local bank rows and the same
    ``all_to_all`` pattern, reversed, carries the scores back to the
    rows. Per-row traffic per CD iteration is two floats — the residual
    in and the score out — with zero host-side gathers anywhere on the
    path (the regression tests count the ``overlap.device_get`` seam).

    All routing metadata is STATIC per (row entity codes, mesh): the
    send position of every row (``owner * cap + rank``) doubles as its
    return position, because ``all_to_all`` is its own inverse on the
    [n_dev, cap] block layout.
    """

    def __init__(self, mesh, row_entity_codes, *, axis: Optional[str] = None):
        self.mesh = mesh
        self.axis = axis or mesh.axis_names[0]
        n_dev = int(mesh.shape[self.axis])
        self.n_dev = n_dev

        codes = np.asarray(row_entity_codes, np.int64)
        n = codes.shape[0]
        self.num_rows = n
        n_pad = ((n + n_dev - 1) // n_dev) * n_dev
        self.num_rows_padded = n_pad
        per_src = n_pad // n_dev
        owner = np.full(n_pad, -1, np.int64)
        owner[:n] = np.where(
            codes >= 0, ownership.owner_of(codes, n_dev), -1
        )

        # rank of each row among same-owner rows WITHIN its source shard
        # (the row-sharded block it lives in), plus the exact capacity —
        # the worst (source, owner) count, so overflow is impossible
        rank = np.zeros(n_pad, np.int64)
        cap = 1
        for s in range(n_dev):
            blk = owner[s * per_src:(s + 1) * per_src]
            for o in range(n_dev):
                m = blk == o
                c = int(m.sum())
                if c:
                    rank[s * per_src:(s + 1) * per_src][m] = np.arange(c)
                    cap = max(cap, c)
        cap = ((cap + 7) // 8) * 8
        self.cap = cap
        self.num_slots = n_dev * cap  # per-owner received slot count

        # send position == return position: owner * cap + rank; invalid
        # rows point at the trash slot (num_slots)
        send_pos = np.where(
            owner >= 0, owner * cap + rank, self.num_slots
        ).astype(np.int32)
        # owner-side inverse tables (host): which global row landed in
        # slot (src * cap + rank) of owner o — the pod data layer builds
        # its per-slot feature/code arrays from these
        slot_row = np.full((n_dev, self.num_slots), -1, np.int64)
        rows = np.nonzero(owner >= 0)[0]
        src = rows // per_src
        slot_row[owner[rows], src * cap + rank[rows]] = rows
        self.slot_row = slot_row  # [owner, slot] -> global row id, -1 pad
        # source-side slot of each row ON ITS OWNER: src * cap + rank
        self.slot_of_row = np.where(
            owner >= 0,
            (np.arange(n_pad) // per_src) * cap + rank,
            -1,
        ).astype(np.int64)

        row_sharding = NamedSharding(mesh, P(self.axis))
        self._row_sharding = row_sharding
        self._send_pos = jax.device_put(jnp.asarray(send_pos), row_sharding)

        cap_ = cap
        n_dev_ = n_dev
        axis_ = self.axis

        # photon: sharding(axes=[entity], in=[entity,entity], out=[entity])
        @jax.jit
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(axis_), P(axis_)),
            out_specs=P(axis_),
            check_vma=False,
        )
        def _route_in(vals, pos):
            buf = jnp.zeros((n_dev_ * cap_ + 1,), jnp.float32)
            buf = buf.at[pos].set(vals, mode="drop")[:-1]
            blocks = buf.reshape(n_dev_, cap_)
            out = lax.all_to_all(
                blocks, axis_, split_axis=0, concat_axis=0, tiled=False
            )
            return out.reshape(-1)

        self._route_in = _route_in

        # photon: sharding(axes=[entity], in=[entity,entity], out=[entity])
        @jax.jit
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(axis_), P(axis_)),
            out_specs=P(axis_),
            check_vma=False,
        )
        def _route_out(slot_vals, pos):
            blocks = slot_vals.reshape(n_dev_, cap_)
            back = lax.all_to_all(
                blocks, axis_, split_axis=0, concat_axis=0, tiled=False
            ).reshape(-1)
            safe = jnp.minimum(pos, n_dev_ * cap_ - 1)
            return jnp.where(pos < n_dev_ * cap_, back[safe], 0.0)

        self._route_out = _route_out

    def _pad_rows(self, vec: Array) -> Array:
        vec = jnp.asarray(vec, jnp.float32)
        if vec.shape[0] != self.num_rows_padded:
            vec = jnp.concatenate([
                vec,
                jnp.zeros(
                    (self.num_rows_padded - vec.shape[0],), jnp.float32
                ),
            ])
        return jax.device_put(vec, self._row_sharding)

    def route_in(self, row_values: Array) -> Array:
        """[n] row values -> [n_dev * num_slots] owner-slot values
        (sharded over the axis). One all_to_all; no host round trip."""
        return self._route_in(self._pad_rows(row_values), self._send_pos)

    def route_out(self, slot_values: Array) -> Array:
        """[n_dev * num_slots] owner-slot values -> [num_rows_padded]
        row-aligned values (sharded). The reverse all_to_all of
        :meth:`route_in`; rows with no owner (padding) read 0."""
        return self._route_out(slot_values, self._send_pos)
