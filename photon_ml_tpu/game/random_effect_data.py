"""Random-effect dataset: per-entity data as bucketed dense blocks.

Reference: photon-ml .../data/RandomEffectDataSet.scala (activeData grouped
per entity with reservoir cap + weight rescale at :254-317, passive split
at :328-369), data/LocalDataSet.scala (Pearson feature filter :116-130,
scorer :202+), projector/IndexMapProjector.scala:83-105 (per-entity dense
re-indexing), ProjectionMatrix.scala:90-119 (shared Gaussian random
projection, intercept-preserving), RandomEffectDataSetPartitioner.scala
(entity load balancing).

TPU-native shape: the groupByKey shuffle becomes a host-side stable sort;
entities are packed into BUCKETS of equal sample capacity (power-of-two)
so per-entity solves vmap over [E_b, S_b, k] dense blocks with weight-0
padding — the "millions of tiny LBFGS solves" run as ONE XLA program per
bucket (SURVEY P2: entities are the expert-parallel analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.game.config import (
    ProjectorType,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.game.data import GameDataset, ShardData


@dataclass
class RandomEffectBucket:
    """Entities with <= capacity active samples, dense-packed."""

    entity_codes: np.ndarray  # int32 [E_b]
    row_index: np.ndarray  # int32 [E_b, S_b] global row id, -1 pad
    indices: np.ndarray  # int32 [E_b, S_b, k] LOCAL feature indices, 0 pad
    values: np.ndarray  # float32 [E_b, S_b, k]
    labels: np.ndarray  # float32 [E_b, S_b]
    offsets: np.ndarray  # float32 [E_b, S_b]
    weights: np.ndarray  # float32 [E_b, S_b] (0 pad; reservoir-rescaled)

    @property
    def num_entities(self) -> int:
        return self.entity_codes.shape[0]

    @property
    def capacity(self) -> int:
        return self.row_index.shape[1]


@dataclass
class RandomEffectDataset:
    """Active data bucketed per entity + row-aligned local projections."""

    config: RandomEffectDataConfiguration
    num_entities: int
    local_dim: int  # D: width of the entity model bank
    # per-entity projection: global feature id per local slot, -1 pad
    projection: np.ndarray  # int32 [E, D]
    # Row-aligned views over the FULL dataset (active + passive + unseen):
    # local feature indices per row (0 pad; unseen features dropped).
    row_local_indices: np.ndarray  # int32 [n, k]
    row_local_values: np.ndarray  # float32 [n, k]
    row_entity_codes: np.ndarray  # int32 [n] (-1 for padding rows)
    buckets: List[RandomEffectBucket]
    num_active_rows: int
    num_passive_rows: int
    # RANDOM projector only: [d_global, D] projection matrix
    random_projection: Optional[np.ndarray] = None

    @property
    def intercept_local_index(self) -> Optional[int]:
        return self._intercept_local

    _intercept_local: Optional[int] = None


def _pearson_keep_mask(
    rows_ix: List[np.ndarray],
    rows_v: List[np.ndarray],
    labels: np.ndarray,
    dim: int,
    num_keep: int,
    intercept_index: Optional[int],
) -> np.ndarray:
    """Top-|Pearson(feature, label)| feature mask over one entity's rows
    (LocalDataSet.filterFeaturesByPearsonCorrelationScore:116-130; the
    intercept is always kept)."""
    m = len(rows_ix)
    x_sum = np.zeros(dim)
    x2_sum = np.zeros(dim)
    xy_sum = np.zeros(dim)
    y = labels - labels.mean()
    for r in range(m):
        np.add.at(x_sum, rows_ix[r], rows_v[r])
        np.add.at(x2_sum, rows_ix[r], rows_v[r] ** 2)
        np.add.at(xy_sum, rows_ix[r], rows_v[r] * y[r])
    x_mean = x_sum / m
    x_var = x2_sum / m - x_mean**2
    y_var = float((y**2).mean())
    denom = np.sqrt(np.maximum(x_var * y_var, 1e-30))
    corr = np.where(denom > 1e-15, np.abs(xy_sum / m) / denom, 0.0)
    if intercept_index is not None:
        corr[intercept_index] = np.inf  # always keep
    order = np.argsort(-corr)
    keep = np.zeros(dim, bool)
    keep[order[:num_keep]] = True
    return keep


def build_random_effect_dataset(
    dataset: GameDataset,
    config: RandomEffectDataConfiguration,
    *,
    seed: int = 0,
) -> RandomEffectDataset:
    """GameDataset + config -> bucketed per-entity dataset.

    Mirrors RandomEffectDataSet.buildWithConfiguration: group by entity,
    reservoir-cap active data with weight rescale cnt/cap, passive split,
    optional Pearson filter, per-entity index (or shared random)
    projection.
    """
    shard: ShardData = dataset.shards[config.feature_shard_id]
    codes = dataset.entity_codes[config.random_effect_type]
    eindex = dataset.entity_indexes[config.random_effect_type]
    E = eindex.num_entities
    n = dataset.num_rows
    k = shard.indices.shape[1]
    rng = np.random.default_rng(seed)

    real = dataset.weights > 0
    # --- group rows by entity (the groupByKey analog: stable sort) -------
    rows_of: List[List[int]] = [[] for _ in range(E)]
    for i in np.nonzero(real)[0]:
        c = codes[i]
        if c >= 0:
            rows_of[int(c)].append(int(i))

    cap = config.active_data_upper_bound
    active_rows: List[List[int]] = []
    active_weight_scale: List[float] = []
    num_passive = 0
    for e in range(E):
        rows = rows_of[e]
        if cap is not None and len(rows) > cap:
            chosen = rng.choice(len(rows), size=cap, replace=False)
            active = [rows[j] for j in np.sort(chosen)]
            # weight rescale cumCount/size (RandomEffectDataSet.scala:254-317)
            scale = len(rows) / cap
            num_passive += len(rows) - cap
        else:
            active = rows
            scale = 1.0
        active_rows.append(active)
        active_weight_scale.append(scale)

    # --- per-entity feature selection + local projection -----------------
    dim = shard.dim
    proj_type = config.projector_type
    random_projection = None
    if proj_type == ProjectorType.RANDOM:
        D = int(config.random_projection_dim)
        # Gaussian N(0, 1/D), intercept column preserved
        # (ProjectionMatrix.scala:90-119).
        random_projection = rng.normal(
            0.0, 1.0 / np.sqrt(D), size=(dim, D)
        ).astype(np.float32)
        if shard.intercept_index is not None:
            random_projection[shard.intercept_index, :] = 0.0
            random_projection[:, D - 1] = np.where(
                np.arange(dim) == shard.intercept_index, 1.0, 0.0
            )

    local_maps: List[Dict[int, int]] = []
    local_dims: List[int] = []
    projections: List[np.ndarray] = []
    intercept_local: Optional[int] = None
    if proj_type == ProjectorType.IDENTITY or proj_type == ProjectorType.RANDOM:
        D = dim if proj_type == ProjectorType.IDENTITY else int(
            config.random_projection_dim
        )
        local_maps = None  # identity/matrix handled row-wise below
    else:  # INDEX_MAP
        for e in range(E):
            feats = set()
            rows = active_rows[e]
            m = len(rows)
            if m and config.features_to_samples_ratio is not None:
                num_keep = max(1, int(np.ceil(config.features_to_samples_ratio * m)))
                rows_ix = [shard.indices[i][shard.values[i] != 0] for i in rows]
                rows_v = [shard.values[i][shard.values[i] != 0] for i in rows]
                keep = _pearson_keep_mask(
                    rows_ix, rows_v, dataset.labels[rows], dim, num_keep,
                    shard.intercept_index,
                )
            else:
                keep = None
            for i in rows:
                for s in range(k):
                    v = shard.values[i, s]
                    if v != 0:
                        j = int(shard.indices[i, s])
                        if keep is None or keep[j]:
                            feats.add(j)
            if shard.intercept_index is not None:
                feats.add(shard.intercept_index)
            ordered = sorted(feats)
            local_maps.append({g: l for l, g in enumerate(ordered)})
            local_dims.append(len(ordered))
            projections.append(np.asarray(ordered, np.int32))
        D = max(local_dims) if local_dims else 1

    D = max(D, 1)
    projection = np.full((E, D), -1, np.int32)
    if proj_type == ProjectorType.INDEX_MAP:
        for e in range(E):
            projection[e, : local_dims[e]] = projections[e]
    elif proj_type == ProjectorType.IDENTITY:
        projection[:] = np.arange(D, dtype=np.int32)[None, :]
        if shard.intercept_index is not None:
            intercept_local = shard.intercept_index
    if proj_type == ProjectorType.RANDOM and shard.intercept_index is not None:
        intercept_local = D - 1

    # --- row-aligned local features over the FULL table ------------------
    row_local_ix = np.zeros((n, k), np.int32)
    row_local_v = np.zeros((n, k), np.float32)
    if proj_type == ProjectorType.IDENTITY:
        row_local_ix = shard.indices.copy()
        row_local_v = shard.values.copy()
    elif proj_type == ProjectorType.RANDOM:
        # dense projected rows: x_local = x . P  [D]; store as dense slots
        if D > k:
            row_local_ix = np.zeros((n, D), np.int32)
            row_local_v = np.zeros((n, D), np.float32)
        else:
            row_local_ix = np.zeros((n, max(k, D)), np.int32)
            row_local_v = np.zeros((n, max(k, D)), np.float32)
        row_local_ix[:, :D] = np.arange(D, dtype=np.int32)[None, :]
        for i in range(n):
            if not real[i]:
                continue
            nz = shard.values[i] != 0
            x_proj = random_projection[shard.indices[i][nz]].T @ shard.values[i][nz]
            row_local_v[i, :D] = x_proj
    else:  # INDEX_MAP
        for i in range(n):
            c = int(codes[i])
            if not real[i] or c < 0:
                continue
            lm = local_maps[c]
            for s in range(k):
                v = shard.values[i, s]
                if v != 0:
                    l = lm.get(int(shard.indices[i, s]))
                    if l is not None:
                        row_local_ix[i, s] = l
                        row_local_v[i, s] = v

    # --- bucketed active data -------------------------------------------
    counts = np.asarray([len(r) for r in active_rows])
    caps: List[int] = []
    for c in counts:
        if c > 0:
            s = 1
            while s < c:
                s *= 2
            caps.append(s)
        else:
            caps.append(0)
    caps_arr = np.asarray(caps)
    buckets: List[RandomEffectBucket] = []
    kk = row_local_ix.shape[1]
    num_active = int(counts.sum())
    for S in sorted(set(c for c in caps if c > 0)):
        members = np.nonzero(caps_arr == S)[0]
        E_b = len(members)
        b_rows = np.full((E_b, S), -1, np.int32)
        b_ix = np.zeros((E_b, S, kk), np.int32)
        b_v = np.zeros((E_b, S, kk), np.float32)
        b_lab = np.zeros((E_b, S), np.float32)
        b_off = np.zeros((E_b, S), np.float32)
        b_w = np.zeros((E_b, S), np.float32)
        for bi, e in enumerate(members):
            rows = active_rows[e]
            scale = active_weight_scale[e]
            for si, i in enumerate(rows):
                b_rows[bi, si] = i
                b_ix[bi, si] = row_local_ix[i]
                b_v[bi, si] = row_local_v[i]
                b_lab[bi, si] = dataset.labels[i]
                b_off[bi, si] = dataset.offsets[i]
                b_w[bi, si] = dataset.weights[i] * scale
        buckets.append(
            RandomEffectBucket(
                entity_codes=members.astype(np.int32),
                row_index=b_rows,
                indices=b_ix,
                values=b_v,
                labels=b_lab,
                offsets=b_off,
                weights=b_w,
            )
        )

    ds = RandomEffectDataset(
        config=config,
        num_entities=E,
        local_dim=D,
        projection=projection,
        row_local_indices=row_local_ix,
        row_local_values=row_local_v,
        row_entity_codes=np.where(real, codes, -1).astype(np.int32),
        buckets=buckets,
        num_active_rows=num_active,
        num_passive_rows=num_passive,
        random_projection=random_projection,
    )
    ds._intercept_local = intercept_local
    return ds
