"""Random-effect dataset: per-entity data as bucketed dense blocks.

Reference: photon-ml .../data/RandomEffectDataSet.scala (activeData grouped
per entity with reservoir cap + weight rescale at :254-317, passive split
at :328-369), data/LocalDataSet.scala (Pearson feature filter :116-130,
scorer :202+), projector/IndexMapProjector.scala:83-105 (per-entity dense
re-indexing), ProjectionMatrix.scala:90-119 (shared Gaussian random
projection, intercept-preserving), RandomEffectDataSetPartitioner.scala
(entity load balancing).

TPU-native shape: the groupByKey shuffle becomes a host-side stable sort;
entities are packed into BUCKETS of equal sample capacity (power-of-two)
so per-entity solves vmap over [E_b, S_b, k] dense blocks with weight-0
padding — the "millions of tiny LBFGS solves" run as ONE XLA program per
bucket (SURVEY P2: entities are the expert-parallel analog).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from photon_ml_tpu.game.config import (
    ProjectorType,
    RandomEffectDataConfiguration,
)
from photon_ml_tpu.game.data import GameDataset, ShardData


@dataclass
class RandomEffectBucket:
    """Entities with <= capacity active samples, dense-packed."""

    entity_codes: np.ndarray  # int32 [E_b]
    row_index: np.ndarray  # int32 [E_b, S_b] global row id, -1 pad
    indices: np.ndarray  # int32 [E_b, S_b, k] LOCAL feature indices, 0 pad
    values: np.ndarray  # float32 [E_b, S_b, k]
    labels: np.ndarray  # float32 [E_b, S_b]
    offsets: np.ndarray  # float32 [E_b, S_b]
    weights: np.ndarray  # float32 [E_b, S_b] (0 pad; reservoir-rescaled)
    # True when ``indices`` is the tiled arange(k) (k == local_dim, the
    # MF latent view): the dense solvers then use X = values directly,
    # skipping the [E, S, k, D] densify broadcast entirely
    identity_indices: bool = False

    @property
    def num_entities(self) -> int:
        return self.entity_codes.shape[0]

    @property
    def capacity(self) -> int:
        return self.row_index.shape[1]


@dataclass
class RandomEffectDataset:
    """Active data bucketed per entity + row-aligned local projections."""

    config: RandomEffectDataConfiguration
    num_entities: int
    local_dim: int  # D: width of the entity model bank
    # per-entity projection: global feature id per local slot, -1 pad
    projection: np.ndarray  # int32 [E, D]
    # Row-aligned views over the FULL dataset (active + passive + unseen):
    # local feature indices per row (0 pad; unseen features dropped).
    row_local_indices: np.ndarray  # int32 [n, k]
    row_local_values: np.ndarray  # float32 [n, k]
    row_entity_codes: np.ndarray  # int32 [n] (-1 for padding rows)
    buckets: List[RandomEffectBucket]
    num_active_rows: int
    num_passive_rows: int
    # RANDOM projector only: [d_global, D] projection matrix
    random_projection: Optional[np.ndarray] = None

    @property
    def intercept_local_index(self) -> Optional[int]:
        return self._intercept_local

    _intercept_local: Optional[int] = None


def build_random_effect_dataset(
    dataset: GameDataset,
    config: RandomEffectDataConfiguration,
    *,
    seed: int = 0,
) -> RandomEffectDataset:
    """GameDataset + config -> bucketed per-entity dataset.

    Mirrors RandomEffectDataSet.buildWithConfiguration: group by entity,
    reservoir-cap active data with weight rescale cnt/cap, passive split,
    optional Pearson filter, per-entity index (or shared random)
    projection.

    The reference does this as a distributed groupByKey shuffle
    (RandomEffectDataSet.scala:169-369); here the whole build is a handful
    of argsort/bincount/flat-scatter passes — no per-row or per-entity
    Python loops — so one host saturates (1M rows x 8 nnz with 100k
    entities builds in ~2-3 s vs ~13 s/1M rows for the round-2 loop
    build; the unique() sort over entity-feature keys dominates).
    """
    shard: ShardData = dataset.shards[config.feature_shard_id]
    codes = np.asarray(dataset.entity_codes[config.random_effect_type])
    eindex = dataset.entity_indexes[config.random_effect_type]
    E = eindex.num_entities
    n = dataset.num_rows
    k = shard.indices.shape[1]
    rng = np.random.default_rng(seed)

    real = np.asarray(dataset.weights) > 0
    valid = real & (codes >= 0)
    labels = np.asarray(dataset.labels)
    offsets = np.asarray(dataset.offsets)
    weights = np.asarray(dataset.weights)

    # --- group rows by entity (the groupByKey analog: one stable sort) ---
    vrows = np.nonzero(valid)[0]
    scodes = codes[vrows]
    order = np.argsort(scodes, kind="stable")
    srows = vrows[order]  # grouped by entity, ascending row id within
    scodes = scodes[order]
    counts = np.bincount(scodes, minlength=E)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])

    # --- reservoir cap with weight rescale cnt/cap -----------------------
    # (RandomEffectDataSet.scala:254-317). Uniform without-replacement
    # sampling per over-cap entity: random priority per row, keep the cap
    # best-ranked priorities within each entity.
    cap = config.active_data_upper_bound
    if cap is not None and len(srows):
        pri = rng.random(len(srows))
        po = np.lexsort((pri, scodes))
        pri_rank = np.empty(len(srows), np.int64)
        pri_rank[po] = np.arange(len(srows)) - starts[scodes[po]]
        keep_active = pri_rank < cap
        scale_e = np.where(counts > cap, counts / max(cap, 1), 1.0)
        num_passive = int(np.maximum(counts - cap, 0).sum())
    else:
        keep_active = np.ones(len(srows), bool)
        scale_e = np.ones(E)
        num_passive = 0
    arows = srows[keep_active]
    acodes = scodes[keep_active]
    acounts = np.bincount(acodes, minlength=E)
    astarts = np.concatenate([[0], np.cumsum(acounts)[:-1]])
    arank = np.arange(len(arows)) - astarts[acodes]
    num_active = int(acounts.sum())

    # --- per-entity feature selection + local projection + row remap -----
    dim = shard.dim
    proj_type = config.projector_type
    random_projection = None
    intercept_local: Optional[int] = None

    if proj_type == ProjectorType.IDENTITY:
        D = max(dim, 1)
        projection = np.full((E, D), -1, np.int32)
        projection[:] = np.arange(D, dtype=np.int32)[None, :]
        if shard.intercept_index is not None:
            intercept_local = shard.intercept_index
        row_local_ix = shard.indices.copy()
        row_local_v = shard.values.copy()
    elif proj_type == ProjectorType.RANDOM:
        D = max(int(config.random_projection_dim), 1)
        # Gaussian N(0, 1/D), intercept column preserved
        # (ProjectionMatrix.scala:90-119).
        random_projection = rng.normal(
            0.0, 1.0 / np.sqrt(D), size=(dim, D)
        ).astype(np.float32)
        if shard.intercept_index is not None:
            random_projection[shard.intercept_index, :] = 0.0
            random_projection[:, D - 1] = np.where(
                np.arange(dim) == shard.intercept_index, 1.0, 0.0
            )
            intercept_local = D - 1
        projection = np.full((E, D), -1, np.int32)
        # dense projected rows: x_local = x . P  [D]
        kk = max(k, D)
        row_local_ix = np.zeros((n, kk), np.int32)
        row_local_v = np.zeros((n, kk), np.float32)
        row_local_ix[:, :D] = np.arange(D, dtype=np.int32)[None, :]
        chunk = max(1, (1 << 22) // max(D, 1))  # bound gather temp memory
        for s in range(0, len(vrows), chunk):
            rs = vrows[s:s + chunk]
            vals = shard.values[rs]  # [c, k]
            proj = random_projection[shard.indices[rs]]  # [c, k, D]
            row_local_v[rs, :D] = np.einsum(
                "ck,ckd->cd", vals, proj, optimize=True
            )
    else:  # INDEX_MAP: per-entity dense re-indexing of active features
        # (IndexMapProjector.scala:83-105). ONE unique(return_inverse) over
        # the live entries of every valid row replaces the per-entity set
        # building AND every later lookup: a per-key "kept" mask (active
        # membership / Pearson top-k / intercept) defines the map, the
        # inverse positions remap every row — no searchsorted anywhere.
        ratio = config.features_to_samples_ratio
        srow_of_entry = np.repeat(np.arange(len(srows)), k)
        slot_of_entry = np.tile(np.arange(k), len(srows))
        ft = shard.indices[srows].ravel().astype(np.int64)
        vv = shard.values[srows].ravel()
        live = vv != 0
        e_srow = srow_of_entry[live]
        e_slot = slot_of_entry[live]
        e_val = vv[live]
        ekeys = scodes[e_srow].astype(np.int64) * dim + ft[live]
        n_live = len(ekeys)
        if shard.intercept_index is not None:
            # intercept key for EVERY entity (always in the map, even for
            # entities with no active rows)
            icept = (
                np.arange(E, dtype=np.int64) * dim + shard.intercept_index
            )
            ekeys = np.concatenate([ekeys, icept])
        uniq, inv = np.unique(ekeys, return_inverse=True)
        inv_live = inv[:n_live]
        U = len(uniq)
        code_u = uniq // dim
        feat_u = uniq % dim
        counts_u = np.bincount(code_u, minlength=E)
        starts_u = np.concatenate([[0], np.cumsum(counts_u)[:-1]])

        entry_active = keep_active[e_srow]
        kept = np.zeros(U, bool)
        if ratio is None:
            if cap is None:
                kept[:] = True
            else:
                # map = features seen in at least one ACTIVE entry
                kept[inv_live[entry_active]] = True
                if shard.intercept_index is not None:
                    kept[inv[n_live:]] = True
        else:
            # Pearson top-k per entity over the ACTIVE entries
            # (LocalDataSet.filterFeaturesByPearsonCorrelationScore:116-130)
            lab_s = labels[srows].astype(np.float64)
            m_safe = np.maximum(acounts, 1)
            ybar = (
                np.bincount(
                    scodes[keep_active], weights=lab_s[keep_active],
                    minlength=E,
                )
                / m_safe
            )
            yc_s = np.where(keep_active, lab_s - ybar[scodes], 0.0)
            y_var = np.bincount(scodes, weights=yc_s**2, minlength=E) / m_safe
            va = np.where(entry_active, e_val.astype(np.float64), 0.0)
            x_sum = np.bincount(inv_live, weights=va, minlength=U)
            x2_sum = np.bincount(inv_live, weights=va * va, minlength=U)
            xy_sum = np.bincount(
                inv_live, weights=va * yc_s[e_srow], minlength=U
            )
            cand = np.zeros(U, bool)
            cand[inv_live[entry_active]] = True
            m = acounts[code_u].astype(np.float64)
            m = np.maximum(m, 1.0)
            x_mean = x_sum / m
            x_var = x2_sum / m - x_mean**2
            denom = np.sqrt(np.maximum(x_var * y_var[code_u], 1e-30))
            corr = np.where(denom > 1e-15, np.abs(xy_sum / m) / denom, 0.0)
            corr = np.where(cand, corr, -np.inf)
            if shard.intercept_index is not None:
                cand[inv[n_live:]] = True
                corr = np.where(feat_u == shard.intercept_index, np.inf, corr)
            num_keep = np.maximum(
                1, np.ceil(ratio * acounts[code_u])
            ).astype(np.int64)
            order_u = np.lexsort((-corr, code_u))
            rank = np.arange(U) - starts_u[code_u[order_u]]
            kept[order_u] = rank < num_keep[order_u]
            kept &= cand

        # local index of each kept key = its rank among kept within entity
        kept_cum = np.cumsum(kept)
        kept_before = np.concatenate([[0], kept_cum])[starts_u]
        local_u = (kept_cum - 1) - kept_before[code_u]  # valid where kept
        local_dims = np.bincount(code_u[kept], minlength=E)
        D = max(int(local_dims.max()) if U else 1, 1)
        projection = np.full((E, D), -1, np.int32)
        if U:
            projection[code_u[kept], local_u[kept]] = feat_u[kept].astype(
                np.int32
            )

        # row remap over the FULL valid table (active + passive rows;
        # filtered-out features drop to 0-slots)
        row_local_ix = np.zeros((n, k), np.int32)
        row_local_v = np.zeros((n, k), np.float32)
        entry_kept = kept[inv_live]
        er = srows[e_srow[entry_kept]]
        es = e_slot[entry_kept]
        row_local_ix[er, es] = local_u[inv_live[entry_kept]].astype(np.int32)
        row_local_v[er, es] = e_val[entry_kept]

    # --- bucketed active data (power-of-two capacities) ------------------
    # one flat scatter per bucket instead of per-entity/per-row fills
    caps_arr = np.zeros(E, np.int64)
    nz_e = acounts > 0
    caps_arr[nz_e] = 1 << np.ceil(
        np.log2(np.maximum(acounts[nz_e], 1))
    ).astype(np.int64)
    buckets: List[RandomEffectBucket] = []
    kk = row_local_ix.shape[1]
    row_scale = scale_e[acodes]  # reservoir weight rescale per active row
    for S in sorted(set(caps_arr[nz_e].tolist())):
        members = np.nonzero(caps_arr == S)[0]
        E_b = len(members)
        in_bucket = caps_arr[acodes] == S
        br = arows[in_bucket]  # global row ids, grouped by entity
        # entity -> dense slot in this bucket
        b_pos = np.searchsorted(members, acodes[in_bucket])
        b_slot = arank[in_bucket]
        b_rows = np.full((E_b, S), -1, np.int32)
        b_ix = np.zeros((E_b, S, kk), np.int32)
        b_v = np.zeros((E_b, S, kk), np.float32)
        b_lab = np.zeros((E_b, S), np.float32)
        b_off = np.zeros((E_b, S), np.float32)
        b_w = np.zeros((E_b, S), np.float32)
        b_rows[b_pos, b_slot] = br.astype(np.int32)
        b_ix[b_pos, b_slot] = row_local_ix[br]
        b_v[b_pos, b_slot] = row_local_v[br]
        b_lab[b_pos, b_slot] = labels[br]
        b_off[b_pos, b_slot] = offsets[br]
        b_w[b_pos, b_slot] = weights[br] * row_scale[in_bucket]
        buckets.append(
            RandomEffectBucket(
                entity_codes=members.astype(np.int32),
                row_index=b_rows,
                indices=b_ix,
                values=b_v,
                labels=b_lab,
                offsets=b_off,
                weights=b_w,
            )
        )

    ds = RandomEffectDataset(
        config=config,
        num_entities=E,
        local_dim=D,
        projection=projection,
        row_local_indices=row_local_ix,
        row_local_values=row_local_v,
        row_entity_codes=np.where(real, codes, -1).astype(np.int32),
        buckets=buckets,
        num_active_rows=num_active,
        num_passive_rows=num_passive,
        random_projection=random_projection,
    )
    ds._intercept_local = intercept_local
    return ds
