"""GAME dataset: multi-shard sparse batches + entity codes, device-resident.

Reference: photon-ml .../data/GameDatum.scala:33-54 (response, offset,
weight, featureShardContainer, idTypeToValueMap),
avro/data/DataProcessingUtils.scala:57-143 (GenericRecord -> GameDatum:
per-shard sparse vectors from feature bags, id extraction from fields or
metadataMap), cli/game/training/Driver.scala:66-124 (prepareGameDataSet).

TPU-native shape: ONE row-aligned table. Every per-row quantity (labels,
offsets, weights, per-shard padded sparse features, per-id-type dense
entity codes) is an array over the same row axis, so scores are plain [n]
arrays (KeyValueScore.scala's fullOuterJoin algebra becomes vector adds)
and coordinate residuals stay on device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.data.batch import SparseBatch
from photon_ml_tpu.game.config import FeatureShardConfiguration
from photon_ml_tpu.utils.index_map import IndexMap, feature_key, intercept_key

Array = jnp.ndarray


@dataclass
class EntityIndex:
    """Dense code <-> raw entity id for one random-effect type."""

    id_type: str
    ids: List[str]  # code -> raw id
    code_of: Dict[str, int]

    @property
    def num_entities(self) -> int:
        return len(self.ids)

    @staticmethod
    def build(id_type: str, values: Iterable[str]) -> "EntityIndex":
        ids = sorted(set(values))
        return EntityIndex(id_type, ids, {v: i for i, v in enumerate(ids)})


@dataclass
class ShardData:
    """Padded sparse features of one feature shard, row-aligned."""

    indices: np.ndarray  # int32 [n, k]
    values: np.ndarray  # float32 [n, k]
    index_map: IndexMap
    intercept_index: Optional[int]

    @property
    def dim(self) -> int:
        return self.index_map.size


@dataclass
class GameDataset:
    """Row-aligned GAME data table."""

    uids: List[str]
    labels: np.ndarray  # [n]
    offsets: np.ndarray  # [n]
    weights: np.ndarray  # [n]
    shards: Dict[str, ShardData]
    entity_codes: Dict[str, np.ndarray]  # id_type -> int32 [n]
    entity_indexes: Dict[str, EntityIndex]
    num_real_rows: int  # rows before padding

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    def batch_for_shard(
        self, shard_id: str, offsets: Optional[np.ndarray] = None
    ) -> SparseBatch:
        """SparseBatch view of one shard (GameDatum.
        generateLabeledPointWithFeatureShardId analog); ``offsets``
        overrides stored offsets (the residual-score path).

        Device copies of the static columns are cached per shard: the
        coordinate-descent loop calls this every iteration and must not
        re-upload the feature table each time (device-resident
        KeyValueScore design, SURVEY §7.9) — only the offsets vector
        varies, and the residual path passes it as an already-on-device
        array."""
        cache = self.__dict__.setdefault("_device_cache", {})
        rows = cache.get(None)  # dataset-level row columns, shared
        if rows is None:
            rows = (
                jnp.asarray(self.labels),
                jnp.asarray(self.offsets),
                jnp.asarray(self.weights),
            )
            cache[None] = rows
        lab, base_off, w = rows
        hit = cache.get(shard_id)
        if hit is None:
            sd = self.shards[shard_id]
            hit = (jnp.asarray(sd.indices), jnp.asarray(sd.values))
            cache[shard_id] = hit
        ix, v = hit
        return SparseBatch(
            indices=ix,
            values=v,
            labels=lab,
            offsets=base_off if offsets is None else jnp.asarray(offsets),
            weights=w,
        )


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _padded_width(k_max: int, pad_nnz_to: int) -> int:
    """Shared nnz-padding policy for both shard builders — changing it in
    one place keeps the record-at-a-time and native-columns paths
    bit-identical (test_game_dataset_parity)."""
    return max(_round_up(max(k_max, 1), pad_nnz_to), pad_nnz_to)


def _shard_data(indices, values, imap: IndexMap, icept: int) -> ShardData:
    return ShardData(
        indices=indices,
        values=values,
        index_map=imap,
        intercept_index=icept if icept >= 0 else None,
    )


def _pad_shard_rows(
    rows: Sequence[Tuple[List[int], List[float]]],
    n_pad: int,
    pad_nnz_to: int,
    imap: IndexMap,
    icept: int,
) -> ShardData:
    """Ragged (indices, values) rows -> padded ShardData (the
    record-at-a-time builder; the native-columns builder scatters into
    its padded arrays directly but shares _padded_width/_shard_data)."""
    k_max = max([1] + [len(ix) for ix, _ in rows])
    k = _padded_width(k_max, pad_nnz_to)
    indices = np.zeros((n_pad, k), np.int32)
    values = np.zeros((n_pad, k), np.float32)
    for i, (ix, vs) in enumerate(rows):
        indices[i, : len(ix)] = ix
        values[i, : len(vs)] = vs
    return _shard_data(indices, values, imap, icept)


def _build_entity_tables(
    random_effect_types: Sequence[str],
    raw_entity: Mapping[str, List[str]],
    n_pad: int,
) -> Tuple[Dict[str, EntityIndex], Dict[str, np.ndarray]]:
    """Raw per-row entity ids -> (EntityIndex, dense code array) per type."""
    entity_indexes: Dict[str, EntityIndex] = {}
    entity_codes: Dict[str, np.ndarray] = {}
    for id_type in random_effect_types:
        raw = raw_entity[id_type]
        eidx = EntityIndex.build(id_type, raw)
        codes = np.full((n_pad,), -1, np.int32)
        for i, v in enumerate(raw):
            codes[i] = eidx.code_of[v]
        entity_indexes[id_type] = eidx
        entity_codes[id_type] = codes
    return entity_indexes, entity_codes


def record_response(r: dict, is_response_required: bool = True) -> float:
    """Response from "response" or "label" field — the single definition
    of the record-level response rule (DataProcessingUtils.scala:57-143),
    shared by the in-memory builder and the streaming GAME scan/stage
    passes (game/streaming.py)."""
    if "response" in r and r["response"] is not None:
        return float(r["response"])
    if "label" in r and r["label"] is not None:
        return float(r["label"])
    if is_response_required:
        raise ValueError("record missing response/label field")
    return 0.0


def record_entity_id(r: dict, id_type: str) -> str:
    """Entity id from a top-level field or metadataMap, stringified —
    shared with the streaming GAME passes like :func:`record_response`."""
    v = r.get(id_type)
    if v is None:
        meta = r.get("metadataMap") or {}
        v = meta.get(id_type)
    if v is None:
        raise ValueError(f"record missing id {id_type!r}")
    return str(v)


def build_game_dataset(
    records: Iterable[dict],
    shard_configs: Sequence[FeatureShardConfiguration],
    random_effect_types: Sequence[str] = (),
    *,
    index_maps: Optional[Mapping[str, IndexMap]] = None,
    is_response_required: bool = True,
    pad_rows_to: int = 8,
    pad_nnz_to: int = 8,
    row_offset: int = 0,
) -> GameDataset:
    """Records -> GameDataset (DataProcessingUtils.getGameDataSetFrom
    GenericRecords analog).

    - response from "response" or "label" field (scoring mode tolerates
      absence with is_response_required=False);
    - ids read from top-level fields or metadataMap, stringified;
    - feature keys are name TAB term per bag, one IndexMap per shard;
    - ``row_offset`` shifts the fallback uid for records with no uid
      field so chunked builds (streaming scoring) stay globally unique.
    """
    records = list(records)
    n = len(records)
    if n == 0:
        raise ValueError("empty GAME dataset")

    def response_of(r):
        return record_response(r, is_response_required)

    def id_of(r, id_type):
        return record_entity_id(r, id_type)

    # Build or reuse per-shard index maps.
    imaps: Dict[str, IndexMap] = {}
    for cfg in shard_configs:
        if index_maps is not None and cfg.shard_id in index_maps:
            imaps[cfg.shard_id] = index_maps[cfg.shard_id]
        else:
            keys = (
                feature_key(f["name"], f["term"])
                for r in records
                for bag in cfg.feature_bags
                for f in (r.get(bag) or [])
            )
            imaps[cfg.shard_id] = IndexMap.build(
                keys, add_intercept=cfg.add_intercept
            )

    n_pad = max(_round_up(n, pad_rows_to), pad_rows_to)
    labels = np.zeros((n_pad,), np.float32)
    offsets = np.zeros((n_pad,), np.float32)
    weights = np.zeros((n_pad,), np.float32)
    uids: List[str] = []
    for i, r in enumerate(records):
        labels[i] = response_of(r)
        off_v = r.get("offset")
        wgt_v = r.get("weight")
        offsets[i] = 0.0 if off_v is None else float(off_v)
        # None -> 1.0 but an EXPLICIT 0.0 weight stays 0 (the old `or`
        # coerced falsy zero, diverging from the native column path)
        weights[i] = 1.0 if wgt_v is None else float(wgt_v)
        # row index only for a MISSING uid: 0 or "" are legitimate ids and
        # must round-trip (the native column path preserves them)
        uid_v = r.get("uid")
        uids.append(str(row_offset + i) if uid_v is None else str(uid_v))

    shards: Dict[str, ShardData] = {}
    for cfg in shard_configs:
        imap = imaps[cfg.shard_id]
        icept = imap.get_index(intercept_key()) if cfg.add_intercept else -1
        rows: List[Tuple[List[int], List[float]]] = []
        for r in records:
            ix: List[int] = []
            vs: List[float] = []
            for bag in cfg.feature_bags:
                for f in r.get(bag) or []:
                    j = imap.get_index(feature_key(f["name"], f["term"]))
                    if j >= 0:
                        ix.append(j)
                        vs.append(float(f["value"]))
            if icept >= 0:
                ix.append(icept)
                vs.append(1.0)
            rows.append((ix, vs))
        shards[cfg.shard_id] = _pad_shard_rows(
            rows, n_pad, pad_nnz_to, imap, icept
        )

    entity_indexes, entity_codes = _build_entity_tables(
        random_effect_types,
        {t: [id_of(r, t) for r in records] for t in random_effect_types},
        n_pad,
    )

    return GameDataset(
        uids=uids,
        labels=labels,
        offsets=offsets,
        weights=weights,
        shards=shards,
        entity_codes=entity_codes,
        entity_indexes=entity_indexes,
        num_real_rows=n,
    )


def slice_game_dataset(ds: GameDataset, start: int, stop: int) -> GameDataset:
    """Row-range view [start, stop) over a dataset's REAL rows — the
    scoring drivers' chunk unit. Array slices are views (no copy);
    entity indexes are shared (codes are already dense)."""
    stop = min(stop, ds.num_real_rows)
    return GameDataset(
        uids=ds.uids[start:stop],
        labels=ds.labels[start:stop],
        offsets=ds.offsets[start:stop],
        weights=ds.weights[start:stop],
        shards={
            k: ShardData(
                sd.indices[start:stop], sd.values[start:stop],
                sd.index_map, sd.intercept_index,
            )
            for k, sd in ds.shards.items()
        },
        entity_codes={t: c[start:stop] for t, c in ds.entity_codes.items()},
        entity_indexes=ds.entity_indexes,
        num_real_rows=stop - start,
    )


def build_game_dataset_from_files(
    paths,
    shard_configs: Sequence[FeatureShardConfiguration],
    random_effect_types: Sequence[str] = (),
    *,
    index_maps: Optional[Mapping[str, IndexMap]] = None,
    is_response_required: bool = True,
    pad_rows_to: int = 8,
    pad_nnz_to: int = 8,
    row_offset: int = 0,
) -> GameDataset:
    """Avro files -> GameDataset through the native column decoder, with a
    transparent fallback to the record-at-a-time Python path
    (:func:`build_game_dataset` over ``read_avro_records``).

    The native path materializes every shard's feature bags, the
    response/offset/weight scalars, the uid, and entity ids (top-level
    string fields or metadataMap entries) as columns in one C++ pass per
    file — the JVM-executor decode of DataProcessingUtils.scala:57-143
    without Spark.
    """
    from photon_ml_tpu.io.avro_codec import (
        read_avro_records,
        read_container_schema,
    )
    from photon_ml_tpu.io.paths import expand_input_paths

    def fallback():
        return build_game_dataset(
            read_avro_records(paths),
            shard_configs,
            random_effect_types,
            index_maps=index_maps,
            is_response_required=is_response_required,
            pad_rows_to=pad_rows_to,
            pad_nnz_to=pad_nnz_to,
            row_offset=row_offset,
        )

    try:
        from photon_ml_tpu.io import native_avro
    except Exception:
        return fallback()
    if not native_avro.available():
        return fallback()
    files = list(expand_input_paths(paths, lambda fn: fn.endswith(".avro")))
    if not files:
        return fallback()

    all_bags = sorted({b for cfg in shard_configs for b in cfg.feature_bags})
    decoded = []
    try:
        for p in files:
            schema = read_container_schema(p)
            fields = {f["name"]: f["type"] for f in schema.get("fields", [])}
            if not all(b in fields for b in all_bags):
                return fallback()
            # BOTH response and label are captured when present: the
            # Python builder falls back per RECORD (response-then-label),
            # not per file
            response_fields = [
                f for f in ("response", "label") if f in fields
            ]
            if not response_fields and is_response_required:
                return fallback()
            numeric = [
                f
                for f in response_fields + ["offset", "weight"]
                if f in fields
            ]
            top_ids = [t for t in random_effect_types if t in fields]
            map_only_ids = [t for t in random_effect_types if t not in fields]
            strings = (["uid"] if "uid" in fields else []) + top_ids
            if map_only_ids and "metadataMap" not in fields:
                return fallback()  # the Python path raises the same way
            # A NULLABLE top-level id field may be null per record with the
            # value in metadataMap — capture both and merge per record,
            # matching the Python builder's id_of fallback. Non-nullable id
            # fields skip the map capture so datasets whose metadataMap the
            # plan can't decode (non-string values) stay on the fast path.
            has_map = "metadataMap" in fields

            def _nullable(ftype):
                return isinstance(ftype, list) and any(
                    t == "null"
                    or (isinstance(t, dict) and t.get("type") == "null")
                    for t in ftype
                )

            map_keys = map_only_ids + (
                [t for t in top_ids if _nullable(fields[t])]
                if has_map
                else []
            )
            plan = native_avro.Plan(schema).compile(
                numeric_fields=numeric,
                string_fields=strings,
                bag_fields=all_bags,
                map_field="metadataMap" if map_keys else None,
                map_keys=map_keys,
            )
            cols = native_avro.decode_columns(p, plan)
            decoded.append((cols, response_fields, set(strings), set(map_keys)))
    except (native_avro.PlanError, ValueError, OSError):
        # ValueError covers decode-time native rejections; semantic errors
        # (missing ids, null labels) are re-detected identically by the
        # fallback, which raises the canonical message
        return fallback()

    n = sum(cols.num_records for cols, _, _, _ in decoded)
    if n == 0:
        raise ValueError("empty GAME dataset")
    n_pad = max(_round_up(n, pad_rows_to), pad_rows_to)
    labels = np.zeros((n_pad,), np.float32)
    offsets = np.zeros((n_pad,), np.float32)
    weights = np.zeros((n_pad,), np.float32)
    uids: List[str] = []
    raw_entity: Dict[str, List[str]] = {t: [] for t in random_effect_types}

    # scalars + ids, file by file
    row0 = 0
    for cols, response_fields, strings, map_keys in decoded:
        m = cols.num_records
        lab = np.full(m, np.nan)
        for f in response_fields:  # response first, then label, per record
            cand = cols.f64(f)
            lab = np.where(np.isnan(lab), cand, lab)
        bad = np.isnan(lab)
        if bad.any():
            if is_response_required:
                raise ValueError("record missing response/label field")
            lab = np.where(bad, 0.0, lab)
        off = (
            cols.f64("offset")
            if "offset" in cols.plan.num_slots
            else np.zeros(m)
        )
        wgt = (
            cols.f64("weight")
            if "weight" in cols.plan.num_slots
            else np.ones(m)
        )
        labels[row0:row0 + m] = lab
        offsets[row0:row0 + m] = np.where(np.isnan(off), 0.0, off)
        weights[row0:row0 + m] = np.where(np.isnan(wgt), 1.0, wgt)

        if "uid" in strings:
            for i, sid in enumerate(cols.str_ids("uid")):
                # only a MISSING uid (null branch) falls back to the row
                # index — "" is a legitimate id (matches the Python
                # builder since round 4)
                uids.append(
                    cols.strings[sid]
                    if sid >= 0
                    else str(row_offset + row0 + i)
                )
        else:
            uids.extend(str(row_offset + row0 + i) for i in range(m))

        for t in random_effect_types:
            if t in strings:
                ids = cols.str_ids(t)
                if t in map_keys:
                    # null top-level value -> per-record metadataMap
                    # fallback (build_game_dataset's id_of)
                    ids = np.where(ids < 0, cols.map_ids(t), ids)
            else:
                ids = cols.map_ids(t)
            missing = ids < 0
            if missing.any():
                raise ValueError(f"record missing id {t!r}")
            raw_entity[t].extend(cols.strings[j] for j in ids)
        row0 += m

    # Decode every bag ONCE per file (cols.bag copies the nnz-sized
    # arrays out of the native buffers on each call) and reuse the tuples
    # for both the index-map key scan and the row assembly below.
    bag_cache: List[Dict[str, tuple]] = [
        {bag: cols.bag(bag) for bag in all_bags}
        for cols, _, _, _ in decoded
    ]

    # shards: merge each config's bags row-wise; vectorized key remap
    imaps: Dict[str, IndexMap] = {}
    for cfg in shard_configs:
        if index_maps is not None and cfg.shard_id in index_maps:
            imaps[cfg.shard_id] = index_maps[cfg.shard_id]
        else:
            keys = (
                cols.strings[j]
                for (cols, _, _, _), bags in zip(decoded, bag_cache)
                for bag in cfg.feature_bags
                for j in bags[bag][1]
            )
            imaps[cfg.shard_id] = IndexMap.build(
                keys, add_intercept=cfg.add_intercept
            )

    shards: Dict[str, ShardData] = {}
    for cfg in shard_configs:
        imap = imaps[cfg.shard_id]
        icept = imap.get_index(intercept_key()) if cfg.add_intercept else -1
        # Fully vectorized assembly (a per-record python loop here cost
        # ~30us/row): per file, remap each bag's interned keys, filter
        # dropped (-1) features, stable-sort entries by global row (bag
        # order preserved within a row), then scatter every entry into
        # the padded [n_pad, k] arrays with one flat assignment.
        per_file = []  # (row_of_entry_global, gix, values) kept entries
        counts = np.zeros(n_pad, np.int64)
        row0 = 0
        for (cols, _, _, _), bags in zip(decoded, bag_cache):
            m = cols.num_records
            # remap table restricted to intern ids this config's bags
            # actually reference (the full string table also holds uids
            # and entity ids — potentially one per row)
            cfg_keys = [bags[bag][1] for bag in cfg.feature_bags]
            used = (
                np.unique(np.concatenate(cfg_keys))
                if any(len(k) for k in cfg_keys)
                else np.zeros(0, np.int64)
            )
            table = np.full(len(cols.strings), -1, dtype=np.int64)
            for j in used:
                table[j] = imap.get_index(cols.strings[j])
            rows_parts, gix_parts, val_parts = [], [], []
            for bag in cfg.feature_bags:
                row_ptr, key_ids, values = bags[bag]
                if not len(key_ids):
                    continue
                gix = table[key_ids]
                keep = gix >= 0
                ent_rows = np.repeat(
                    np.arange(m, dtype=np.int64), np.diff(row_ptr)
                )
                rows_parts.append(ent_rows[keep])
                gix_parts.append(gix[keep])
                val_parts.append(values[keep])
            if rows_parts:
                r = np.concatenate(rows_parts)
                g = np.concatenate(gix_parts)
                v = np.concatenate(val_parts)
                # stable: equal rows keep bag-concat order, matching the
                # record-at-a-time builder's per-row bag traversal
                order = np.argsort(r, kind="stable")
                r, g, v = r[order], g[order], v[order]
            else:
                r = np.zeros(0, np.int64)
                g = np.zeros(0, np.int64)
                v = np.zeros(0, np.float32)
            counts[row0:row0 + m] = np.bincount(r, minlength=m)
            per_file.append((r + row0, g, v))
            row0 += m
        if icept >= 0:
            counts[:n] += 1  # intercept slot per real row
        k_max = int(counts.max()) if counts.size else 1
        k = _padded_width(k_max, pad_nnz_to)
        indices = np.zeros((n_pad, k), np.int32)
        values_arr = np.zeros((n_pad, k), np.float32)
        for r, g, v in per_file:
            if not len(r):
                continue
            # within-row positions: entries are row-sorted, so positions
            # are arange minus each row's start offset
            starts = np.searchsorted(r, r)  # first occurrence index per entry
            intra = np.arange(len(r)) - starts
            flat = r * k + intra
            indices.flat[flat] = g
            values_arr.flat[flat] = v
        if icept >= 0:
            rows_real = np.arange(n, dtype=np.int64)
            flat_i = rows_real * k + (counts[:n] - 1)
            indices.flat[flat_i] = icept
            values_arr.flat[flat_i] = 1.0
        shards[cfg.shard_id] = _shard_data(indices, values_arr, imap, icept)

    entity_indexes, entity_codes = _build_entity_tables(
        random_effect_types, raw_entity, n_pad
    )

    return GameDataset(
        uids=uids,
        labels=labels,
        offsets=offsets,
        weights=weights,
        shards=shards,
        entity_codes=entity_codes,
        entity_indexes=entity_indexes,
        num_real_rows=n,
    )
