"""Unified (grid × entity) GAME training: one program, four axes.

The pod path (game/pod.py) trains ONE entity-sharded GAME model; a
λ-grid sweep over it runs G sequential pod CD loops — G dispatches per
block per iteration, G all_to_alls per exchange, G host readbacks. This
module generalizes every pod currency by one leading grid axis so the
WHOLE sweep is one shard_mapped program family on the
``parallel/unified_mesh.py`` (grid, entity) mesh:

- :class:`GridShardedREBank` — the pod ``[N·E_loc, d]`` bank becomes
  ``[G_pad, N·E_loc, d]`` sharded ``P(grid, entity)``: member g's bank
  rows live on grid row ``g // G_loc``, entity-hash-sharded exactly
  like the pod layout (same ownership rule, same padding semantics).
- Grid programs — the pod update/score/route-in programs with a
  ``vmap`` over the member axis INSIDE the shard_map body: the solver
  cores run batched under the masked ``lax.while_loop`` (a converged
  λ's rows freeze bit-stable while stragglers run on), the tile/block
  schedule is walked ONCE per grid, and each residual exchange is ONE
  ``all_to_all`` on ``[G_loc, n_dev, cap]`` blocks (``split_axis=1``)
  — the pod exchange amortized over the grid axis.
- :class:`UnifiedGridREProblem` — PodRandomEffectProblem's twin over
  the grid bank; reuses the UNCHANGED :class:`~photon_ml_tpu.game.pod.
  _PodView` (router tables, scoring slots and solver blocks are
  λ-independent, so one view serves every member).
- :func:`run_game_grid` — the unified coordinate-descent trainer: a
  G-member λ-grid over (fixed effect + entity-sharded random effect)
  with the exact CD residual algebra of game/coordinate_descent.py,
  one batched readback per CD iteration and zero re-lowerings after
  the first (tests/test_unified_mesh.py pins both).

Scope bounds (documented, not silent): the fixed effect runs the
replicated/grid-batched solve (sparse scatter objective) with its
coefficient bank replicated — the feature-sharded FE sweep stays on
the (data, model) mesh family — and per-member variance banks are not
computed by the unified RE update (run the pod variance pass on a
member's bank after unpacking when needed).
"""

from __future__ import annotations

import weakref
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu.game.pod import (
    EntityShardSpec,
    PodRandomEffectModel,
    ShardedREBank,
    _N_REASONS,
    _PodView,
    _bounded_put,
    _cached_program,
    _donate_args,
    _mesh_key,
    per_device_bytes,
)
from photon_ml_tpu.game.random_effect import RandomEffectTracker
from photon_ml_tpu.optim.common import CONVERGENCE_REASON_NAMES
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.parallel.mesh import ENTITY_AXIS, GRID_AXIS
from photon_ml_tpu.parallel.unified_mesh import MeshPlan

Array = jnp.ndarray

__all__ = [
    "GridShardedREBank",
    "UnifiedGridREProblem",
    "UnifiedGridGameResult",
    "run_game_grid",
]


def _grid_entity_sharding(mesh) -> NamedSharding:
    return NamedSharding(mesh, P(GRID_AXIS, ENTITY_AXIS))


# Grid-bank builders keyed by (mesh, shape): the jit out_shardings
# create/re-shard banks ON DEVICE — no [G, E, d] host array on any
# training path (PL012 discipline; the checkpoint plane materializes
# member views only inside its declared scopes).
_GRID_ZEROS_CACHE: dict = {}
_MEMBER_SLICE_CACHE: dict = {}
_RESHARD_CACHE: dict = {}


def _zeros_grid_sharded(mesh, g_pad: int, rows: int, d: int) -> Array:
    key = (_mesh_key(mesh), g_pad, rows, d)
    fn = _GRID_ZEROS_CACHE.get(key)
    if fn is None:

        def _make(g=g_pad, rows=rows, d=d):
            return jnp.zeros((g, rows, d), jnp.float32)

        fn = _bounded_put(
            _GRID_ZEROS_CACHE, key,
            # photon: sharding(axes=[grid,entity], out=[grid+entity])
            jax.jit(_make, out_shardings=_grid_entity_sharding(mesh)),
        )
    return fn()


def _member_slice(mesh):
    """(data, g) -> member g's [rows, d] bank, entity-sharded (a 1-D
    P(entity) spec on the 2-D mesh replicates over the grid rows, so
    the slice is immediately usable by every pod program)."""
    key = _mesh_key(mesh)
    fn = _MEMBER_SLICE_CACHE.get(key)
    if fn is None:

        def _take(data, g):
            return jnp.take(data, g, axis=0)

        fn = _bounded_put(
            _MEMBER_SLICE_CACHE, key,
            # photon: sharding(axes=[grid,entity], in=[grid+entity,r], out=[entity])
            jax.jit(_take, out_shardings=NamedSharding(mesh, P(ENTITY_AXIS))),
        )
    return fn


def _reshard_grid(mesh):
    """Identity jit whose out_shardings re-shard a grid bank onto
    P(grid, entity) — the checkpoint-restore seam (device-side
    re-shard; the host never holds the sharded layout)."""
    key = _mesh_key(mesh)
    fn = _RESHARD_CACHE.get(key)
    if fn is None:

        def _ident(a):
            return a

        fn = _bounded_put(
            _RESHARD_CACHE, key,
            # photon: sharding(axes=[grid,entity], in=[r], out=[grid+entity])
            jax.jit(_ident, out_shardings=_grid_entity_sharding(mesh)),
        )
    return fn


class GridShardedREBank:
    """A λ-grid of entity-sharded random-effect banks as ONE array:
    ``data`` is ``[G_pad, n_shards * E_loc, d]`` sharded
    ``P(grid, entity)``. Member g uses the SAME hash placement as the
    pod bank (entity ``e`` at row ``(e % n) * E_loc + e // n``);
    padding members (index >= ``grid_size``) run inert duplicates of
    the last λ and are dropped at unpack."""

    __slots__ = ("mesh", "spec", "grid_size", "data")

    def __init__(self, mesh, spec: EntityShardSpec, grid_size: int,
                 data: Array):
        self.mesh = mesh
        self.spec = spec
        self.grid_size = int(grid_size)
        self.data = data

    @property
    def dim(self) -> int:
        return int(self.data.shape[2])

    @property
    def grid_padded(self) -> int:
        return int(self.data.shape[0])

    @classmethod
    def zeros(cls, mesh, spec: EntityShardSpec, grid_size: int,
              grid_padded: int, dim: int) -> "GridShardedREBank":
        return cls(
            mesh, spec, grid_size,
            _zeros_grid_sharded(mesh, grid_padded, spec.bank_rows, dim),
        )

    @classmethod
    def from_member_globals(
        cls, mesh, spec: EntityShardSpec, grid_size: int, banks,
    ) -> "GridShardedREBank":
        """[E, d] entity-code-ordered member banks -> the grid-sharded
        layout. The hash gather runs on device and the single
        out_shardings re-shard places it — the restore path's twin of
        ``ShardedREBank.from_global`` (list shorter than G_pad is
        padded by repeating the last member)."""
        banks = [jnp.asarray(b, jnp.float32) for b in banks]
        if not banks:
            raise ValueError("empty member bank list")
        rows = np.arange(spec.bank_rows, dtype=np.int64)
        e = (rows % spec.rows_per_shard) * spec.num_shards + (
            rows // spec.rows_per_shard
        )
        valid = e < spec.num_entities
        safe = np.minimum(e, max(spec.num_entities - 1, 0))
        stacked = jnp.stack(banks)
        gathered = jnp.take(stacked, jnp.asarray(safe, jnp.int32), axis=1)
        gathered = jnp.where(jnp.asarray(valid)[None, :, None], gathered, 0.0)
        return cls(mesh, spec, grid_size, _reshard_grid(mesh)(gathered))

    def member(self, g: int) -> ShardedREBank:
        """Member g's bank as a pod ShardedREBank (device-side slice,
        still entity-sharded — export/validation scoring reuse every
        pod consumer unchanged)."""
        data = _member_slice(self.mesh)(self.data, jnp.int32(g))
        return ShardedREBank(self.mesh, self.spec, data)

    # photon: sharding(export)
    def member_global(self, g: int) -> Array:
        """Replicated [E, d] view of member g (export / checkpoint /
        parity oracles only — the CD hot path never calls this)."""
        return self.member(g).to_global()

    # photon: sharding(export)
    def snapshot(self) -> np.ndarray:
        """Host copy of the RAW [G_pad, rows, d] sharded layout for the
        checkpoint plane (GridCheckpointer.save_grid_bank). The rows
        stay in hash placement — no per-member [E, d] gather in either
        direction; :meth:`restore` re-shards device-side."""
        return np.asarray(self.data)

    def layout(self) -> Dict[str, int]:
        """Marker metadata guarding a snapshot against restore onto a
        different mesh/shard layout (the row hash placement depends on
        the entity-shard count)."""
        return {
            "grid_size": self.grid_size,
            "grid_padded": self.grid_padded,
            "num_shards": self.spec.num_shards,
            "num_entities": self.spec.num_entities,
            "dim": self.dim,
        }

    @classmethod
    def restore(cls, mesh, spec: EntityShardSpec, grid_size: int,
                data) -> "GridShardedREBank":
        """Checkpoint restore: place a :meth:`snapshot` array back onto
        ``P(grid, entity)`` through the cached identity jit's
        ``out_shardings`` — the re-shard happens device-side and the
        host never reorders rows out of hash placement."""
        arr = jnp.asarray(data, jnp.float32)
        if arr.ndim != 3 or int(arr.shape[1]) != spec.bank_rows:
            raise ValueError(
                f"snapshot shape {tuple(arr.shape)} does not match the "
                f"{spec.num_shards}-shard bank layout "
                f"({spec.bank_rows} rows)"
            )
        return cls(mesh, spec, grid_size, _reshard_grid(mesh)(arr))

    def per_device_bytes(self) -> int:
        return per_device_bytes(self.data)


# ---------------------------------------------------------------------------
# grid-batched sharded programs
# ---------------------------------------------------------------------------
#
# The pod programs with ONE extra leading axis: member banks/slots ride
# P(grid, entity), the per-entity block data stays P(entity) (shared by
# every member — it is λ-independent), and the member vmap runs INSIDE
# the shard_map body so each device solves only (its grid row × its
# entity shard). Collectives: entity-axis psum/pmax AFTER the member
# vmap; ONE all_to_all per exchange on [G_loc, n_dev, cap] blocks.


def _build_grid_route_in(mesh, n_dev: int, cap: int):
    """Hop 1 for the whole grid: [G_pad, n_pad] per-member residual
    rows -> [G_pad, n_dev * cap] routed slot banks. The slot scatter is
    member-batched; the exchange is ONE all_to_all with the member axis
    riding along (``split_axis=1`` on the [G_loc, n_dev, cap] blocks)."""
    num_slots = n_dev * cap

    # photon: sharding(axes=[grid,entity], in=[grid+entity,entity], out=[grid+entity])
    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(GRID_AXIS, ENTITY_AXIS), P(ENTITY_AXIS)),
        out_specs=P(GRID_AXIS, ENTITY_AXIS),
        check_vma=False,
    )
    def route_in(vals, pos):
        def one(v):
            buf = jnp.zeros((num_slots + 1,), v.dtype)
            return buf.at[pos].set(v, mode="drop")[:-1]

        slabs = jax.vmap(one)(vals)  # [G_loc, num_slots]
        blocks = slabs.reshape(slabs.shape[0], n_dev, cap)
        routed = lax.all_to_all(
            blocks, ENTITY_AXIS, split_axis=1, concat_axis=1, tiled=False
        )
        return routed.reshape(slabs.shape[0], -1)

    return route_in


def _build_grid_update_program(solvers, kind: str, mesh):
    """Grid-batched sharded bucket update: each device runs the vmapped
    per-entity solver for ITS G_loc members on ITS entity shard's block
    rows — G·E solves in one dispatch. Per-member (l1, l2) ride [G_pad]
    vectors sharded over the grid axis; tracker stats come back as
    per-member vectors (entity-psum'd after the member vmap). The bank
    is donated off-CPU like the pod program."""
    core = getattr(solvers, kind)

    # photon: sharding(axes=[grid,entity], in=[grid+entity,entity,entity,entity,entity,entity,entity,entity,grid+entity,grid,grid], out=[grid+entity,grid,grid,grid], donates=[0])
    @partial(jax.jit, donate_argnums=_donate_args())
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(GRID_AXIS, ENTITY_AXIS), P(ENTITY_AXIS), P(ENTITY_AXIS),
            P(ENTITY_AXIS), P(ENTITY_AXIS), P(ENTITY_AXIS), P(ENTITY_AXIS),
            P(ENTITY_AXIS), P(GRID_AXIS, ENTITY_AXIS), P(GRID_AXIS),
            P(GRID_AXIS),
        ),
        out_specs=(
            P(GRID_AXIS, ENTITY_AXIS), P(GRID_AXIS), P(GRID_AXIS),
            P(GRID_AXIS),
        ),
        check_vma=False,
    )
    def fused(bank_g, lrow, valid, ix, v, lab, w, offslot, slots, l1, l2):
        e_loc = bank_g.shape[1]
        safe = jnp.minimum(lrow, e_loc - 1)
        idx = jnp.where(valid, lrow, e_loc)  # pad lanes drop out of bounds

        def one(bank_l, slots_m, l1_m, l2_m):
            off = jnp.where(
                offslot >= 0, jnp.take(slots_m, jnp.maximum(offslot, 0)), 0.0
            )
            sl = jnp.where(
                valid[:, None], jnp.take(bank_l, safe, axis=0), 0.0
            )
            new_sl, iters, reasons = core(sl, ix, v, lab, off, w, l1_m, l2_m)
            bank_l = bank_l.at[idx].set(new_sl, mode="drop")
            vi = jnp.where(valid, iters, 0)
            r = jnp.where(valid, reasons, _N_REASONS)
            # equality-sum instead of bincount: batches cleanly under
            # the member vmap (bincount's gather-scatter does not)
            counts = jnp.sum(
                (r[:, None] == jnp.arange(_N_REASONS + 1)[None, :])
                .astype(jnp.int32),
                axis=0,
            )[:_N_REASONS]
            return bank_l, jnp.sum(vi), jnp.max(vi), counts

        bank_g, it_sum, it_max, counts = jax.vmap(one)(bank_g, slots, l1, l2)
        it_sum = lax.psum(it_sum, ENTITY_AXIS)
        it_max = lax.pmax(it_max, ENTITY_AXIS)
        counts = lax.psum(counts, ENTITY_AXIS)
        return bank_g, it_sum, it_max, counts

    return fused


def _build_grid_score_program(mesh, n_dev: int, cap: int):
    """Hop 2 for the whole grid, fused with member-batched local
    scoring: each owner scores its slots against each of its G_loc
    member bank slices, then ONE reverse all_to_all lands every
    member's scores back at the sending rows — [G_pad, n_pad] out."""
    num_slots = n_dev * cap

    # photon: sharding(axes=[grid,entity], in=[grid+entity,entity,entity,entity,entity,entity], out=[grid+entity])
    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(GRID_AXIS, ENTITY_AXIS), P(ENTITY_AXIS), P(ENTITY_AXIS),
            P(ENTITY_AXIS), P(ENTITY_AXIS), P(ENTITY_AXIS),
        ),
        out_specs=P(GRID_AXIS, ENTITY_AXIS),
        check_vma=False,
    )
    def score(bank_g, slot_lrow, slot_ix, slot_v, slot_valid, send_pos):
        e_loc = bank_g.shape[1]
        safe = jnp.minimum(slot_lrow, e_loc - 1)

        def one(bank_l):
            w_rows = jnp.take(bank_l, safe, axis=0)
            s = jnp.sum(
                slot_v * jnp.take_along_axis(w_rows, slot_ix, axis=1),
                axis=-1,
            )
            return jnp.where(slot_valid, s, 0.0)

        s = jax.vmap(one)(bank_g)  # [G_loc, num_slots]
        blocks = s.reshape(s.shape[0], n_dev, cap)
        back = lax.all_to_all(
            blocks, ENTITY_AXIS, split_axis=1, concat_axis=1, tiled=False
        ).reshape(s.shape[0], -1)
        safe_p = jnp.minimum(send_pos, num_slots - 1)
        return jnp.where(
            send_pos[None, :] < num_slots, back[:, safe_p], 0.0
        )

    return score


# ---------------------------------------------------------------------------
# the grid problem
# ---------------------------------------------------------------------------


class UnifiedGridREProblem:
    """λ-grid × entity-sharded twin of PodRandomEffectProblem: ONE
    [G_pad, N·E_loc, d] bank, per-member (l1, l2) from
    ``regularization.split(reg_weights[g])``, the pod _PodView reused
    verbatim (its router tables, scoring slots and solver blocks are
    member-independent), and every update/score/exchange grid-batched.

    ``base`` must carry ``mesh=None`` like the pod problem — placement
    is owned by the unified mesh plan."""

    def __init__(self, base, plan: MeshPlan,
                 reg_weights: Sequence[float]):
        if base.mesh is not None:
            raise ValueError(
                "UnifiedGridREProblem wraps a mesh-less base problem; "
                "placement is owned by the unified mesh plan"
            )
        mesh = plan.mesh
        names = tuple(getattr(mesh, "axis_names", ()))
        if GRID_AXIS not in names or ENTITY_AXIS not in names:
            raise ValueError(
                f"unified mesh must carry ({GRID_AXIS!r}, {ENTITY_AXIS!r}) "
                f"axes, got {names!r}"
            )
        weights = [float(w) for w in reg_weights]
        if len(weights) != plan.grid_size:
            raise ValueError(
                f"{len(weights)} reg weights for a grid of "
                f"{plan.grid_size} members"
            )
        self.base = base
        self.plan = plan
        self.mesh = mesh
        self.num_shards = int(mesh.shape[ENTITY_AXIS])
        self.reg_weights = weights
        padded = plan.pad_members(weights)
        splits = [base.regularization.split(w) for w in padded]
        grid_sharding = NamedSharding(mesh, P(GRID_AXIS))
        self._l1 = jax.device_put(
            jnp.asarray([s[0] for s in splits], jnp.float32), grid_sharding
        )
        self._l2 = jax.device_put(
            jnp.asarray([s[1] for s in splits], jnp.float32), grid_sharding
        )
        self._views: Dict[int, tuple] = {}

    def spec_for(self, dataset) -> EntityShardSpec:
        return EntityShardSpec(self.num_shards, dataset.num_entities)

    def init_bank(self, dataset) -> GridShardedREBank:
        return GridShardedREBank.zeros(
            self.mesh, self.spec_for(dataset), self.plan.grid_size,
            self.plan.grid_padded, dataset.local_dim,
        )

    def pod_view(self, dataset) -> _PodView:  # photon: entropy(id-keyed device-view memo; weakref-pinned, never serialized)
        key = id(dataset)
        hit = self._views.get(key)
        if hit is not None and hit[0]() is dataset:
            return hit[1]
        view = _PodView(self.mesh, dataset, self.base, axis=ENTITY_AXIS)
        cache = self._views
        ref = weakref.ref(dataset, lambda _, k=key, c=cache: c.pop(k, None))
        cache[key] = (ref, view)
        return view

    def prepare(self, dataset) -> None:
        self.pod_view(dataset)

    def route_in(self, view: _PodView, residual_bank: Array) -> Array:
        """[G_pad, n] per-member residual/offset rows -> routed
        [G_pad, n_dev * cap] slot banks, ONE all_to_all for the grid."""
        router = view.router
        off = jnp.asarray(residual_bank, jnp.float32)
        if off.shape[1] != router.num_rows_padded:
            off = jnp.concatenate(
                [
                    off,
                    jnp.zeros(
                        (off.shape[0],
                         router.num_rows_padded - off.shape[1]),
                        jnp.float32,
                    ),
                ],
                axis=1,
            )
        fn = _cached_program(
            ("grid_route_in", _mesh_key(self.mesh), router.n_dev,
             router.cap),
            lambda: _build_grid_route_in(
                self.mesh, router.n_dev, router.cap
            ),
        )
        return fn(off, router._send_pos)

    def update_bank(
        self,
        bank: GridShardedREBank,
        dataset,
        residual_bank: Array,
        defer_tracker: bool = False,
    ):
        """One grid-batched cross-replica bank update.
        ``residual_bank`` is the [G_pad(, or G), n] per-member
        offsets-plus-residual rows. Returns ``(new_bank, trackers)``
        where trackers is a per-member list of RandomEffectTracker
        (or, with ``defer_tracker``, a Deferred resolving to it for
        the CD loop's one batched readback)."""
        view = self.pod_view(dataset)
        if residual_bank.shape[0] != self.plan.grid_padded:
            raise ValueError(
                f"residual bank carries {residual_bank.shape[0]} members, "
                f"expected the padded grid {self.plan.grid_padded}"
            )
        slots = self.route_in(view, residual_bank)  # hop 1, whole grid
        solvers = self.base._solvers
        data = bank.data
        if _donate_args():
            # defensive copy so the fused updates can DONATE the bank
            # shards while the caller's reference stays valid
            data = jnp.array(data, copy=True)
        n_reals: List[int] = []
        stat_vecs: List[Array] = []
        for blk in view.blocks:
            fused = _cached_program(
                ("grid_update", _mesh_key(self.mesh), blk.kind),
                lambda kind=blk.kind: _build_grid_update_program(
                    solvers, kind, self.mesh
                ),
            )
            data, it_sum, it_max, counts = fused(
                data, blk.lrow, blk.valid, blk.ix, blk.v, blk.lab, blk.w,
                blk.offslot, slots, self._l1, self._l2,
            )
            n_reals.append(blk.num_real)
            # [G_pad, 2 + R] per block: (iter_sum, iter_max, counts...)
            stat_vecs.append(
                jnp.concatenate(
                    [it_sum[:, None], it_max[:, None], counts], axis=1
                )
            )
        new_bank = GridShardedREBank(
            self.mesh, bank.spec, bank.grid_size, data
        )
        if not stat_vecs:
            trackers = [
                RandomEffectTracker(0, 0.0, 0, {})
                for _ in range(bank.grid_size)
            ]
            return new_bank, trackers

        total = max(sum(n_reals), 1)
        g = bank.grid_size

        def _finalize(all_stats, total=total, g=g):
            # all_stats [B, G_pad, 2 + R]; padding members dropped
            out = []
            for m in range(g):
                s = all_stats[:, m, :]
                count_vec = s[:, 2:].sum(axis=0)
                counts_dict: Dict[str, int] = {
                    CONVERGENCE_REASON_NAMES.get(code, "?"): int(cnt)
                    for code, cnt in enumerate(count_vec)
                    if cnt
                }
                out.append(RandomEffectTracker(
                    num_entities=total,
                    iterations_mean=float(s[:, 0].sum()) / total,
                    iterations_max=int(s[:, 1].max()),
                    reason_counts=counts_dict,
                ))
            return out

        deferred = overlap.Deferred(jnp.stack(stat_vecs), _finalize)
        if defer_tracker and not deferred.done:
            return new_bank, deferred
        return new_bank, deferred.result()

    def score(self, bank: GridShardedREBank, dataset) -> Array:
        """[G_pad, n_pad] row-aligned scores for every member at once
        (rows beyond the real row count are 0, like the pod path)."""
        view = self.pod_view(dataset)
        fn = _cached_program(
            ("grid_score", _mesh_key(self.mesh), view.n_dev,
             view.router.cap),
            lambda: _build_grid_score_program(
                self.mesh, view.n_dev, view.router.cap
            ),
        )
        return fn(
            bank.data, view.slot_lrow, view.slot_ix, view.slot_v,
            view.slot_valid, view.router._send_pos,
        )

    def regularization_term_device(self, bank: GridShardedREBank) -> Array:
        """[G_pad] per-member reg terms over the sharded grid bank —
        one device vector joining the CD iteration's batched readback."""
        data = bank.data
        term = 0.5 * self._l2 * jnp.sum(data * data, axis=(1, 2))
        return term + self._l1 * jnp.sum(jnp.abs(data), axis=(1, 2))


# ---------------------------------------------------------------------------
# the unified coordinate-descent trainer
# ---------------------------------------------------------------------------


@jax.jit
def _fe_grid_scores(w_bank: Array, batch) -> Array:
    """[G_pad, n] scores of every member's FE coefficients (module-level
    jit: one lowering serves every run_game_grid call of this shape —
    the 0-relowering contract the tests pin)."""
    from photon_ml_tpu.models.glm import compute_scores

    return jax.vmap(lambda w: compute_scores(w, batch))(w_bank)


@partial(jax.jit, static_argnames=("loss", "fe_l1", "fe_l2"))
def _grid_objective(
    total_bank, fe_bank, re_reg_vec, base_off, labels, weights,
    *, loss, fe_l1, fe_l2,
) -> Array:
    """[G_pad] per-member CD objectives: weighted loss over the summed
    scores plus the FE reg term plus the (device-resident) RE reg
    vector — the grid twin of CoordinateDescent._objective_deferred."""
    z = total_bank + base_off[None, :]
    val = jnp.sum(
        weights[None, :] * loss.value(z, labels[None, :]), axis=1
    )
    fe_reg = 0.5 * fe_l2 * jnp.sum(fe_bank * fe_bank, axis=1)
    if fe_l1:
        fe_reg = fe_reg + fe_l1 * jnp.sum(jnp.abs(fe_bank), axis=1)
    return val + fe_reg + re_reg_vec


@dataclass
class UnifiedGridGameResult:
    """Per-member outcome of one unified grid CD run. ``fe_banks`` is
    the final [G_pad, d] fixed-effect coefficient bank (device);
    ``re_bank`` the final grid-sharded RE bank; histories/trackers are
    aligned with ``re_reg_weights`` (padding members dropped)."""

    plan: MeshPlan
    re_reg_weights: List[float]
    fe_banks: Array
    re_bank: GridShardedREBank
    objective_history: List[List[float]] = field(default_factory=list)
    fe_trackers: List[object] = field(default_factory=list)
    re_trackers: List[List[RandomEffectTracker]] = field(default_factory=list)

    def fe_means(self, g: int) -> Array:
        return self.fe_banks[g]

    def re_member(self, g: int) -> ShardedREBank:
        return self.re_bank.member(g)

    def re_model(self, g: int, re_dataset) -> PodRandomEffectModel:
        return PodRandomEffectModel(
            self.re_bank.member(g),
            re_dataset,
            re_dataset.config.random_effect_type,
            re_dataset.config.feature_shard_id,
        )


def run_game_grid(
    plan: MeshPlan,
    dataset,
    re_dataset,
    fe_problem,
    re_problem,
    re_reg_weights: Sequence[float],
    *,
    feature_shard_id: str,
    fe_reg_weight: float = 0.0,
    num_iterations: int = 2,
    down_sampling_rate: float = 1.0,
    sampler_seed: int = 0,
) -> UnifiedGridGameResult:
    """λ-grid GAME coordinate descent as ONE program family.

    Runs the exact residual algebra of
    :class:`~photon_ml_tpu.game.coordinate_descent.CoordinateDescent`
    over (fixed effect, entity-sharded random effect) for EVERY member
    of ``re_reg_weights`` simultaneously: the FE solves batch through
    ``GLMOptimizationProblem.run_grid`` with a per-member offsets bank,
    the RE updates/scores run the grid-sharded pod programs, and each
    CD iteration issues ONE batched readback (the [G] objective vector
    plus the RE tracker stats) — instead of G sequential pod CD loops.

    Per-member semantics match the sequential pod loop: same warm
    starts (each member from its own previous coefficients), same
    down-sampling draw (λ-independent, one draw shared by the grid),
    same objective accounting (loss + FE reg + RE reg per member).
    """
    from photon_ml_tpu.data.sampler import down_sample
    from photon_ml_tpu.parallel.mesh import ensure_data_sharded

    mesh = plan.mesh
    G = plan.grid_size
    g_pad = plan.grid_padded
    uni = UnifiedGridREProblem(re_problem, plan, re_reg_weights)
    view = uni.pod_view(re_dataset)
    re_bank = uni.init_bank(re_dataset)

    batch = dataset.batch_for_shard(feature_shard_id)
    if down_sampling_rate < 1.0:
        # one λ-independent draw, same PRNG stream as the sequential
        # coordinate (weights-only rewrite; the layout is untouched)
        batch = down_sample(
            jax.random.PRNGKey(sampler_seed), batch, down_sampling_rate,
            fe_problem.task,
        )
    batch = ensure_data_sharded(batch, mesh, ENTITY_AXIS)
    n_pad = int(batch.labels.shape[0])
    if n_pad != view.router.num_rows_padded:
        raise ValueError(
            f"row padding mismatch: batch {n_pad} vs router "
            f"{view.router.num_rows_padded}"
        )
    base_off = jnp.asarray(batch.offsets, jnp.float32)
    fe_weights = [float(fe_reg_weight)] * g_pad
    fe_l1, fe_l2 = fe_problem.regularization.split(float(fe_reg_weight))
    loss = fe_problem.objective.loss

    fe_bank = jnp.zeros((g_pad, fe_problem.objective.dim), jnp.float32)
    fe_scores = jnp.zeros((g_pad, n_pad), jnp.float32)
    re_scores = jnp.zeros((g_pad, n_pad), jnp.float32)

    result = UnifiedGridGameResult(
        plan=plan,
        re_reg_weights=[float(w) for w in re_reg_weights],
        fe_banks=fe_bank,
        re_bank=re_bank,
    )
    fe_result = None
    for _ in range(int(num_iterations)):
        total = fe_scores + re_scores
        # -- fixed effect: residual = total - own; one batched solve
        residual = total - fe_scores
        _, fe_result = fe_problem.run_grid(
            batch, fe_weights, initial=fe_bank, mesh=mesh,
            offsets_bank=base_off[None, :] + residual,
        )
        fe_bank = fe_result.coefficients
        fe_scores = _fe_grid_scores(fe_bank, batch)
        total = residual + fe_scores
        # -- random effect: grid-sharded update + fused score exchange
        residual = total - re_scores
        re_bank, tracker_d = uni.update_bank(
            re_bank, re_dataset, base_off[None, :] + residual,
            defer_tracker=True,
        )
        re_scores = uni.score(re_bank, re_dataset)
        total = residual + re_scores
        # -- one batched readback: [G] objective + RE tracker stats
        obj_vec = _grid_objective(
            total, fe_bank, uni.regularization_term_device(re_bank),
            base_off, batch.labels, batch.weights,
            loss=loss, fe_l1=fe_l1, fe_l2=fe_l2,
        )
        obj_d = overlap.Deferred(
            obj_vec, lambda a, g=G: [float(x) for x in a[:g]]
        )
        fetch = [obj_d]
        if hasattr(tracker_d, "result"):
            fetch.append(tracker_d)
        overlap.fetch_all(fetch)
        result.objective_history.append(obj_d.result())
        result.fe_trackers.append(fe_result)
        result.re_trackers.append(
            tracker_d.result() if hasattr(tracker_d, "result")
            else tracker_d
        )
    result.fe_banks = fe_bank
    result.re_bank = re_bank
    return result
