"""Pod-scale GAME: entity-sharded random-effect banks with all-to-all
residual routing and cross-replica sharded updates.

The replicated path (game/random_effect.py) holds every random-effect
coordinate's [E, d] bank — plus its variances and tracker inputs — ON
EVERY device, so coefficient capacity is capped by one host no matter
how many devices the mesh has. Photon ML's headline claim is "hundreds
of billions of coefficients" (PAPER.md); that only works if memory AND
per-step work scale with the mesh. This module is that scaling story:

- **Hash placement** (:class:`EntityShardSpec`): entity ``e`` lives on
  shard ``e % n_shards`` at local bank row ``e // n_shards`` — the
  LongHashPartitioner analog, the SAME ownership rule as
  ``parallel.shuffle.entity_all_to_all``, and stable as E grows (new
  entities never re-home old ones, which the serving shard loader and
  incremental retraining both rely on).
- **Sharded banks** (:class:`ShardedREBank`): one ``[n * E_loc, d]``
  ``jax.Array`` sharded over the ``entity`` mesh axis; each device
  holds only its ``[E_loc, d]`` shard. Variance banks shard the same
  way, and the tracker never materializes anything [E]-sized — its
  stats are psum-reduced scalars.
- **Sharded updates** (:class:`PodRandomEffectProblem`): every bucket
  solve runs under ``shard_map`` — each replica computes ONLY its own
  entities' LBFGS/TRON/Newton steps against its local bank shard (the
  "Automatic Cross-Replica Sharding of Weight Update" recipe,
  PAPERS.md: replicas own disjoint slices of the update), with the CD
  objective's tracker reductions riding psum through the fused program.
- **Two-hop residual routing** (:class:`~photon_ml_tpu.game.
  residual_routing.PodResidualRouter`): per CD iteration ONE
  all_to_all carries each row's residual to its entity's owner shard,
  the owner scores/solves locally, and the reverse all_to_all carries
  the new scores back — two floats of traffic per row, zero host-side
  gathers (the tests count the ``overlap.device_get`` seam).

The streamed path (game/streaming.py) reuses the same fused sharded
segment solve: each ``SpilledREBuckets`` segment is split by the same
entity hash so a device only ever stages its own shard of a segment.

Weak-scaling contract (pinned by tests/test_pod_game.py and bench.py's
``12_pod_game``): at N shards, per-device bank + optimizer-state bytes
are ~1/N of the replicated path for the same model, with CD parity
inside the established fp32 envelopes.
"""

from __future__ import annotations

import weakref
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu import ownership
from photon_ml_tpu.game.model import RandomEffectModel
from photon_ml_tpu.game.random_effect import (
    LazyRandomEffectTracker,
    RandomEffectOptimizationProblem,
    RandomEffectTracker,
)
from photon_ml_tpu.game.random_effect_data import RandomEffectDataset
from photon_ml_tpu.game.residual_routing import PodResidualRouter
from photon_ml_tpu.optim.common import CONVERGENCE_REASON_NAMES
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.parallel.mesh import ENTITY_AXIS

Array = jnp.ndarray

__all__ = [
    "EntityShardSpec",
    "ShardedREBank",
    "PodRandomEffectProblem",
    "PodRandomEffectModel",
    "entity_shard_of",
    "per_device_bytes",
]


def entity_shard_of(codes, num_shards: int):
    """The one placement rule, shared by training, streaming, the
    serving shard loader AND the scatter/gather router: entity code ->
    owning shard. Delegates to :mod:`photon_ml_tpu.ownership` so no
    plane can drift from the others."""
    return ownership.owner_of(np.asarray(codes), int(num_shards))


@dataclass(frozen=True)
class EntityShardSpec:
    """Static placement of an entity axis over ``num_shards`` devices."""

    num_shards: int
    num_entities: int

    @property
    def rows_per_shard(self) -> int:
        """Local bank rows per shard (>= 1 so empty banks stay valid)."""
        return ownership.rows_per_shard(self.num_entities, self.num_shards)

    @property
    def bank_rows(self) -> int:
        return self.num_shards * self.rows_per_shard

    def local_of(self, codes):
        return ownership.local_row_of(np.asarray(codes), self.num_shards)

    def sharded_row_of(self, codes):
        """Entity code -> row in the sharded [n * E_loc, d] layout."""
        return ownership.sharded_row_of(
            np.asarray(codes), self.num_shards, self.rows_per_shard
        )


def _mesh_key(mesh):
    return (
        tuple(mesh.axis_names),
        tuple(int(n) for n in mesh.devices.shape),
        tuple(d.id for d in mesh.devices.flat),
    )


def _entity_sharding(mesh):
    return NamedSharding(mesh, P(mesh.axis_names[0]))


# Zero-bank builders keyed by (mesh, rows, d): jit with out_shardings
# creates the sharded zeros ON DEVICE — no [E, d] host array is ever
# materialized, which is the whole point at pod scale.
_ZEROS_CACHE: dict = {}
# One shape-polymorphic replicate program per mesh (all-gather a sharded
# value to every device — model export / score hand-off, off hot path).
_REPL_CACHE: dict = {}
_POD_CACHE_MAX = 32


def _bounded_put(cache: dict, key, value):
    while len(cache) >= _POD_CACHE_MAX:
        cache.pop(next(iter(cache)))
    cache[key] = value
    return value


def _zeros_sharded(mesh, rows: int, d: int) -> Array:
    key = (_mesh_key(mesh), rows, d)
    fn = _ZEROS_CACHE.get(key)
    if fn is None:

        def _make(rows=rows, d=d):
            return jnp.zeros((rows, d), jnp.float32)

        fn = _bounded_put(
            _ZEROS_CACHE, key,
            # photon: sharding(axes=[entity], out=[entity])
            jax.jit(_make, out_shardings=_entity_sharding(mesh)),
        )
    return fn()


def _replicate(mesh, value: Array) -> Array:
    key = _mesh_key(mesh)
    fn = _REPL_CACHE.get(key)
    if fn is None:

        def _ident(a):
            return a

        fn = _bounded_put(
            _REPL_CACHE, key,
            # photon: sharding(axes=[entity], in=[entity], out=[r])
            jax.jit(_ident, out_shardings=NamedSharding(mesh, P())),
        )
    return fn(value)


class ShardedREBank:
    """One random-effect coefficient (or variance) bank, hash-sharded
    over the entity mesh axis. ``data`` is a [num_shards * E_loc, d]
    ``jax.Array`` with entity ``e`` at row
    ``(e % n) * E_loc + e // n`` — device ``s`` holds exactly the
    entities it owns, nothing else. Padding rows (local index beyond the
    shard's real entity count) are zeros and inert everywhere (the reg
    term sums them as 0, no solve ever touches them)."""

    __slots__ = ("mesh", "spec", "data")

    def __init__(self, mesh, spec: EntityShardSpec, data: Array):
        self.mesh = mesh
        self.spec = spec
        self.data = data

    @property
    def dim(self) -> int:
        return int(self.data.shape[1])

    @classmethod
    def zeros(cls, mesh, spec: EntityShardSpec, dim: int) -> "ShardedREBank":
        return cls(mesh, spec, _zeros_sharded(mesh, spec.bank_rows, dim))

    @classmethod
    def from_global(cls, mesh, spec: EntityShardSpec, bank) -> "ShardedREBank":
        """[E, d] entity-code-ordered bank -> sharded layout. The gather
        runs on device; only the device_put re-shard moves data."""
        bank = jnp.asarray(bank, jnp.float32)
        rows = np.arange(spec.bank_rows, dtype=np.int64)
        e = (rows % spec.rows_per_shard) * spec.num_shards + (
            rows // spec.rows_per_shard
        )
        valid = e < spec.num_entities
        safe = np.minimum(e, max(spec.num_entities - 1, 0))
        gathered = jnp.take(bank, jnp.asarray(safe, jnp.int32), axis=0)
        gathered = jnp.where(jnp.asarray(valid)[:, None], gathered, 0.0)
        return cls(
            mesh, spec, jax.device_put(gathered, _entity_sharding(mesh))
        )

    def to_global(self) -> Array:
        """Sharded layout -> replicated [E, d] in entity-code order (a
        device-side all-gather; model export / parity checks only — the
        CD hot path never calls this)."""
        rows = self.spec.sharded_row_of(
            np.arange(self.spec.num_entities, dtype=np.int64)
        )
        out = jnp.take(self.data, jnp.asarray(rows, jnp.int32), axis=0)
        return _replicate(self.mesh, out)

    # photon: sharding(export)
    def __array__(self, dtype=None):
        # host materialization is an explicit, counted readback
        host = overlap.device_get(self.to_global())
        return np.asarray(host, dtype) if dtype is not None else np.asarray(host)

    def per_device_bytes(self) -> int:
        return per_device_bytes(self.data)


def per_device_bytes(*values) -> int:
    """Max bytes any single device holds across the given arrays /
    ShardedREBanks — the weak-scaling accounting the tests and bench
    pin (per-device bank + optimizer-state bytes ~flat as total
    coefficients grow with the shard count)."""
    per: Dict[object, int] = {}
    for v in values:
        arr = v.data if isinstance(v, ShardedREBank) else v
        for s in arr.addressable_shards:
            per[s.device] = per.get(s.device, 0) + int(s.data.nbytes)
    return max(per.values()) if per else 0


# ---------------------------------------------------------------------------
# sharded fused programs
# ---------------------------------------------------------------------------
#
# One program object per (mesh, solver kind) — jit re-specializes per
# block shape internally, so every capacity class reuses the same
# wrapper. Gather + solve + scatter + the psum'd tracker reductions run
# in ONE dispatch per class block, mirroring the replicated path's
# _fused programs; no [E]-sized value ever leaves its shard.

_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_MAX = 64

_N_REASONS = max(CONVERGENCE_REASON_NAMES) + 1


def _cached_program(key, build):
    from photon_ml_tpu.utils.memo import get_or_build

    return get_or_build(_PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, build)


def _donate_args():
    from photon_ml_tpu.utils.backend import effective_platform

    return (0,) if effective_platform() != "cpu" else ()


def _build_update_program(solvers, kind: str, mesh, axis: str,
                          with_slots: bool = True):
    """Sharded fused bucket update: each shard gathers ITS entities'
    bank rows, folds the residual into per-sample offsets, runs the
    vmapped per-entity solver on its slice only, scatters the new rows
    back into its local bank shard, and psums the tracker scalars. The
    bank shard is donated off-CPU (in-place scatter, like the
    replicated fused programs).

    ``with_slots``: offsets arrive as routed slot buffers + a static
    slot index per sample (the in-memory two-hop path); False takes a
    direct per-sample offsets block (the streamed-segment path, whose
    residual fold is host-side by the out-of-core contract)."""
    core = getattr(solvers, kind)
    ax = axis
    off_spec = (P(ax), P(ax)) if with_slots else (P(ax),)

    # photon: sharding(axes=[entity], in=?, out=[entity,r,r,r], donates=[0])
    @partial(jax.jit, donate_argnums=_donate_args())
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(ax), P(ax), P(ax), P(ax), P(ax), P(ax), P(ax),
        ) + off_spec + (P(), P()),
        out_specs=(P(ax), P(), P(), P()),
        check_vma=False,
    )
    def fused(bank_l, lrow, valid, ix, v, lab, w, *rest):
        if with_slots:
            offslot, slots, l1, l2 = rest
            off = jnp.where(
                offslot >= 0, jnp.take(slots, jnp.maximum(offslot, 0)), 0.0
            )
        else:
            off, l1, l2 = rest
        e_loc = bank_l.shape[0]
        safe = jnp.minimum(lrow, e_loc - 1)
        sl = jnp.where(valid[:, None], jnp.take(bank_l, safe, axis=0), 0.0)
        new_sl, iters, reasons = core(sl, ix, v, lab, off, w, l1, l2)
        idx = jnp.where(valid, lrow, e_loc)  # pad lanes drop out of bounds
        bank_l = bank_l.at[idx].set(new_sl, mode="drop")
        vi = jnp.where(valid, iters, 0)
        it_sum = lax.psum(jnp.sum(vi), ax)
        it_max = lax.pmax(jnp.max(vi), ax)
        r = jnp.where(valid, reasons, _N_REASONS)  # pad lanes -> extra bin
        counts = lax.psum(
            jnp.bincount(r, length=_N_REASONS + 1)[:_N_REASONS], ax
        )
        return bank_l, it_sum, it_max, counts

    return fused


def _build_variance_program(solvers, mesh, axis: str,
                            with_slots: bool = True):
    """Sharded Hdiag pass at the just-solved rows, writing a sharded
    variance bank — the computeVariances analog with no replicated
    [E, d] anywhere."""
    from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

    hdiag = solvers.hdiag
    ax = axis
    off_spec = (P(ax), P(ax)) if with_slots else (P(ax),)

    # photon: sharding(axes=[entity], in=?, out=[entity], donates=[0])
    @partial(jax.jit, donate_argnums=_donate_args())
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(ax), P(ax), P(ax), P(ax), P(ax), P(ax), P(ax), P(ax),
        ) + off_spec + (P(),),
        out_specs=P(ax),
        check_vma=False,
    )
    def fused_var(var_l, bank_l, lrow, valid, ix, v, lab, w, *rest):
        if with_slots:
            offslot, slots, l2 = rest
            off = jnp.where(
                offslot >= 0, jnp.take(slots, jnp.maximum(offslot, 0)), 0.0
            )
        else:
            off, l2 = rest
        e_loc = bank_l.shape[0]
        safe = jnp.minimum(lrow, e_loc - 1)
        sl = jnp.where(valid[:, None], jnp.take(bank_l, safe, axis=0), 0.0)
        hd = hdiag(sl, ix, v, lab, off, w, l2)
        idx = jnp.where(valid, lrow, e_loc)
        return var_l.at[idx].set(
            1.0 / (hd + _VARIANCE_EPSILON), mode="drop"
        )

    return fused_var


def _build_chunk_score_program(mesh, axis: str, n_dev: int):
    """Streamed-chunk scoring against a sharded bank: chunk columns are
    replicated (they were just uploaded from a host chunk — out-of-core
    data has no resident device home), each shard scores only the rows
    it OWNS, and one psum assembles the row vector. Traffic is O(R) per
    chunk — never a bank gather, never a host crossing."""
    ax = axis

    # photon: sharding(axes=[entity], in=[entity,r,r,r,r], out=[r])
    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(ax), P(), P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    def score_chunk(bank_l, codes, ix, v, valid):
        e_loc = bank_l.shape[0]
        me = lax.axis_index(ax)
        mine = valid & (ownership.owner_of(codes, n_dev) == me)
        lrow = jnp.minimum(
            ownership.local_row_of(jnp.maximum(codes, 0), n_dev), e_loc - 1
        )
        w_rows = jnp.take(bank_l, jnp.where(mine, lrow, 0), axis=0)
        s = jnp.sum(v * jnp.take_along_axis(w_rows, ix, axis=1), axis=-1)
        return lax.psum(jnp.where(mine, s, 0.0), ax)

    return score_chunk


def _build_score_program(mesh, axis: str, n_dev: int, cap: int):
    """Hop 2 of the residual exchange, fused with the local scoring:
    each owner shard scores its received row slots against its LOCAL
    bank rows, then the reverse all_to_all lands each score back at the
    row that sent the residual — one dispatch, one collective, zero
    host crossings."""
    ax = axis

    # photon: sharding(axes=[entity], in=[entity,*], out=[entity])
    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(ax), P(ax), P(ax), P(ax), P(ax), P(ax)),
        out_specs=P(ax),
        check_vma=False,
    )
    def score(bank_l, slot_lrow, slot_ix, slot_v, slot_valid, send_pos):
        e_loc = bank_l.shape[0]
        safe = jnp.minimum(slot_lrow, e_loc - 1)
        w_rows = jnp.take(bank_l, safe, axis=0)
        s = jnp.sum(
            slot_v * jnp.take_along_axis(w_rows, slot_ix, axis=1), axis=-1
        )
        s = jnp.where(slot_valid, s, 0.0)
        blocks = s.reshape(n_dev, cap)
        back = lax.all_to_all(
            blocks, ax, split_axis=0, concat_axis=0, tiled=False
        ).reshape(-1)
        safe_p = jnp.minimum(send_pos, n_dev * cap - 1)
        return jnp.where(send_pos < n_dev * cap, back[safe_p], 0.0)

    return score


# ---------------------------------------------------------------------------
# pod view of a RandomEffectDataset
# ---------------------------------------------------------------------------


@dataclass
class _PodBlock:
    """One capacity class, split by entity hash into per-shard padded
    blocks [n_dev * E_blk, S(, k)] (leading dim sharded). ``kind`` is
    the SAME solver-family selection the replicated path would make for
    the global bucket, so sharded-vs-replicated parity compares like
    solvers."""

    kind: str
    num_real: int  # real entities across all shards (tracker accounting)
    lrow: Array
    valid: Array
    ix: Array
    v: Array
    lab: Array
    w: Array
    offslot: Array


class _PodView:
    """Device-resident, entity-hash-sharded view of one
    RandomEffectDataset: the residual router, the per-owner scoring
    slots, and the per-capacity-class solver blocks. Built host-side
    ONCE per (dataset, mesh) and reused every CD iteration — only the
    residual values move after that."""

    def __init__(self, mesh, dataset: RandomEffectDataset, base_problem,
                 axis: Optional[str] = None):
        self.mesh = mesh
        # default: 1-D pod mesh. The unified (grid, entity) mesh passes
        # axis explicitly — row currency and blocks shard over the
        # entity axis and replicate over the grid axis, so this same
        # view (and the router's static tables) serves every λ member.
        axis = axis or mesh.axis_names[0]
        self.axis = axis
        n_dev = int(mesh.shape[axis])
        self.n_dev = n_dev
        self.num_rows = int(dataset.row_entity_codes.shape[0])
        self.spec = EntityShardSpec(n_dev, dataset.num_entities)
        e_loc = self.spec.rows_per_shard
        sharding = NamedSharding(mesh, P(axis))

        codes = np.asarray(dataset.row_entity_codes, np.int64)
        self.router = PodResidualRouter(mesh, codes, axis=axis)
        cap = self.router.cap
        n_slots = self.router.num_slots

        # -- scoring slots: every valid row's features staged at its
        # owner's (source, rank) slot — covers active AND passive rows,
        # exactly like score_random_effect on the replicated path
        slot_row = self.router.slot_row  # [owner, slot] -> gid
        flat_gid = slot_row.reshape(-1)
        s_valid = flat_gid >= 0
        safe_gid = np.maximum(flat_gid, 0)
        k = dataset.row_local_indices.shape[1]
        slot_ix = np.where(
            s_valid[:, None], dataset.row_local_indices[safe_gid], 0
        ).astype(np.int32)
        slot_v = np.where(
            s_valid[:, None], dataset.row_local_values[safe_gid], 0.0
        ).astype(np.float32)
        slot_codes = np.where(s_valid, codes[safe_gid], 0)
        slot_lrow = np.where(
            s_valid, self.spec.local_of(slot_codes), e_loc
        ).astype(np.int32)
        self.slot_ix = jax.device_put(jnp.asarray(slot_ix), sharding)
        self.slot_v = jax.device_put(jnp.asarray(slot_v), sharding)
        self.slot_lrow = jax.device_put(jnp.asarray(slot_lrow), sharding)
        self.slot_valid = jax.device_put(jnp.asarray(s_valid), sharding)
        self._score = _cached_program(
            ("score", _mesh_key(mesh), n_dev, cap),
            lambda: _build_score_program(mesh, axis, n_dev, cap),
        )

        # -- solver blocks: each bucket's entities split by hash; every
        # sample's residual offset arrives via its row's scoring slot
        # (same owner device by construction: a sample's entity IS the
        # slot's owner), so the solve needs no second exchange
        slot_of_row = self.router.slot_of_row
        self.blocks: List[_PodBlock] = []
        d_local = dataset.local_dim
        for bucket in dataset.buckets:
            kind = base_problem._bucket_kind(bucket, d_local)
            b_codes = np.asarray(bucket.entity_codes, np.int64)
            sh = entity_shard_of(b_codes, n_dev)
            lo = self.spec.local_of(b_codes)
            counts = np.bincount(sh, minlength=n_dev)
            e_blk = max(1, int(counts.max()))
            pos = np.zeros(len(b_codes), np.int64)
            for s in range(n_dev):
                m = sh == s
                pos[m] = np.arange(int(m.sum()))
            dest = sh * e_blk + pos
            S = bucket.capacity
            kk = bucket.indices.shape[2]
            rows_total = n_dev * e_blk
            b_lrow = np.full(rows_total, e_loc, np.int32)
            b_valid = np.zeros(rows_total, bool)
            b_ix = np.zeros((rows_total, S, kk), np.int32)
            b_v = np.zeros((rows_total, S, kk), np.float32)
            b_lab = np.zeros((rows_total, S), np.float32)
            b_w = np.zeros((rows_total, S), np.float32)
            b_offslot = np.full((rows_total, S), -1, np.int32)
            b_lrow[dest] = lo
            b_valid[dest] = True
            b_ix[dest] = bucket.indices
            b_v[dest] = bucket.values
            b_lab[dest] = bucket.labels
            b_w[dest] = bucket.weights
            gids = bucket.row_index
            b_offslot[dest] = np.where(
                gids >= 0, slot_of_row[np.maximum(gids, 0)], -1
            ).astype(np.int32)
            self.blocks.append(_PodBlock(
                kind=kind,
                num_real=bucket.num_entities,
                lrow=jax.device_put(jnp.asarray(b_lrow), sharding),
                valid=jax.device_put(jnp.asarray(b_valid), sharding),
                ix=jax.device_put(jnp.asarray(b_ix), sharding),
                v=jax.device_put(jnp.asarray(b_v), sharding),
                lab=jax.device_put(jnp.asarray(b_lab), sharding),
                w=jax.device_put(jnp.asarray(b_w), sharding),
                offslot=jax.device_put(jnp.asarray(b_offslot), sharding),
            ))

    def per_device_data_bytes(self) -> int:
        """Per-device bytes of the staged solver blocks + scoring slots
        (the dataset side of the weak-scaling accounting)."""
        arrays = [self.slot_ix, self.slot_v, self.slot_lrow, self.slot_valid]
        for b in self.blocks:
            arrays += [b.lrow, b.valid, b.ix, b.v, b.lab, b.w, b.offslot]
        return per_device_bytes(*arrays)


# ---------------------------------------------------------------------------
# the sharded problem
# ---------------------------------------------------------------------------


class PodRandomEffectProblem:
    """Entity-sharded twin of RandomEffectOptimizationProblem: same
    solver cores, same convergence semantics, but the bank / variances /
    tracker inputs / per-entity data all live sharded over the entity
    mesh axis, residuals arrive via one all_to_all, and every update is
    a cross-replica sharded step (each replica solves only the entities
    it owns).

    ``base`` must carry ``mesh=None`` — the pod layer owns placement;
    the base problem contributes solver construction, solver-kind
    selection and regularization semantics.
    """

    def __init__(self, base: RandomEffectOptimizationProblem, mesh):
        if base.mesh is not None:
            raise ValueError(
                "PodRandomEffectProblem wraps a mesh-less base problem; "
                "the entity mesh is owned by the pod layer"
            )
        self.base = base
        self.mesh = mesh
        self.axis = mesh.axis_names[0]
        if self.axis != ENTITY_AXIS:
            raise ValueError(
                f"pod mesh must carry the {ENTITY_AXIS!r} axis, got "
                f"{mesh.axis_names!r}"
            )
        self.num_shards = int(mesh.shape[self.axis])
        self._views: Dict[int, tuple] = {}

    def spec_for(self, dataset: RandomEffectDataset) -> EntityShardSpec:
        return EntityShardSpec(self.num_shards, dataset.num_entities)

    def init_bank(self, dataset: RandomEffectDataset) -> ShardedREBank:
        return ShardedREBank.zeros(
            self.mesh, self.spec_for(dataset), dataset.local_dim
        )

    def pod_view(self, dataset: RandomEffectDataset) -> _PodView:  # photon: entropy(id-keyed device-view memo; weakref-pinned, never serialized)
        """The sharded device view, built once per dataset (weakref-keyed
        like the base problem's device caches)."""
        key = id(dataset)
        hit = self._views.get(key)
        if hit is not None and hit[0]() is dataset:
            return hit[1]
        view = _PodView(self.mesh, dataset, self.base)
        cache = self._views
        ref = weakref.ref(dataset, lambda _, k=key, c=cache: c.pop(k, None))
        cache[key] = (ref, view)
        return view

    def prepare(self, dataset: RandomEffectDataset) -> None:
        """Stage the pod view (routing tables, sharded blocks, scoring
        slots) — the overlap prefetched-dispatch hook."""
        self.pod_view(dataset)

    def _coerce_bank(self, bank, dataset) -> ShardedREBank:
        if isinstance(bank, ShardedREBank):
            return bank
        # replicated [E, d] (warm start / checkpoint restore): shard it
        return ShardedREBank.from_global(
            self.mesh, self.spec_for(dataset), bank
        )

    def update_bank(
        self,
        bank,
        dataset: RandomEffectDataset,
        residual_offsets: Optional[Array] = None,
        with_variances: bool = False,
        defer_tracker: bool = False,
    ):
        """One cross-replica sharded bank update. ``residual_offsets``
        is the row-aligned [n] offsets-plus-residual vector (the CD loop
        always has it in hand); the pod path routes it device-side —
        there is no stored-offsets fallback because the routed slots ARE
        the offset currency here."""
        if residual_offsets is None:
            raise ValueError(
                "the pod update requires the row-aligned residual/offsets "
                "vector; pass dataset offsets (+ residual) like the CD "
                "loop does"
            )
        view = self.pod_view(dataset)
        bank = self._coerce_bank(bank, dataset)
        l1, l2 = self.base.regularization.split(self.base.reg_weight)
        l1_d, l2_d = jnp.float32(l1), jnp.float32(l2)
        slots = view.router.route_in(residual_offsets)  # hop 1
        solvers = self.base._solvers
        data = bank.data
        if _donate_args():
            # one defensive copy so the fused updates can DONATE the
            # bank shards while the caller's reference stays valid
            # (same contract as the replicated fused path)
            data = jnp.array(data, copy=True)
        n_reals: List[int] = []
        stat_vecs: List[Array] = []
        var_data = None
        if with_variances:
            var_data = _zeros_sharded(
                self.mesh, bank.spec.bank_rows, bank.dim
            )
        for blk in view.blocks:
            fused = _cached_program(
                ("update", _mesh_key(self.mesh), blk.kind, True),
                lambda kind=blk.kind: _build_update_program(
                    solvers, kind, self.mesh, self.axis, with_slots=True
                ),
            )
            data, it_sum, it_max, counts = fused(
                data, blk.lrow, blk.valid, blk.ix, blk.v, blk.lab, blk.w,
                blk.offslot, slots, l1_d, l2_d,
            )
            if with_variances:
                fused_var = _cached_program(
                    ("variance", _mesh_key(self.mesh), True),
                    lambda: _build_variance_program(
                        solvers, self.mesh, self.axis, with_slots=True
                    ),
                )
                var_data = fused_var(
                    var_data, data, blk.lrow, blk.valid, blk.ix, blk.v,
                    blk.lab, blk.w, blk.offslot, slots, l2_d,
                )
            n_reals.append(blk.num_real)
            stat_vecs.append(
                jnp.concatenate([jnp.stack([it_sum, it_max]), counts])
            )
        new_bank = ShardedREBank(self.mesh, bank.spec, data)
        if stat_vecs:
            total = sum(n_reals)

            def _finalize(all_stats, total=total):
                iter_sum = int(all_stats[:, 0].sum())
                iter_max = int(all_stats[:, 1].max())
                count_vec = all_stats[:, 2:].sum(axis=0)
                counts_dict: Dict[str, int] = {
                    CONVERGENCE_REASON_NAMES.get(code, "?"): int(cnt)
                    for code, cnt in enumerate(count_vec)
                    if cnt
                }
                return RandomEffectTracker(
                    num_entities=total,
                    iterations_mean=iter_sum / total,
                    iterations_max=iter_max,
                    reason_counts=counts_dict,
                )

            deferred = overlap.Deferred(jnp.stack(stat_vecs), _finalize)
            if defer_tracker and not deferred.done:
                tracker = LazyRandomEffectTracker(deferred)
            else:
                tracker = deferred.result()
        else:
            tracker = RandomEffectTracker(0, 0.0, 0, {})
        if with_variances:
            return new_bank, tracker, ShardedREBank(
                self.mesh, bank.spec, var_data
            )
        return new_bank, tracker

    def update_segment(
        self,
        bank: ShardedREBank,
        entity_codes: np.ndarray,
        arrays: Dict[str, np.ndarray],
        offsets: np.ndarray,
        *,
        kind: str,
    ):
        """Sharded update of ONE streamed bucket segment
        (game/streaming.SpilledREBuckets): the segment's entities are
        split by the entity hash and each device stages/solves only its
        shard of the segment — the "each host stages only its shard's
        segments" contract at device granularity. Residual offsets are
        already folded host-side (the out-of-core path's score stores
        live on disk), so this uses the direct-offset program variant.
        Returns (new bank, tracker-stat vec Deferred payload) shaped
        like the in-memory path's per-block stats."""
        n_dev = self.num_shards
        spec = bank.spec
        e_loc = spec.rows_per_shard
        sharding = _entity_sharding(self.mesh)
        codes = np.asarray(entity_codes, np.int64)
        sh = entity_shard_of(codes, n_dev)
        lo = spec.local_of(codes)
        counts = np.bincount(sh, minlength=n_dev)
        e_blk = max(1, int(counts.max()))
        pos = np.zeros(len(codes), np.int64)
        for s in range(n_dev):
            m = sh == s
            pos[m] = np.arange(int(m.sum()))
        dest = sh * e_blk + pos
        rows_total = n_dev * e_blk
        S = arrays["lab"].shape[1]
        kk = arrays["ix"].shape[2]
        b_lrow = np.full(rows_total, e_loc, np.int32)
        b_valid = np.zeros(rows_total, bool)
        b_ix = np.zeros((rows_total, S, kk), np.int32)
        b_v = np.zeros((rows_total, S, kk), np.float32)
        b_lab = np.zeros((rows_total, S), np.float32)
        b_w = np.zeros((rows_total, S), np.float32)
        b_off = np.zeros((rows_total, S), np.float32)
        b_lrow[dest] = lo
        b_valid[dest] = True
        b_ix[dest] = arrays["ix"]
        b_v[dest] = arrays["v"]
        b_lab[dest] = arrays["lab"]
        b_w[dest] = arrays["wgt"]
        b_off[dest] = np.asarray(offsets, np.float32)
        put = partial(jax.device_put, device=sharding)
        l1, l2 = self.base.regularization.split(self.base.reg_weight)
        fused = _cached_program(
            ("update", _mesh_key(self.mesh), kind, False),
            lambda: _build_update_program(
                solvers=self.base._solvers, kind=kind, mesh=self.mesh,
                axis=self.axis, with_slots=False,
            ),
        )
        data, it_sum, it_max, counts_v = fused(
            bank.data,
            put(jnp.asarray(b_lrow)), put(jnp.asarray(b_valid)),
            put(jnp.asarray(b_ix)), put(jnp.asarray(b_v)),
            put(jnp.asarray(b_lab)), put(jnp.asarray(b_w)),
            put(jnp.asarray(b_off)),
            jnp.float32(l1), jnp.float32(l2),
        )
        stat_vec = jnp.concatenate(
            [jnp.stack([it_sum, it_max]), counts_v]
        )
        return ShardedREBank(self.mesh, spec, data), stat_vec

    def segment_tracker(self, stat_vecs, num_entities: int,
                        defer: bool = True):
        """Fold per-segment stat vecs into one RandomEffectTracker —
        deferred so the CD loop's single batched readback fetches it."""

        def _finalize(all_stats, total=max(num_entities, 1)):
            iter_sum = int(all_stats[:, 0].sum())
            iter_max = int(all_stats[:, 1].max())
            count_vec = all_stats[:, 2:].sum(axis=0)
            counts_dict: Dict[str, int] = {
                CONVERGENCE_REASON_NAMES.get(code, "?"): int(cnt)
                for code, cnt in enumerate(count_vec)
                if cnt
            }
            return RandomEffectTracker(
                num_entities=num_entities,
                iterations_mean=iter_sum / total,
                iterations_max=iter_max,
                reason_counts=counts_dict,
            )

        deferred = overlap.Deferred(jnp.stack(list(stat_vecs)), _finalize)
        if defer and not deferred.done:
            return LazyRandomEffectTracker(deferred)
        return deferred.result()

    def score_chunk(self, bank: ShardedREBank, codes, ix, v, valid) -> Array:
        """[R] scores of one streamed chunk against the sharded bank:
        each shard scores its OWN rows, psum assembles — the bank never
        replicates, the chunk columns ride the upload they already pay
        on the replicated streaming path."""
        fn = _cached_program(
            ("chunk_score", _mesh_key(self.mesh)),
            lambda: _build_chunk_score_program(
                self.mesh, self.axis, self.num_shards
            ),
        )
        return fn(
            bank.data, jnp.asarray(codes), jnp.asarray(ix),
            jnp.asarray(v), jnp.asarray(valid),
        )

    def score(self, bank, dataset: RandomEffectDataset) -> Array:
        """Row-aligned [n] scores via the fused hop-2 program: owners
        score their slots locally, the reverse all_to_all returns each
        score to its row. Output is replicated (the CD score algebra's
        currency) — an O(n) row vector, never anything [E]-sized."""
        view = self.pod_view(dataset)
        bank = self._coerce_bank(bank, dataset)
        rows = view._score(
            bank.data, view.slot_lrow, view.slot_ix, view.slot_v,
            view.slot_valid, view.router._send_pos,
        )
        return _replicate(self.mesh, rows)[: view.num_rows]

    def regularization_term_device(self, bank) -> Array:
        """Reg term over the SHARDED bank — the sum reduces device-side
        (padding rows are zeros and contribute nothing); the scalar
        joins the CD iteration's one batched readback."""
        data = bank.data if isinstance(bank, ShardedREBank) else bank
        l1, l2 = self.base.regularization.split(self.base.reg_weight)
        term = 0.5 * l2 * jnp.sum(data * data)
        if l1:
            term = term + l1 * jnp.sum(jnp.abs(data))
        return term

    def regularization_term(self, bank) -> float:
        return float(overlap.device_get(self.regularization_term_device(bank)))


class PodRandomEffectModel(RandomEffectModel):
    """RandomEffectModel whose bank lives SHARDED: ``bank`` /
    ``variances`` materialize a replicated view lazily (export,
    validation scoring — off the CD hot path), while the pod coordinate
    trains and scores against ``sharded_bank`` directly. Subclassing
    keeps every isinstance-dispatched consumer (model_io.save, the
    drivers' validation scorer) working unchanged."""

    # not a @dataclass: bank/variances are lazy properties over the
    # sharded state instead of stored fields
    def __init__(
        self,
        sharded_bank: ShardedREBank,
        re_dataset: RandomEffectDataset,
        random_effect_type: str,
        feature_shard_id: str,
        variances_sharded: Optional[ShardedREBank] = None,
    ):
        self.sharded_bank = sharded_bank
        self.re_dataset = re_dataset
        self.random_effect_type = random_effect_type
        self.feature_shard_id = feature_shard_id
        self.variances_sharded = variances_sharded
        self._bank_cache: Optional[Array] = None
        self._var_cache: Optional[Array] = None

    @property
    # photon: sharding(export)
    def bank(self) -> Array:
        if self._bank_cache is None:
            self._bank_cache = self.sharded_bank.to_global()
        return self._bank_cache

    @property
    # photon: sharding(export)
    def variances(self) -> Optional[Array]:
        if self.variances_sharded is None:
            return None
        if self._var_cache is None:
            self._var_cache = self.variances_sharded.to_global()
        return self._var_cache

    @variances.setter
    def variances(self, value) -> None:  # dataclass-replace compatibility
        self._var_cache = value

    def to_random_effect_model(self) -> RandomEffectModel:
        """Materialized replicated twin (model artifacts)."""
        return RandomEffectModel(
            self.bank,
            self.re_dataset,
            self.random_effect_type,
            self.feature_shard_id,
            variances=self.variances,
        )
