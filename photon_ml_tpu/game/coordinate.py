"""GAME coordinates: fixed effect, random effect, factored RE, MF.

Reference: photon-ml .../algorithm/Coordinate.scala:82 (score /
initializeModel / updateModel / regTerm over its dataset),
FixedEffectCoordinate.scala:137-164, RandomEffectCoordinate.scala:104-199,
RandomEffectCoordinateInProjectedSpace.scala:30-140,
FactoredRandomEffectCoordinate.scala:99-289 (alternating latent-space RE
solves and a distributed projection-matrix fit).

The KeyValueScore residual currency is a row-aligned [n] array here; every
``updateModel(model, partialScore)`` first folds the residual into offsets
(dataSet.addScoresToOffsets analog) by passing ``offsets + residual``.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import SparseBatch
from photon_ml_tpu.game.config import FactoredRandomEffectConfiguration
from photon_ml_tpu.game.data import GameDataset
from photon_ml_tpu.game.model import (
    DatumScoringModel,
    FixedEffectModel,
    MatrixFactorizationModel,
    RandomEffectModel,
)
from photon_ml_tpu.game.random_effect import (
    RandomEffectOptimizationProblem,
    score_random_effect,
)
from photon_ml_tpu.game.random_effect_data import RandomEffectDataset
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import create_model
from photon_ml_tpu.optim.problem import GLMOptimizationProblem

Array = jnp.ndarray


class Coordinate:
    """One block of the coordinate descent (Coordinate.scala)."""

    name: str

    def initialize_model(self) -> DatumScoringModel:
        raise NotImplementedError

    def update_model(
        self, model: DatumScoringModel, residual: Optional[Array]
    ) -> Tuple[DatumScoringModel, object]:
        raise NotImplementedError

    def score(self, model: DatumScoringModel) -> Array:
        raise NotImplementedError

    def regularization_term(self, model: DatumScoringModel) -> float:
        raise NotImplementedError

    def regularization_term_device(self, model: DatumScoringModel) -> Array:
        """The reg term as a DEVICE scalar: the CD loop sums these into
        its one batched readback per iteration (parallel/overlap) instead
        of pulling 1-2 host floats per coordinate. Default falls back to
        the host implementation for coordinate types with no device
        expression."""
        return jnp.float32(self.regularization_term(model))

    def prepare(self, model: Optional[DatumScoringModel] = None) -> None:
        """Host-side staging for this coordinate's NEXT update (device
        transfers, layout builds, AOT warming) — idempotent, and safe to
        run on a background thread while another coordinate's solves
        occupy the device (overlap prefetched dispatch). Default: no-op."""


@dataclass
class FixedEffectCoordinate(Coordinate):
    """Global GLM block (FixedEffectCoordinate.scala:137-164).

    With a 2-D (data, model) ``mesh`` the solve runs FEATURE-SHARDED:
    the coefficient vector splits over the model axis and the existing
    sparse/tiled feature-sharded fits (incl. TRON) run inside the GAME
    coordinate descent — the reference's whole scale story is the GAME
    fixed effect at huge dimension (treeAggregate depth valve at >=200k
    features, cli/game/training/Driver.scala:357-363,717-719; "hundreds
    of billions of coefficients", README.md:73). The sharded layout is
    built once and reused across CD iterations — only the row vectors a
    sweep changes (offsets, the residual currency, and the down-sampling
    draw's weights) are re-placed per update.
    """

    name: str
    dataset: GameDataset
    problem: GLMOptimizationProblem
    feature_shard_id: str
    reg_weight: float = 0.0
    down_sampling_rate: float = 1.0
    sampler_seed: int = 0
    # data-parallel mesh for the global solve (FixedEffectCoordinate runs
    # distributed by construction in the reference; None = single device).
    # A mesh carrying a "model" axis selects the feature-sharded solve.
    mesh: Optional[object] = None

    def initialize_model(self) -> FixedEffectModel:
        dim = self.dataset.shards[self.feature_shard_id].dim
        return FixedEffectModel(
            create_model(self.problem.task, Coefficients.zeros(dim)),
            self.feature_shard_id,
        )

    def _batch(self, residual: Optional[Array]) -> SparseBatch:
        offsets = self.dataset.offsets
        if residual is not None:
            # residual algebra stays on device (SURVEY §7.9: KeyValueScore
            # is a device-resident [n] array; no host round trip)
            offsets = jnp.asarray(offsets) + residual
        return self.dataset.batch_for_shard(self.feature_shard_id, offsets)

    def _is_feature_sharded(self) -> bool:
        from photon_ml_tpu.parallel.mesh import MODEL_AXIS

        return (
            self.mesh is not None
            and MODEL_AXIS in getattr(self.mesh, "axis_names", ())
        )

    def update_model(self, model, residual=None):
        if self._is_feature_sharded():
            return self._update_model_feature_sharded(model, residual)
        batch = self._batch(residual)
        initial = model.model.means if model is not None else None
        if self.down_sampling_rate < 1.0:
            coefficients, result = self.problem.run_with_sampling(
                batch,
                jax.random.PRNGKey(self.sampler_seed),
                self.down_sampling_rate,
                initial=initial,
                reg_weight=self.reg_weight,
                mesh=self.mesh,
            )
        else:
            coefficients, result = self.problem.run(
                batch, initial=initial, reg_weight=self.reg_weight,
                mesh=self.mesh,
            )
        return (
            FixedEffectModel(
                self.problem.create_model(coefficients), self.feature_shard_id
            ),
            result,
        )

    # -- feature-sharded solve (2-D mesh) ----------------------------------

    def _feature_sharded_state(self):
        """Build-once layout + jitted fit for the (data, model) mesh.

        The sharded batch STRUCTURE (entry routing, tile schedules) only
        depends on indices/values and the BUILD-time weight mask — fixed
        across CD iterations — so it is cached on the coordinate; per
        update only the row vectors (offsets — the residual currency —
        and, when down-sampling, the draw's weights) are re-padded and
        re-placed. A sampled weight only ever ZEROES a row that was live
        at build time (inert through c = w * l'(z)), never revives a
        built-out one, so the cached layout stays exact under every
        draw."""
        state = self.__dict__.get("_fs_state")
        if state is not None:
            return state
        from photon_ml_tpu.ops.tiled_sparse import (
            TiledGLMObjective,
            feature_shard_tiled_batch,
        )
        from photon_ml_tpu.optim.config import OptimizerType
        from photon_ml_tpu.parallel import overlap
        from photon_ml_tpu.parallel.distributed import (
            feature_shard_sparse_batch,
            feature_sharded_glm_fit,
            feature_sharded_hessian_diagonal,
        )
        from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        problem = self.problem
        dim = self.dataset.shards[self.feature_shard_id].dim
        data_shards = int(self.mesh.shape[DATA_AXIS])
        model_shards = int(self.mesh.shape[MODEL_AXIS])
        tiled = isinstance(problem.objective, TiledGLMObjective)
        # The LAYOUT only depends on the shard + mesh CONTENT + kernel,
        # not on the optimizer config — cache it on the dataset so a grid
        # of combos (each building fresh coordinates AND a fresh,
        # content-identical mesh) pays the multi-second re-layout once,
        # like batch_for_shard's device cache on the replicated path.
        # Keyed by mesh content (axes + device ids), not object identity:
        # shardings over content-equal meshes are interchangeable. The
        # sparse layout never touches the mesh, so its key omits it.
        # Bounded to ONE entry per feature shard: a sweep that varies the
        # mesh shape or kernel must not accumulate device-pinned layouts.
        layout_cache = self.dataset.__dict__.setdefault(
            "_fs_layout_cache", {}
        )
        mesh_key = (
            (
                tuple(self.mesh.axis_names),
                tuple(int(n) for n in self.mesh.devices.shape),
                tuple(d.id for d in self.mesh.devices.flat),
            )
            if tiled
            else None
        )
        layout_key = (
            self.feature_shard_id, data_shards, model_shards, tiled,
            mesh_key,
        )
        hit = layout_cache.get(layout_key)
        if hit is not None:
            sharded, block_dim, meta, layout, rows_total = hit
        else:
            base = self.dataset.batch_for_shard(self.feature_shard_id)
            # counted seam: a one-time layout-build fetch, but still a
            # device->host round trip the discipline tests should see
            host = overlap.device_get(base)
            if tiled:
                sharded, block_dim = feature_shard_tiled_batch(
                    host, dim, data_shards, model_shards, mesh=self.mesh
                )
                meta, layout = sharded.meta, "tiled"
                rows_total = meta.data_shards * meta.rows_per_shard
            else:
                sharded, block_dim = feature_shard_sparse_batch(
                    host, dim, model_shards, rows_multiple=data_shards
                )
                meta, layout = None, "sparse"
                rows_total = sharded.labels.shape[0]
            for k in [
                k for k in layout_cache if k[0] == self.feature_shard_id
            ]:
                del layout_cache[k]
            layout_cache[layout_key] = (
                sharded, block_dim, meta, layout, rows_total
            )
        use_tron = problem.config.optimizer_type == OptimizerType.TRON
        use_owlqn = problem.regularization.has_l1
        norm = problem.objective.norm
        d_pad = model_shards * block_dim
        from photon_ml_tpu.parallel.distributed import (
            feature_sharded_extras,
        )

        extras_tail, l1_mask, with_norm = feature_sharded_extras(
            dim, d_pad, normalization=norm, box=problem.box,
            use_owlqn=use_owlqn, intercept_index=problem.intercept_index,
        )
        fit = feature_sharded_glm_fit(
            problem.objective, self.mesh, meta, layout=layout,
            optimizer=(
                "tron" if use_tron else ("owlqn" if use_owlqn else "lbfgs")
            ),
            max_iter=problem.config.max_iter,
            tol=problem.config.tolerance,
            history=problem.config.lbfgs_history,
            max_cg=problem.config.tron_max_cg,
            with_norm=with_norm, with_box=problem.box is not None,
        )
        hdiag = None
        if problem.compute_variances:
            hdiag = feature_sharded_hessian_diagonal(
                problem.objective, self.mesh, meta, layout=layout,
                with_norm=with_norm,
            )
        state = dict(
            sharded=sharded, fit=fit, hdiag=hdiag, dim=dim, d_pad=d_pad,
            rows_total=rows_total, use_owlqn=use_owlqn, l1_mask=l1_mask,
            extras_tail=extras_tail, with_norm=with_norm,
            meta=meta, layout=layout,
        )
        self.__dict__["_fs_state"] = state
        return state

    def _refresh_sharded_rows(self, residual):
        """Re-pad and re-place the per-update row vectors (offsets — the
        residual currency — and, when down-sampling, the draw's weights)
        against the cached sharded layout. Shared by the sequential
        update and the λ-grid solve so the two row paths cannot
        diverge."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from photon_ml_tpu.parallel.mesh import DATA_AXIS

        st = self._feature_sharded_state()
        offsets = jnp.asarray(self.dataset.offsets)
        if residual is not None:
            offsets = offsets + residual
        n = offsets.shape[0]
        row_sharding = NamedSharding(self.mesh, P(DATA_AXIS))

        def _place_rows(vec):
            if st["rows_total"] != n:
                vec = jnp.concatenate(
                    [vec, jnp.zeros((st["rows_total"] - n,), jnp.float32)]
                )
            return jax.device_put(vec, row_sharding)

        sharded = st["sharded"]._replace(offsets=_place_rows(offsets))
        if self.down_sampling_rate < 1.0:
            # Down-sampling is pure row re-weighting (data/sampler.py):
            # the per-draw weights ride the SAME re-pad-and-place path as
            # the residual offsets — traced arguments against the cached
            # layout, so the entry routing, tile schedules and compiled
            # fit all survive sampling (padding rows keep weight 0 and
            # stay inert). Same PRNG key as the replicated path, so
            # sampled-sharded == sampled-replicated draw-for-draw.
            from photon_ml_tpu.data.sampler import down_sample_weights

            w_new = down_sample_weights(
                jax.random.PRNGKey(self.sampler_seed),
                jnp.asarray(self.dataset.labels),
                jnp.asarray(self.dataset.weights),
                self.down_sampling_rate,
                self.problem.task,
            )
            sharded = sharded._replace(weights=_place_rows(w_new))
        st["sharded"] = sharded  # keep the freshest placement cached
        return sharded

    def _update_model_feature_sharded(self, model, residual):
        st = self._feature_sharded_state()
        sharded = self._refresh_sharded_rows(residual)

        initial = model.model.means if model is not None else None
        w0 = jnp.zeros((st["d_pad"],), jnp.float32)
        if initial is not None:
            w0 = w0.at[: initial.shape[0]].set(initial)
        l1, l2 = self.problem.regularization.split(self.reg_weight)
        extras = (
            [jnp.float32(l1), st["l1_mask"]] if st["use_owlqn"] else []
        ) + st["extras_tail"]
        result = st["fit"](w0, sharded, jnp.float32(l2), *extras)
        variances = None
        if st["hdiag"] is not None:
            from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

            norm_extras = st["extras_tail"][:2] if st["with_norm"] else []
            hd = st["hdiag"](
                result.coefficients, sharded, jnp.float32(l2), *norm_extras
            )
            variances = (1.0 / (hd + _VARIANCE_EPSILON))[: st["dim"]]
        coefficients = Coefficients(
            result.coefficients[: st["dim"]], variances
        )
        result = result._replace(coefficients=coefficients.means)
        return (
            FixedEffectModel(
                self.problem.create_model(coefficients), self.feature_shard_id
            ),
            result,
        )

    def update_model_grid(self, reg_weights):
        """Batched λ tuning for this fixed effect: solve EVERY grid
        weight in ONE vmapped program (training.train_grid_batched's
        engine — GLMOptimizationProblem.run_grid on the replicated and
        data-parallel layouts, feature_sharded_glm_fit(grid=True) on the
        feature-sharded (data, model) mesh) instead of one warm-started
        solve per combo — the GAME grid sweep's FE λ axis collapses to 1
        compile / 1 optimizer loop / 1 dispatch. Down-sampling composes:
        the draw is λ-independent (one shared weight rewrite, same PRNG
        stream as the sequential path), so the whole grid solves against
        the same sampled batch. Cold starts per member.

        Returns ``[(FixedEffectModel, OptResult), ...]`` aligned with
        ``reg_weights``; result scalars stay device-resident for the
        caller's batched fetch.
        """
        if self._is_feature_sharded():
            return self._update_model_grid_feature_sharded(reg_weights)
        from photon_ml_tpu.models.coefficients import Coefficients
        from photon_ml_tpu.optim.common import OptResult, Tracker

        batch = self._batch(None)
        if self.down_sampling_rate < 1.0:
            from photon_ml_tpu.data.sampler import down_sample

            batch = down_sample(
                jax.random.PRNGKey(self.sampler_seed), batch,
                self.down_sampling_rate, self.problem.task,
            )
        variances, result = self.problem.run_grid(
            batch, [float(w) for w in reg_weights], mesh=self.mesh
        )
        out = []
        tracker = result.tracker
        for i in range(len(reg_weights)):
            var_i = variances[i] if variances is not None else None
            coefficients = Coefficients(result.coefficients[i], var_i)
            out.append((
                FixedEffectModel(
                    self.problem.create_model(coefficients),
                    self.feature_shard_id,
                ),
                OptResult(
                    coefficients=result.coefficients[i],
                    value=result.value[i],
                    grad_norm=result.grad_norm[i],
                    iterations=result.iterations[i],
                    reason=result.reason[i],
                    tracker=Tracker(
                        values=tracker.values[i],
                        grad_norms=tracker.grad_norms[i],
                        count=tracker.count[i],
                        coefs=(
                            tracker.coefs[i]
                            if tracker.coefs is not None else None
                        ),
                    ),
                ),
            ))
        return out

    def _update_model_grid_feature_sharded(self, reg_weights):
        """The λ-grid solve on the (data, model) mesh: ONE
        feature_sharded_glm_fit(grid=True) dispatch covers every member
        — a [G, d_pad] coefficient bank (replicated grid axis, feature
        blocks sharded over "model"), [G] l1/l2 vectors, and the cached
        tile/entry layout walked once per data pass for the whole grid."""
        from photon_ml_tpu.models.coefficients import Coefficients
        from photon_ml_tpu.optim.common import OptResult, Tracker
        from photon_ml_tpu.optim.config import OptimizerType
        from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON
        from photon_ml_tpu.parallel.distributed import (
            feature_sharded_glm_fit,
        )

        st = self._feature_sharded_state()
        problem = self.problem
        use_tron = problem.config.optimizer_type == OptimizerType.TRON
        grid_fit = feature_sharded_glm_fit(
            problem.objective, self.mesh, st["meta"], layout=st["layout"],
            optimizer=(
                "tron" if use_tron
                else ("owlqn" if st["use_owlqn"] else "lbfgs")
            ),
            max_iter=problem.config.max_iter,
            tol=problem.config.tolerance,
            history=problem.config.lbfgs_history,
            max_cg=problem.config.tron_max_cg,
            with_norm=st["with_norm"], with_box=problem.box is not None,
            grid=True,
        )
        weights = [float(w) for w in reg_weights]
        G = len(weights)
        splits = [problem.regularization.split(w) for w in weights]
        l1_vec = jnp.asarray([s[0] for s in splits], jnp.float32)
        l2_vec = jnp.asarray([s[1] for s in splits], jnp.float32)
        # same row currency as the sequential sharded update: dataset
        # offsets (no residual at grid-tuning time) + the sampled draw
        sharded = self._refresh_sharded_rows(None)
        w0_bank = jnp.zeros((G, st["d_pad"]), jnp.float32)
        extras = (
            [l1_vec, st["l1_mask"]] if st["use_owlqn"] else []
        ) + st["extras_tail"]
        result = grid_fit(w0_bank, sharded, l2_vec, *extras)
        out = []
        tracker = result.tracker
        norm_extras = st["extras_tail"][:2] if st["with_norm"] else []
        for i in range(G):
            var_i = None
            if st["hdiag"] is not None:
                hd = st["hdiag"](
                    result.coefficients[i], sharded,
                    jnp.float32(splits[i][1]), *norm_extras
                )
                var_i = (1.0 / (hd + _VARIANCE_EPSILON))[: st["dim"]]
            coef_i = result.coefficients[i][: st["dim"]]
            coefficients = Coefficients(coef_i, var_i)
            out.append((
                FixedEffectModel(
                    problem.create_model(coefficients),
                    self.feature_shard_id,
                ),
                OptResult(
                    coefficients=coef_i,
                    value=result.value[i],
                    grad_norm=result.grad_norm[i],
                    iterations=result.iterations[i],
                    reason=result.reason[i],
                    tracker=Tracker(
                        values=tracker.values[i],
                        grad_norms=tracker.grad_norms[i],
                        count=tracker.count[i],
                        coefs=(
                            tracker.coefs[i]
                            if tracker.coefs is not None else None
                        ),
                    ),
                ),
            ))
        return out

    def score(self, model: FixedEffectModel) -> Array:
        return model.score(self.dataset)

    def regularization_term(self, model: FixedEffectModel) -> float:
        from photon_ml_tpu.parallel import overlap

        return float(
            overlap.device_get(self.regularization_term_device(model))
        )

    def regularization_term_device(self, model: FixedEffectModel) -> Array:
        l1, l2 = self.problem.regularization.split(self.reg_weight)
        w = model.model.means
        term = 0.5 * l2 * jnp.vdot(w, w)
        if l1:
            term = term + l1 * jnp.sum(jnp.abs(w))
        return term

    def prepare(self, model=None) -> None:
        """Stage the solve's static inputs ahead of update_model: the
        feature-sharded layout (built once, multi-second cold) or the
        replicated path's device copies of the shard columns."""
        if self._is_feature_sharded():
            self._feature_sharded_state()
        else:
            self.dataset.batch_for_shard(self.feature_shard_id)


@dataclass
class RandomEffectCoordinate(Coordinate):
    """Per-entity block (RandomEffectCoordinate[InProjectedSpace])."""

    name: str
    dataset: GameDataset
    re_dataset: RandomEffectDataset
    problem: RandomEffectOptimizationProblem

    def initialize_model(self) -> RandomEffectModel:
        bank = jnp.zeros(
            (self.re_dataset.num_entities, self.re_dataset.local_dim),
            jnp.float32,
        )
        return RandomEffectModel(
            bank,
            self.re_dataset,
            self.re_dataset.config.random_effect_type,
            self.re_dataset.config.feature_shard_id,
        )

    def update_model(self, model, residual=None):
        offsets = self.dataset.offsets
        if residual is not None:
            offsets = jnp.asarray(offsets) + residual  # device-resident
        variances = None
        if self.problem.compute_variances:
            bank, tracker, variances = self.problem.update_bank(
                model.bank, self.re_dataset, residual_offsets=offsets,
                with_variances=True, defer_tracker=True,
            )
        else:
            bank, tracker = self.problem.update_bank(
                model.bank, self.re_dataset, residual_offsets=offsets,
                defer_tracker=True,
            )
        return replace(model, bank=bank, variances=variances), tracker

    def score(self, model: RandomEffectModel) -> Array:
        return score_random_effect(model.bank, self.re_dataset)

    def regularization_term(self, model: RandomEffectModel) -> float:
        return self.problem.regularization_term(model.bank)

    def regularization_term_device(self, model: RandomEffectModel) -> Array:
        return self.problem.regularization_term_device(model.bank)

    def prepare(self, model=None) -> None:
        """Stage bucket device transfers / stacked group args / AOT
        programs + the row view the score pass reads."""
        from photon_ml_tpu.game.random_effect import device_row_view

        bank = (
            model.bank
            if model is not None
            else jnp.zeros(
                (self.re_dataset.num_entities, self.re_dataset.local_dim),
                jnp.float32,
            )
        )
        self.problem.prepare(bank, self.re_dataset)
        device_row_view(self.re_dataset)


@dataclass
class PodRandomEffectCoordinate(Coordinate):
    """Entity-sharded random-effect block (pod-scale GAME, game/pod.py):
    the bank, variances and per-entity data shard over the ``entity``
    mesh axis by entity hash, each replica solves only its own entities
    (cross-replica sharded update), and the residual currency rides a
    two-hop all_to_all — residuals in, scores out — instead of any
    host gather. Model state is a PodRandomEffectModel whose replicated
    ``bank`` view materializes lazily (export/validation only)."""

    name: str
    dataset: GameDataset
    re_dataset: RandomEffectDataset
    problem: RandomEffectOptimizationProblem  # mesh-less base
    mesh: object = None  # 1-D entity mesh (required)

    def __post_init__(self):
        from photon_ml_tpu.game.pod import PodRandomEffectProblem

        if self.mesh is None:
            raise ValueError("PodRandomEffectCoordinate requires an entity mesh")
        self.pod = PodRandomEffectProblem(self.problem, self.mesh)

    def initialize_model(self):
        from photon_ml_tpu.game.pod import PodRandomEffectModel

        return PodRandomEffectModel(
            self.pod.init_bank(self.re_dataset),
            self.re_dataset,
            self.re_dataset.config.random_effect_type,
            self.re_dataset.config.feature_shard_id,
        )

    def update_model(self, model, residual=None):
        from photon_ml_tpu.game.pod import PodRandomEffectModel

        offsets = self.dataset.offsets
        if residual is not None:
            offsets = jnp.asarray(offsets) + residual  # device-resident
        bank = getattr(model, "sharded_bank", None)
        if bank is None and model is not None:
            bank = model.bank  # warm start from a replicated model
        variances = None
        if self.problem.compute_variances:
            bank, tracker, variances = self.pod.update_bank(
                bank, self.re_dataset, residual_offsets=offsets,
                with_variances=True, defer_tracker=True,
            )
        else:
            bank, tracker = self.pod.update_bank(
                bank, self.re_dataset, residual_offsets=offsets,
                defer_tracker=True,
            )
        return (
            PodRandomEffectModel(
                bank,
                self.re_dataset,
                self.re_dataset.config.random_effect_type,
                self.re_dataset.config.feature_shard_id,
                variances_sharded=variances,
            ),
            tracker,
        )

    def score(self, model) -> Array:
        bank = getattr(model, "sharded_bank", None)
        if bank is None:
            return score_random_effect(model.bank, self.re_dataset)
        return self.pod.score(bank, self.re_dataset)

    def regularization_term(self, model) -> float:
        from photon_ml_tpu.parallel import overlap

        return float(
            overlap.device_get(self.regularization_term_device(model))
        )

    def regularization_term_device(self, model) -> Array:
        bank = getattr(model, "sharded_bank", None)
        if bank is None:
            bank = model.bank
        return self.pod.regularization_term_device(bank)

    def prepare(self, model=None) -> None:
        self.pod.prepare(self.re_dataset)


@dataclass
class FactoredRandomEffectCoordinate(Coordinate):
    """Random effects in a LEARNED latent projection: alternate
    (1) per-entity solves in latent space and (2) a distributed fit of the
    shared projection matrix (FactoredRandomEffectCoordinate.scala:99-289).

    Model state: RandomEffectModel whose re_dataset is a latent-space view,
    plus the projection matrix B [d, L] kept on this coordinate's model via
    the MatrixFactorization-style composition below.
    """

    name: str
    dataset: GameDataset
    re_dataset: RandomEffectDataset  # IDENTITY-projected base view
    problem: RandomEffectOptimizationProblem
    projection_problem: GLMOptimizationProblem  # over flattened B
    config: FactoredRandomEffectConfiguration
    reg_weight_projection: float = 0.0
    seed: int = 0

    def initialize_model(self) -> "FactoredRandomEffectModel":
        d = self.re_dataset.local_dim
        L = self.config.latent_space_dimension
        rng = np.random.default_rng(self.seed)
        projection = jnp.asarray(
            rng.normal(0.0, 1.0 / np.sqrt(L), size=(d, L)).astype(np.float32)
        )
        bank = jnp.zeros((self.re_dataset.num_entities, L), jnp.float32)
        return FactoredRandomEffectModel(
            bank=bank,
            projection=projection,
            re_dataset=self.re_dataset,
            random_effect_type=self.re_dataset.config.random_effect_type,
            feature_shard_id=self.re_dataset.config.feature_shard_id,
        )

    def _latent_rows(self, projection: Array) -> Tuple[Array, Array]:
        """Project every row into latent space: dense [n, L] values with
        identity local indices."""
        from photon_ml_tpu.game.random_effect import device_row_view

        _, _, ix, v = device_row_view(self.re_dataset)
        # x_lat = sum_s v_s * B[ix_s]  -> [n, L]
        return jnp.einsum("nk,nkl->nl", v, jnp.take(projection, ix, axis=0))

    def update_model(self, model, residual=None):
        offsets_np = self.dataset.offsets
        if residual is not None:
            offsets_np = jnp.asarray(offsets_np) + residual
        bank = model.bank
        projection = model.projection
        L = self.config.latent_space_dimension
        tracker = None
        for _ in range(self.config.num_inner_iterations):
            # (1) latent-space per-entity solves over re-projected buckets
            x_lat = np.asarray(self._latent_rows(projection))
            lat_view = _latent_view(self.re_dataset, x_lat)
            bank, tracker = self.problem.update_bank(
                bank, lat_view, residual_offsets=offsets_np
            )
            # (2) distributed projection-matrix fit with per-row features
            # outer(x_i, w_e(i)) flattened to d*L (updateLatentProjection
            # Matrix analog: a plain GLM over vec(B)).
            projection = self._update_projection(bank, projection, offsets_np)
        new_model = replace(model, bank=bank, projection=projection)
        return new_model, tracker

    def _update_projection(
        self, bank: Array, projection: Array, offsets_np: np.ndarray
    ) -> Array:
        from photon_ml_tpu.game.random_effect import device_row_view

        d = self.re_dataset.local_dim
        L = self.config.latent_space_dimension
        codes, valid, ix, v = device_row_view(self.re_dataset)
        w_rows = jnp.take(bank, codes, axis=0)  # [n, L]
        n, k = ix.shape
        # flattened sparse features: index (j*L + l), value v_s * w_l
        flat_ix = (ix[:, :, None] * L + jnp.arange(L)[None, None, :]).reshape(n, k * L)
        flat_v = (v[:, :, None] * w_rows[:, None, :]).reshape(n, k * L)
        batch = SparseBatch(
            indices=flat_ix.astype(jnp.int32),
            values=jnp.where(valid[:, None], flat_v, 0.0),
            labels=jnp.asarray(self.dataset.labels),
            offsets=jnp.asarray(offsets_np),
            weights=jnp.asarray(self.dataset.weights),
        )
        coefficients, _ = self.projection_problem.run(
            batch,
            initial=projection.reshape(-1),
            reg_weight=self.reg_weight_projection,
        )
        return coefficients.means.reshape(d, L)

    def score(self, model) -> Array:
        from photon_ml_tpu.game.random_effect import device_row_view

        x_lat = self._latent_rows(model.projection)  # [n, L]
        codes, valid, _, _ = device_row_view(self.re_dataset)
        w_rows = jnp.take(model.bank, codes, axis=0)
        return jnp.where(valid, jnp.sum(x_lat * w_rows, axis=-1), 0.0)

    def regularization_term(self, model) -> float:
        return self.problem.regularization_term(model.bank)


@dataclass
class FactoredRandomEffectModel(DatumScoringModel):
    """Latent bank [E, L] + shared projection [d, L]
    (FactoredRandomEffectModel.scala:75)."""

    bank: Array
    projection: Array
    re_dataset: RandomEffectDataset
    random_effect_type: str
    feature_shard_id: str

    def score(self, dataset: GameDataset) -> Array:
        from photon_ml_tpu.game.random_effect import device_row_view

        codes, valid, ix, v = device_row_view(self.re_dataset)
        x_lat = jnp.einsum("nk,nkl->nl", v, jnp.take(self.projection, ix, axis=0))
        w_rows = jnp.take(self.bank, codes, axis=0)
        return jnp.where(valid, jnp.sum(x_lat * w_rows, axis=-1), 0.0)


def _latent_view(
    base: RandomEffectDataset, x_lat: np.ndarray
) -> RandomEffectDataset:
    """Re-project a RandomEffectDataset's rows into latent space: dense
    identity-local features of width L, same entity grouping/buckets."""
    from dataclasses import replace as dc_replace

    L = x_lat.shape[1]
    n = base.row_local_indices.shape[0]
    row_ix = np.tile(np.arange(L, dtype=np.int32)[None, :], (n, 1))
    buckets = []
    for b in base.buckets:
        safe = np.maximum(b.row_index, 0)
        bix = np.tile(
            np.arange(L, dtype=np.int32)[None, None, :],
            (b.num_entities, b.capacity, 1),
        )
        bv = x_lat[safe].astype(np.float32)
        bv = np.where((b.row_index >= 0)[:, :, None], bv, 0.0)
        buckets.append(
            dc_replace(b, indices=bix, values=bv, identity_indices=True)
        )
    return dc_replace(
        base,
        local_dim=L,
        projection=np.tile(np.arange(L, dtype=np.int32)[None, :], (base.num_entities, 1)),
        row_local_indices=row_ix,
        row_local_values=x_lat.astype(np.float32),
        buckets=buckets,
        random_projection=None,
    )


@dataclass
class MatrixFactorizationCoordinate(Coordinate):
    """MF block trained by alternating least squares on residuals: row
    factors solve a K-dim GLM with features = colLatent[col_i] (a
    random-effect solve in disguise), then columns symmetrically.

    The reference trains factored models via FactoredRandomEffect and
    scores external MF models (MatrixFactorizationModel.scala); training
    in-tree here completes the GAME loop for MovieLens-style benchmarks.
    """

    name: str
    dataset: GameDataset
    row_effect_type: str
    col_effect_type: str
    num_latent_factors: int
    problem: RandomEffectOptimizationProblem
    num_inner_iterations: int = 1
    seed: int = 0

    def initialize_model(self) -> MatrixFactorizationModel:
        rng = np.random.default_rng(self.seed)
        R = self.dataset.entity_indexes[self.row_effect_type].num_entities
        C = self.dataset.entity_indexes[self.col_effect_type].num_entities
        K = self.num_latent_factors
        return MatrixFactorizationModel(
            self.row_effect_type,
            self.col_effect_type,
            jnp.asarray(rng.normal(0, 0.1, size=(R, K)).astype(np.float32)),
            jnp.asarray(rng.normal(0, 0.1, size=(C, K)).astype(np.float32)),
        )

    def _side_structure(self, side: str, solve_codes, fixed_codes, num_solved):
        """Static ALS half-step structure: entity grouping, bucket
        membership and per-bucket latent GATHER plans. Depends only on
        the dataset's entity codes, so it is built once per side and
        cached — per half-step only the latent VALUES change, and those
        are gathered on device (see _als_side).
        """
        cache = getattr(self, "_als_structure_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_als_structure_cache", cache)
        hit = cache.get(side)
        if hit is not None:
            return hit

        import jax.numpy as jnp

        from photon_ml_tpu.game.config import (
            ProjectorType,
            RandomEffectDataConfiguration,
        )
        from photon_ml_tpu.game.random_effect_data import (
            RandomEffectBucket,
            RandomEffectDataset,
        )

        K = self.num_latent_factors
        real = (
            (self.dataset.weights > 0)
            & (solve_codes >= 0)
            & (fixed_codes >= 0)
        )

        # vectorized entity grouping (a python append-per-rating loop
        # here took minutes at MovieLens scale): stable-sort rows by
        # entity, then scatter each cap-class's grouped rows into its
        # padded [E_b, S] block with one flat assignment
        real_idx = np.nonzero(real)[0]
        codes_real = solve_codes[real_idx].astype(np.int64)
        order = np.argsort(codes_real, kind="stable")
        sorted_rows = real_idx[order]
        counts = np.bincount(codes_real, minlength=num_solved)
        starts = np.cumsum(counts) - counts
        caps = np.where(
            counts > 0,
            1 << np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64),
            0,
        )
        # Merge sparse cap-classes upward: every distinct (E_b, S) bucket
        # shape costs a multi-second trace + compile of the fused solver
        # (measured ~5 s/program over the relay — 9 programs made the MF
        # first step 63 s warm-cache), while padding a FEW entities to
        # the next power of two only squares their tiny share of the
        # Gram work. Keep a class only when it holds >= 25% of the
        # active entities; everything else pads up to the next kept
        # class (the largest class is always kept — entities can never
        # pad DOWN without dropping samples).
        active = caps > 0
        if active.any():
            classes, class_counts = np.unique(caps[active], return_counts=True)
            total_active = int(class_counts.sum())
            kept = {
                int(s)
                for s, c in zip(classes, class_counts)
                if c >= 0.25 * total_active
            }
            kept.add(int(classes.max()))
            # bound the padding: no entity pads more than 4x its own cap
            # (heavy-tailed count distributions can otherwise leave every
            # class under the 25% bar and collapse the merge onto the
            # largest class — [E, S_max] blocks would blow host memory)
            for s in sorted((int(c) for c in classes), reverse=True):
                target = min((k for k in kept if k >= s), default=None)
                if target is None or target > 4 * s:
                    kept.add(s)
            kept = np.asarray(sorted(kept), np.int64)
            # next kept class >= each entity's cap
            idx = np.searchsorted(kept, caps[active])
            caps[active] = kept[idx]
        buckets = []
        gather_plans = []  # (partner_codes [E_b, S] device, ok [E_b, S] device)
        for S in sorted(set(int(c) for c in caps if c > 0)):
            members = np.nonzero(caps == S)[0]
            E_b = len(members)
            lens = counts[members]
            total = int(lens.sum())
            intra = np.arange(total) - np.repeat(np.cumsum(lens) - lens, lens)
            src = sorted_rows[np.repeat(starts[members], lens) + intra]
            b_rows = np.full((E_b, S), -1, np.int32)
            b_rows.flat[np.repeat(np.arange(E_b) * S, lens) + intra] = src
            safe = np.maximum(b_rows, 0)
            ok = b_rows >= 0
            buckets.append(RandomEffectBucket(
                entity_codes=members.astype(np.int32),
                row_index=b_rows,
                indices=np.tile(
                    np.arange(K, dtype=np.int32)[None, None, :], (E_b, S, 1)
                ),
                # zero-size placeholder: every update passes
                # values_override (on-device gathers of the partner
                # side's factors) and _bucket_device_args skips the
                # stored values on that path, so nothing is pinned
                values=np.zeros((E_b, S, 0), np.float32),
                labels=np.where(ok, self.dataset.labels[safe], 0.0),
                offsets=np.where(ok, self.dataset.offsets[safe], 0.0),
                weights=np.where(ok, self.dataset.weights[safe], 0.0),
                identity_indices=True,
            ))
            gather_plans.append((
                jnp.asarray(
                    np.where(ok, fixed_codes[safe], 0).astype(np.int32)
                ),
                jnp.asarray(ok),
            ))
        view = RandomEffectDataset(
            config=RandomEffectDataConfiguration(
                random_effect_type="__mf__",
                feature_shard_id="__latent__",
                projector_type=ProjectorType.IDENTITY,
            ),
            num_entities=num_solved,
            local_dim=K,
            projection=np.tile(
                np.arange(K, dtype=np.int32)[None, :], (num_solved, 1)
            ),
            # zero-length row-level placeholders: update_bank never
            # reads them (scoring goes through
            # MatrixFactorizationModel.score on the real dataset), and
            # [n, K] zeros would pin ~0.5 GB host RAM per side for the
            # coordinate's lifetime
            row_local_indices=np.zeros((0, K), np.int32),
            row_local_values=np.zeros((0, K), np.float32),
            row_entity_codes=np.where(real, solve_codes, -1).astype(np.int32),
            buckets=buckets,
            num_active_rows=int(counts.sum()),
            num_passive_rows=0,
        )
        cache[side] = (view, gather_plans)
        return cache[side]

    def _als_side(
        self,
        side: str,
        solve_codes: np.ndarray,  # [n] entity codes of the side being solved
        fixed_codes: np.ndarray,
        fixed_latent: Array,  # [F, K]
        bank: Array,  # [S, K] current factors of the solved side
        offsets_np: np.ndarray,
        num_solved: int,
    ) -> Array:
        import jax.numpy as jnp

        view, gather_plans = self._side_structure(
            side, solve_codes, fixed_codes, num_solved
        )
        # latent feature views gathered ON DEVICE from the partner side's
        # current factors — no host round trip, no [E, S, K] re-upload.
        # Deferred per bucket (callables): only the bucket being solved
        # holds its gathered values in HBM.
        values = [
            (lambda codes=codes, ok=ok: jnp.where(
                ok[..., None], jnp.take(fixed_latent, codes, axis=0), 0.0
            ))
            for codes, ok in gather_plans
        ]
        new_bank, _ = self.problem.update_bank(
            bank, view, residual_offsets=offsets_np, values_override=values
        )
        return new_bank

    def update_model(self, model, residual=None):
        offsets_np = self.dataset.offsets
        if residual is not None:
            offsets_np = jnp.asarray(offsets_np) + residual
        rows = self.dataset.entity_codes[self.row_effect_type]
        cols = self.dataset.entity_codes[self.col_effect_type]
        R = self.dataset.entity_indexes[self.row_effect_type].num_entities
        C = self.dataset.entity_indexes[self.col_effect_type].num_entities
        row_latent, col_latent = model.row_latent, model.col_latent
        # With no residual the cached bucket offsets already hold the
        # dataset offsets — passing residual_offsets would re-gather and
        # re-upload [E, S] offsets per bucket every half-step for nothing
        offsets_arg = None if residual is None else offsets_np
        if not self.__dict__.get("_als_prewarmed"):
            # cold start: AOT-compile BOTH sides' bucket programs in one
            # threaded pool before the first half-step — per-side warming
            # serialized the col side's compiles behind the row solves
            # (and skipped single-bucket sides entirely)
            row_view, _ = self._side_structure("row", rows, cols, R)
            col_view, _ = self._side_structure("col", cols, rows, C)
            self.problem.prewarm([
                (row_latent, row_view, True, offsets_arg is not None),
                (col_latent, col_view, True, offsets_arg is not None),
            ])
            self.__dict__["_als_prewarmed"] = True
        for _ in range(self.num_inner_iterations):
            row_latent = self._als_side(
                "row", rows, cols, col_latent, row_latent, offsets_arg, R
            )
            col_latent = self._als_side(
                "col", cols, rows, row_latent, col_latent, offsets_arg, C
            )
        return replace(model, row_latent=row_latent, col_latent=col_latent), None

    def score(self, model: MatrixFactorizationModel) -> Array:
        return model.score(self.dataset)

    def regularization_term(self, model: MatrixFactorizationModel) -> float:
        from photon_ml_tpu.parallel import overlap

        return float(
            overlap.device_get(self.regularization_term_device(model))
        )

    def regularization_term_device(
        self, model: MatrixFactorizationModel
    ) -> Array:
        # device scalar, like the FE/RE coordinates: the CD loop folds it
        # into its one batched readback instead of a per-coordinate pull
        l1, l2 = self.problem.regularization.split(self.problem.reg_weight)
        return 0.5 * l2 * (
            jnp.sum(model.row_latent**2) + jnp.sum(model.col_latent**2)
        )
