"""Step-level checkpoint / resume for GAME coordinate descent.

The reference has NO mid-optimization checkpointing — its recovery units
are saved models and warm starts (SURVEY §5.4; ModelTraining.scala:183-208,
CoordinateDescent.scala:82-87). This module is the deliberate TPU-era
upgrade: orbax-backed per-iteration checkpoints of every coordinate's
model state, resumable across process restarts (preemptible TPU jobs).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax.numpy as jnp
import orbax.checkpoint as ocp


def model_state(model) -> Dict[str, Any]:
    """Extract the array state of any GAME submodel as a pytree."""
    from photon_ml_tpu.game.coordinate import FactoredRandomEffectModel
    from photon_ml_tpu.game.model import (
        FixedEffectModel,
        MatrixFactorizationModel,
        RandomEffectModel,
    )

    if isinstance(model, FixedEffectModel):
        return {"means": model.model.means}
    if isinstance(model, RandomEffectModel):
        return {"bank": model.bank}
    if isinstance(model, FactoredRandomEffectModel):
        return {"bank": model.bank, "projection": model.projection}
    if isinstance(model, MatrixFactorizationModel):
        return {"row_latent": model.row_latent, "col_latent": model.col_latent}
    raise ValueError(f"cannot checkpoint model type {type(model)}")


def restore_model(model, state: Dict[str, Any]):
    """Rebuild a submodel of the same type from checkpointed arrays."""
    from dataclasses import replace

    from photon_ml_tpu.game.coordinate import FactoredRandomEffectModel
    from photon_ml_tpu.game.model import (
        FixedEffectModel,
        MatrixFactorizationModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.coefficients import Coefficients

    # the template model's type selects the restore path; key mismatches
    # (checkpoint from a different configuration) raise KeyError below.
    if isinstance(model, FixedEffectModel) and "means" in state:
        glm = model.model.update_coefficients(
            Coefficients(jnp.asarray(state["means"]))
        )
        return replace(model, model=glm)
    from photon_ml_tpu.game.pod import PodRandomEffectModel, ShardedREBank

    if isinstance(model, PodRandomEffectModel) and "bank" in state:
        # re-shard the checkpointed replicated bank over the template's
        # entity mesh (dataclasses.replace cannot rebuild the lazy-bank
        # subclass)
        sb = ShardedREBank.from_global(
            model.sharded_bank.mesh,
            model.sharded_bank.spec,
            jnp.asarray(state["bank"]),
        )
        return PodRandomEffectModel(
            sb, model.re_dataset, model.random_effect_type,
            model.feature_shard_id,
        )
    if isinstance(model, RandomEffectModel) and "bank" in state:
        return replace(model, bank=jnp.asarray(state["bank"]))
    if isinstance(model, FactoredRandomEffectModel) and "projection" in state:
        return replace(
            model,
            bank=jnp.asarray(state["bank"]),
            projection=jnp.asarray(state["projection"]),
        )
    if isinstance(model, MatrixFactorizationModel) and "row_latent" in state:
        return replace(
            model,
            row_latent=jnp.asarray(state["row_latent"]),
            col_latent=jnp.asarray(state["col_latent"]),
        )
    raise ValueError(f"checkpoint state {list(state)} does not match {type(model)}")


class TrainingCheckpointer:
    """Orbax CheckpointManager wrapper keyed by coordinate-descent
    iteration."""

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, models: Dict[str, Any]) -> None:
        from photon_ml_tpu.reliability.retry import io_call

        state = {name: model_state(m) for name, m in models.items()}

        def _save():
            self._mgr.save(
                step, args=ocp.args.StandardSave(state), force=True
            )
            self._mgr.wait_until_finished()

        # ckpt_save seam: orbax's own protocol is atomic per step, and
        # force=True overwrites a half-finished attempt — so a retried
        # save converges on a complete step directory
        io_call(
            "ckpt_save", _save, detail=f"{self.directory} step {step}"
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def available_steps(self) -> list:
        return list(self._mgr.all_steps())

    # -- host-side metadata sidecar (best-iteration tracking etc.) --------
    def save_meta(self, meta: Dict[str, Any]) -> None:
        """Small JSON sidecar next to the step checkpoints — resume needs
        more than weights (e.g. which iteration was validation-best).
        Atomic write-rename behind the ckpt_save seam."""
        from photon_ml_tpu.reliability.artifacts import atomic_write_json
        from photon_ml_tpu.reliability.retry import io_call

        path = os.path.join(self.directory, "cd_meta.json")
        io_call("ckpt_save", atomic_write_json, path, meta, detail=path)

    def load_meta(self) -> Optional[Dict[str, Any]]:
        from photon_ml_tpu.reliability.retry import io_call

        path = os.path.join(self.directory, "cd_meta.json")
        if not os.path.isfile(path):
            return None

        def _load():
            with open(path) as f:
                return json.load(f)

        return io_call("ckpt_restore", _load, detail=path)

    def restore(self, step: int, models: Dict[str, Any]) -> Dict[str, Any]:
        """-> {name: restored model}, using ``models`` as type templates.

        Explicit StandardRestore args: a FRESH process (the actual resume
        scenario) has no handler registered for the saved item, and
        orbax's inference-from-history only works after a save in the
        same process — without the args the restore raises KeyError
        ("provide a CheckpointHandlerRegistry"). The host-side topology
        check happens in restore_model (template-typed)."""
        from photon_ml_tpu.reliability.retry import io_call

        state = io_call(
            "ckpt_restore",
            lambda: self._mgr.restore(
                step, args=ocp.args.StandardRestore()
            ),
            detail=f"{self.directory} step {step}",
        )
        return {
            name: restore_model(models[name], state[name]) for name in models
        }

    def close(self) -> None:
        self._mgr.close()
