"""Python binding for the native mmap index store (PalDB analog).

Reference: photon-ml .../util/PalDBIndexMap.scala:43-130 (partitioned
off-heap stores with offset arrays + per-partition local indices, global
index = local + partition offset; readers guarded by PALDB_READER_LOCK —
unnecessary here, the mmap is immutable and lock-free) and
PalDBIndexMapBuilder.scala / PalDBIndexMapLoader.scala,
FeatureIndexingJob.scala:59-136 (hash-partitioned vocabulary build).

The .so is compiled from native/index_store.cpp on first use (no pip
installs in the image); ctypes keeps the binding dependency-free.
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from typing import Iterable, List, Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "index_store.cpp")
_LIB_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_LIB_DIR, "libindex_store.so")
_COMPILE_LOCK = threading.Lock()
_lib_handle = None


def _compile_if_needed() -> str:
    with _COMPILE_LOCK:
        if os.path.isfile(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
            return _LIB
        os.makedirs(_LIB_DIR, exist_ok=True)
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            _SRC, "-o", _LIB,
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        return _LIB


def _lib():
    global _lib_handle
    if _lib_handle is None:
        lib = ctypes.CDLL(_compile_if_needed())
        lib.pidx_build.restype = ctypes.c_int
        lib.pidx_build.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
        ]
        lib.pidx_open.restype = ctypes.c_void_p
        lib.pidx_open.argtypes = [ctypes.c_char_p]
        lib.pidx_close.argtypes = [ctypes.c_void_p]
        lib.pidx_size.restype = ctypes.c_uint64
        lib.pidx_size.argtypes = [ctypes.c_void_p]
        lib.pidx_get_index.restype = ctypes.c_int64
        lib.pidx_get_index.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.pidx_get_key.restype = ctypes.c_int64
        lib.pidx_get_key.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.pidx_get_indices.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _lib_handle = lib
    return _lib_handle


def build_store(path: str, keys: Sequence[str]) -> None:
    """Write one partition store; keys get local indices 0..n-1."""
    lib = _lib()
    encoded = [k.encode("utf-8") for k in keys]
    n = len(encoded)
    arr = (ctypes.c_char_p * n)(*encoded)
    lens = (ctypes.c_uint32 * n)(*[len(e) for e in encoded])
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    rc = lib.pidx_build(path.encode(), arr, lens, n)
    if rc == -2:
        raise ValueError("duplicate keys in index store build")
    if rc != 0:
        raise OSError(f"pidx_build failed with code {rc}")


class NativeIndexStore:
    """One open partition store (immutable, lock-free reads)."""

    def __init__(self, path: str):
        self._lib = _lib()
        self._handle = self._lib.pidx_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot open index store {path}")
        self.path = path

    def __len__(self) -> int:
        return self._lib.pidx_size(self._handle)

    def get_index(self, key: str) -> int:
        e = key.encode("utf-8")
        return self._lib.pidx_get_index(self._handle, e, len(e))

    def get_key(self, local_index: int) -> Optional[str]:
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.pidx_get_key(self._handle, local_index, buf, 4096)
        if n < 0:
            return None
        if n > 4096:
            buf = ctypes.create_string_buffer(n)
            self._lib.pidx_get_key(self._handle, local_index, buf, n)
        return buf.raw[:n].decode("utf-8")

    def get_indices(self, keys: Sequence[str]) -> np.ndarray:
        encoded = [k.encode("utf-8") for k in keys]
        packed = b"".join(encoded)
        offsets = np.zeros(len(encoded) + 1, np.uint64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        out = np.empty(len(encoded), np.int64)
        self._lib.pidx_get_indices(
            self._handle,
            packed,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(encoded),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.pidx_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class PartitionedIndexMap:
    """IndexMap API over hash-partitioned native stores
    (PalDBIndexMap semantics: partition = hash(key) %% P, global index =
    local + offset[partition])."""

    STORE_PATTERN = "index-partition-{part}.pidx"

    def __init__(self, directory: str):
        self.directory = directory
        # sort by NUMERIC partition id: lexicographic order would place
        # partition 10 before 2 and misalign hash(key) % P routing
        parts = sorted(
            (
                f
                for f in os.listdir(directory)
                if f.startswith("index-partition-")
            ),
            key=lambda f: int(
                f[len("index-partition-"):].split(".", 1)[0]
            ),
        )
        if not parts:
            raise OSError(f"no index partitions in {directory}")
        expected = [
            self.STORE_PATTERN.format(part=p) for p in range(len(parts))
        ]
        if parts != expected:
            raise OSError(
                f"{directory}: partition files {parts} are not the "
                f"contiguous set {expected}"
            )
        self._stores = [
            NativeIndexStore(os.path.join(directory, f)) for f in parts
        ]
        self._offsets = np.zeros(len(self._stores) + 1, np.int64)
        np.cumsum([len(s) for s in self._stores], out=self._offsets[1:])

    @property
    def size(self) -> int:
        return int(self._offsets[-1])

    def __len__(self) -> int:
        return self.size

    def __contains__(self, key: str) -> bool:
        return self.get_index(key) >= 0

    def _partition_of(self, key: str) -> int:
        import zlib

        return zlib.crc32(key.encode("utf-8")) % len(self._stores)

    def get_index(self, key: str, default: int = -1) -> int:
        p = self._partition_of(key)
        local = self._stores[p].get_index(key)
        return int(local + self._offsets[p]) if local >= 0 else default

    def get_feature_name(self, index: int) -> Optional[str]:
        p = int(np.searchsorted(self._offsets, index, side="right")) - 1
        if p < 0 or p >= len(self._stores):
            return None
        return self._stores[p].get_key(index - int(self._offsets[p]))

    def items(self):
        for p, store in enumerate(self._stores):
            base = int(self._offsets[p])
            for local in range(len(store)):
                yield store.get_key(local), base + local

    def close(self) -> None:
        for s in self._stores:
            s.close()

    def save(self, path: str) -> None:
        """Write a POINTER to the store instead of duplicating a
        potentially >200k-key vocabulary as JSON (IndexMap.save parity
        for the driver's feature-index output). ``IndexMap.load``
        recognizes the pointer and reopens the store; the relative path
        keeps an output directory relocatable together with its index."""
        from photon_ml_tpu.reliability.artifacts import atomic_writer

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with atomic_writer(path, encoding="utf-8") as f:
            json.dump(
                {
                    "offheap_index_store": os.path.abspath(self.directory),
                    "offheap_index_store_relative": os.path.relpath(
                        os.path.abspath(self.directory),
                        os.path.dirname(os.path.abspath(path)),
                    ),
                    "num_partitions": len(self._stores),
                    "size": self.size,
                },
                f,
            )

    @staticmethod
    def from_pointer(meta: dict, pointer_path: str) -> "PartitionedIndexMap":
        """Reopen a store from a ``save`` pointer; tries the relative
        path (relocated output tree) before the recorded absolute one."""
        rel = meta.get("offheap_index_store_relative")
        if rel is not None:
            cand = os.path.join(
                os.path.dirname(os.path.abspath(pointer_path)), rel
            )
            if _has_store(cand):
                return PartitionedIndexMap(cand)
        return PartitionedIndexMap(meta["offheap_index_store"])


def build_partitioned_index(
    keys: Iterable[str],
    directory: str,
    num_partitions: int = 1,
) -> PartitionedIndexMap:
    """The FeatureIndexingJob analog: hash-partition DISTINCT keys, build
    one native store per partition (sorted within partition for
    determinism), return the loader."""
    import zlib

    os.makedirs(directory, exist_ok=True)
    parts: List[List[str]] = [[] for _ in range(num_partitions)]
    for key in sorted(set(keys)):
        parts[zlib.crc32(key.encode("utf-8")) % num_partitions].append(key)
    for p, part_keys in enumerate(parts):
        build_store(
            os.path.join(
                directory, PartitionedIndexMap.STORE_PATTERN.format(part=p)
            ),
            sorted(part_keys),
        )
    return PartitionedIndexMap(directory)


def load_offheap_index_map(
    directory: str,
    shard_name: Optional[str] = None,
    num_partitions: Optional[int] = None,
) -> PartitionedIndexMap:
    """Open a prebuilt partitioned store (the drivers'
    ``--offheap-indexmap-dir`` path; PalDBIndexMapLoader analog,
    cli/game/GAMEDriver.scala:89-97 prepareFeatureMaps).

    With ``shard_name`` (the GAME per-shard path) the store MUST be at
    ``<directory>/<shard_name>`` — pointing different shards at one store
    would silently merge their feature spaces. Without it, accepts either
    a store directory itself (contains ``index-partition-*``) or a parent
    with exactly one shard subdirectory. ``num_partitions`` — the
    reference's ``offheap-indexmap-num-partitions`` — is validated
    against the store when given (here partition count is discovered
    from the files, so the option is a consistency check only).
    """
    if shard_name is not None:
        d = os.path.join(directory, shard_name)
        if not _has_store(d):
            raise OSError(
                f"no index store for feature shard {shard_name!r} at {d} "
                "— run the feature-indexing job with "
                f"--shard-name {shard_name}"
            )
    else:
        d = directory
        if not _has_store(d):
            subs = [
                s
                for s in sorted(os.listdir(d))
                if _has_store(os.path.join(d, s))
            ] if os.path.isdir(d) else []
            if len(subs) != 1:
                raise OSError(
                    f"{directory}: expected an index store or exactly one "
                    f"shard subdirectory, found {subs or 'none'}"
                )
            d = os.path.join(d, subs[0])
    pm = PartitionedIndexMap(d)
    if num_partitions is not None and len(pm._stores) != num_partitions:
        pm.close()
        raise ValueError(
            f"offheap index map at {d} has {len(pm._stores)} partitions, "
            f"expected {num_partitions}"
        )
    return pm


def load_offheap_index_maps(
    directory: str,
    shard_ids: Sequence[str],
    num_partitions: Optional[int] = None,
) -> dict:
    """{shard_id: PartitionedIndexMap} for the GAME drivers'
    --offheap-indexmap-dir (prepareFeatureMaps analog); every shard must
    have its ``<directory>/<shard_id>`` store."""
    return {
        sid: load_offheap_index_map(
            directory, shard_name=sid, num_partitions=num_partitions
        )
        for sid in shard_ids
    }


def _has_store(d: str) -> bool:
    return os.path.isdir(d) and any(
        f.startswith("index-partition-") for f in os.listdir(d)
    )
