"""Python binding for the native mmap index store (PalDB analog).

Reference: photon-ml .../util/PalDBIndexMap.scala:43-130 (partitioned
off-heap stores with offset arrays + per-partition local indices, global
index = local + partition offset; readers guarded by PALDB_READER_LOCK —
unnecessary here, the mmap is immutable and lock-free) and
PalDBIndexMapBuilder.scala / PalDBIndexMapLoader.scala,
FeatureIndexingJob.scala:59-136 (hash-partitioned vocabulary build).

The .so is compiled from native/index_store.cpp on first use (no pip
installs in the image); ctypes keeps the binding dependency-free.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterable, List, Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "index_store.cpp")
_LIB_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_LIB_DIR, "libindex_store.so")
_COMPILE_LOCK = threading.Lock()
_lib_handle = None


def _compile_if_needed() -> str:
    with _COMPILE_LOCK:
        if os.path.isfile(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC):
            return _LIB
        os.makedirs(_LIB_DIR, exist_ok=True)
        cmd = [
            "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
            _SRC, "-o", _LIB,
        ]
        subprocess.run(cmd, check=True, capture_output=True)
        return _LIB


def _lib():
    global _lib_handle
    if _lib_handle is None:
        lib = ctypes.CDLL(_compile_if_needed())
        lib.pidx_build.restype = ctypes.c_int
        lib.pidx_build.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_uint64,
        ]
        lib.pidx_open.restype = ctypes.c_void_p
        lib.pidx_open.argtypes = [ctypes.c_char_p]
        lib.pidx_close.argtypes = [ctypes.c_void_p]
        lib.pidx_size.restype = ctypes.c_uint64
        lib.pidx_size.argtypes = [ctypes.c_void_p]
        lib.pidx_get_index.restype = ctypes.c_int64
        lib.pidx_get_index.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.pidx_get_key.restype = ctypes.c_int64
        lib.pidx_get_key.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        lib.pidx_get_indices.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        _lib_handle = lib
    return _lib_handle


def build_store(path: str, keys: Sequence[str]) -> None:
    """Write one partition store; keys get local indices 0..n-1."""
    lib = _lib()
    encoded = [k.encode("utf-8") for k in keys]
    n = len(encoded)
    arr = (ctypes.c_char_p * n)(*encoded)
    lens = (ctypes.c_uint32 * n)(*[len(e) for e in encoded])
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    rc = lib.pidx_build(path.encode(), arr, lens, n)
    if rc == -2:
        raise ValueError("duplicate keys in index store build")
    if rc != 0:
        raise OSError(f"pidx_build failed with code {rc}")


class NativeIndexStore:
    """One open partition store (immutable, lock-free reads)."""

    def __init__(self, path: str):
        self._lib = _lib()
        self._handle = self._lib.pidx_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot open index store {path}")
        self.path = path

    def __len__(self) -> int:
        return self._lib.pidx_size(self._handle)

    def get_index(self, key: str) -> int:
        e = key.encode("utf-8")
        return self._lib.pidx_get_index(self._handle, e, len(e))

    def get_key(self, local_index: int) -> Optional[str]:
        buf = ctypes.create_string_buffer(4096)
        n = self._lib.pidx_get_key(self._handle, local_index, buf, 4096)
        if n < 0:
            return None
        if n > 4096:
            buf = ctypes.create_string_buffer(n)
            self._lib.pidx_get_key(self._handle, local_index, buf, n)
        return buf.raw[:n].decode("utf-8")

    def get_indices(self, keys: Sequence[str]) -> np.ndarray:
        encoded = [k.encode("utf-8") for k in keys]
        packed = b"".join(encoded)
        offsets = np.zeros(len(encoded) + 1, np.uint64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        out = np.empty(len(encoded), np.int64)
        self._lib.pidx_get_indices(
            self._handle,
            packed,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            len(encoded),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        )
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.pidx_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass


class PartitionedIndexMap:
    """IndexMap API over hash-partitioned native stores
    (PalDBIndexMap semantics: partition = hash(key) %% P, global index =
    local + offset[partition])."""

    STORE_PATTERN = "index-partition-{part}.pidx"

    def __init__(self, directory: str):
        self.directory = directory
        parts = sorted(
            f for f in os.listdir(directory) if f.startswith("index-partition-")
        )
        if not parts:
            raise OSError(f"no index partitions in {directory}")
        self._stores = [
            NativeIndexStore(os.path.join(directory, f)) for f in parts
        ]
        self._offsets = np.zeros(len(self._stores) + 1, np.int64)
        np.cumsum([len(s) for s in self._stores], out=self._offsets[1:])

    @property
    def size(self) -> int:
        return int(self._offsets[-1])

    def __len__(self) -> int:
        return self.size

    def __contains__(self, key: str) -> bool:
        return self.get_index(key) >= 0

    def _partition_of(self, key: str) -> int:
        import zlib

        return zlib.crc32(key.encode("utf-8")) % len(self._stores)

    def get_index(self, key: str, default: int = -1) -> int:
        p = self._partition_of(key)
        local = self._stores[p].get_index(key)
        return int(local + self._offsets[p]) if local >= 0 else default

    def get_feature_name(self, index: int) -> Optional[str]:
        p = int(np.searchsorted(self._offsets, index, side="right")) - 1
        if p < 0 or p >= len(self._stores):
            return None
        return self._stores[p].get_key(index - int(self._offsets[p]))

    def items(self):
        for p, store in enumerate(self._stores):
            base = int(self._offsets[p])
            for local in range(len(store)):
                yield store.get_key(local), base + local

    def close(self) -> None:
        for s in self._stores:
            s.close()


def build_partitioned_index(
    keys: Iterable[str],
    directory: str,
    num_partitions: int = 1,
) -> PartitionedIndexMap:
    """The FeatureIndexingJob analog: hash-partition DISTINCT keys, build
    one native store per partition (sorted within partition for
    determinism), return the loader."""
    import zlib

    os.makedirs(directory, exist_ok=True)
    parts: List[List[str]] = [[] for _ in range(num_partitions)]
    for key in set(keys):
        parts[zlib.crc32(key.encode("utf-8")) % num_partitions].append(key)
    for p, part_keys in enumerate(parts):
        build_store(
            os.path.join(
                directory, PartitionedIndexMap.STORE_PATTERN.format(part=p)
            ),
            sorted(part_keys),
        )
    return PartitionedIndexMap(directory)
