"""Preemption detection for long-running training loops.

The reference delegates failure recovery entirely to Spark/YARN (lineage
recompute, container restarts — SURVEY §5.3; the code itself only fails
fast, Driver.scala:148-151). A TPU job has no resource manager underneath
it: preemptible/spot TPU VMs receive SIGTERM with a short grace window
before eviction. This module turns that signal into a cooperative flag
that training loops poll at safe points (iteration boundaries), so the
loop can write a final checkpoint and exit cleanly; the restarted job
resumes from the checkpoint (CoordinateDescent + TrainingCheckpointer).

Design: a tiny chained-handler guard rather than raising out of the
signal handler — a mid-``jit`` KeyboardInterrupt-style unwind can leave
the runtime wedged, while a flag checked between device calls is always
safe.
"""

from __future__ import annotations

import signal
import threading
from typing import List, Optional


class PreemptionGuard:
    """Cooperative preemption flag set by SIGTERM (and optionally other
    signals). Poll :meth:`requested` at iteration boundaries."""

    def __init__(self, signals: Optional[List[int]] = None):
        self.signals = list(signals) if signals is not None else [signal.SIGTERM]
        self._event = threading.Event()
        self._prev = {}
        self._installed = False

    # -- signal plumbing ---------------------------------------------------
    def install(self) -> "PreemptionGuard":
        """Register handlers; chains any previously-installed handler so
        outer supervisors still observe the signal. Main thread only."""
        if self._installed:
            return self
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._handle)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self._installed = False

    def _handle(self, signum, frame) -> None:
        self._event.set()
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -- polling -----------------------------------------------------------
    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self) -> None:
        """Set the flag programmatically (tests, host-level watchdogs)."""
        self._event.set()

    def reset(self) -> None:
        self._event.clear()
