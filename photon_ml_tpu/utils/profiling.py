"""Profiler hooks: ``jax.profiler`` traces behind the drivers'
``--profile-dir`` flag (SURVEY §7.11 — the deliberate upgrade over the
reference's Timer-only observability: XLA/TPU timelines instead of wall
-clock buckets). Traces land in the given directory (conventionally
``<output-dir>/profile``, next to optimization-log.txt) and open in
TensorBoard / Perfetto."""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager, nullcontext
from typing import Dict, Optional


@contextmanager
def _trace(profile_dir: str):
    import jax

    os.makedirs(profile_dir, exist_ok=True)
    with jax.profiler.trace(profile_dir):
        yield


def profile_trace(profile_dir: Optional[str]):
    """Context manager: a ``jax.profiler`` trace into ``profile_dir``,
    or a no-op when the flag is unset."""
    if not profile_dir:
        return nullcontext()
    return _trace(profile_dir)


# -- host-side timing registry ----------------------------------------------
#
# jax.profiler covers device timelines; HOST-side one-off costs (schedule
# builds, cache loads/stores) need their own accumulation so drivers can
# report them without wrapping every call site in a Timer. Named buckets
# accumulate across the process; drivers snapshot into metrics.json.

_HOST_TIMINGS: Dict[str, float] = {}
_HOST_TIMINGS_LOCK = threading.Lock()


def record_host_timing(name: str, seconds: float) -> None:
    """Accumulate ``seconds`` into the named host-timing bucket
    (thread-safe — schedule builds run on worker threads)."""
    with _HOST_TIMINGS_LOCK:
        _HOST_TIMINGS[name] = _HOST_TIMINGS.get(name, 0.0) + seconds


def host_timings() -> Dict[str, float]:
    """Snapshot of all accumulated host-timing buckets."""
    with _HOST_TIMINGS_LOCK:
        return dict(_HOST_TIMINGS)


def reset_host_timings() -> None:
    with _HOST_TIMINGS_LOCK:
        _HOST_TIMINGS.clear()


def peak_rss_bytes() -> int:
    """Host peak-RSS high-water of this process in BYTES (ru_maxrss is
    KiB on Linux, bytes on macOS) — the out-of-core layer's reported
    memory ceiling (metrics.json / bench.py streaming sections)."""
    import resource
    import sys

    ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(ru if sys.platform == "darwin" else ru * 1024)
