"""Profiler hooks: ``jax.profiler`` traces behind the drivers'
``--profile-dir`` flag (SURVEY §7.11 — the deliberate upgrade over the
reference's Timer-only observability: XLA/TPU timelines instead of wall
-clock buckets). Traces land in the given directory (conventionally
``<output-dir>/profile``, next to optimization-log.txt) and open in
TensorBoard / Perfetto."""

from __future__ import annotations

import os
from contextlib import contextmanager, nullcontext
from typing import Optional


@contextmanager
def _trace(profile_dir: str):
    import jax

    os.makedirs(profile_dir, exist_ok=True)
    with jax.profiler.trace(profile_dir):
        yield


def profile_trace(profile_dir: Optional[str]):
    """Context manager: a ``jax.profiler`` trace into ``profile_dir``,
    or a no-op when the flag is unset."""
    if not profile_dir:
        return nullcontext()
    return _trace(profile_dir)
