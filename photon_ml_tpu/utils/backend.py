"""Backend/platform introspection that respects ``jax.default_device``.

``jax.default_backend()`` initializes and reports the process-default
platform (TPU when a plugin is pinned) even inside a
``jax.default_device(cpu)`` scope. Hermetic CPU-mesh paths (the driver's
``dryrun_multichip``) must never touch the TPU runtime, so library code
that branches on "what device will my arrays land on" uses
:func:`effective_platform` instead.
"""

from __future__ import annotations


def effective_platform() -> str:
    """Platform new unannotated arrays land on under the CURRENT context.

    Honors ``jax.default_device`` scopes (returns "cpu" inside one even
    when a TPU plugin is installed) and only initializes the backend the
    caller is about to use anyway.
    """
    import jax.numpy as jnp

    return next(iter(jnp.zeros(()).devices())).platform
