"""Backend/platform introspection that respects ``jax.default_device``.

``jax.default_backend()`` initializes and reports the process-default
platform (TPU when a plugin is pinned) even inside a
``jax.default_device(cpu)`` scope. Hermetic CPU-mesh paths (the driver's
``dryrun_multichip``) must never touch the TPU runtime, so library code
that branches on "what device will my arrays land on" uses
:func:`effective_platform` instead.
"""

from __future__ import annotations


def enable_compilation_cache(path: "str | None" = None) -> str:
    """Turn on JAX's persistent XLA compilation cache.

    Cold compiles dominate first-step latency on relay-attached chips
    (the MF/ALS coordinate measured 82 s for its first update vs 2.5 s
    warm, BASELINE 5b round 3) — the persistent cache amortizes them
    across processes and rounds. Default location:
    $PHOTON_COMPILE_CACHE or ~/.cache/photon-ml-tpu/xla-cache. Safe to
    call multiple times; returns the cache directory."""
    import os

    import jax

    if path is None:
        path = os.environ.get("PHOTON_COMPILE_CACHE") or os.path.expanduser(
            "~/.cache/photon-ml-tpu/xla-cache"
        )
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache anything that took meaningful compile time (default 1s floor
    # skips the many tiny programs)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return path


def effective_platform() -> str:
    """Platform new unannotated arrays land on under the CURRENT context.

    Honors ``jax.default_device`` scopes (returns "cpu" inside one even
    when a TPU plugin is installed) and only initializes the backend the
    caller is about to use anyway.
    """
    import jax.numpy as jnp

    return next(iter(jnp.zeros(()).devices())).platform
