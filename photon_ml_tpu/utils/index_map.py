"""Feature index maps: bidirectional name<->index lookup.

Reference: photon-ml .../util/IndexMap.scala:23-44 (getIndex /
getFeatureName), DefaultIndexMap(|Loader).scala (in-heap
collect-distinct-zipWithIndex build, GLMSuite.scala:160-187) and the
off-heap PalDBIndexMap.scala (partitioned stores + offsets) whose
TPU-native replacement is the mmap store in
photon_ml_tpu.utils.native_index (C++, built by FeatureIndexingJob analog).

Feature keys are ``name + "\\t" + term`` (Utils.getFeatureKey semantics);
the intercept uses ``("(INTERCEPT)", "")``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional

DELIMITER = "\t"
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""


def feature_key(name: str, term: str = "") -> str:
    """name TAB term (Utils.getFeatureKey)."""
    return f"{name}{DELIMITER}{term}"


def intercept_key() -> str:
    return feature_key(INTERCEPT_NAME, INTERCEPT_TERM)


def split_feature_key(key: str):
    name, _, term = key.partition(DELIMITER)
    return name, term


class IndexMap:
    """Bidirectional map feature-key <-> dense index."""

    def __init__(self, name_to_index: Dict[str, int]):
        self._fwd = name_to_index
        self._rev: Optional[List[Optional[str]]] = None

    def __len__(self) -> int:
        return len(self._fwd)

    def __contains__(self, key: str) -> bool:
        return key in self._fwd

    @property
    def size(self) -> int:
        return len(self._fwd)

    def get_index(self, key: str, default: int = -1) -> int:
        return self._fwd.get(key, default)

    def get_feature_name(self, index: int) -> Optional[str]:
        if self._rev is None:
            rev: List[Optional[str]] = [None] * (max(self._fwd.values(), default=-1) + 1)
            for k, i in self._fwd.items():
                rev[i] = k
            self._rev = rev
        if 0 <= index < len(self._rev):
            return self._rev[index]
        return None

    def items(self):
        return self._fwd.items()

    @staticmethod
    def build(
        keys: Iterable[str],
        *,
        add_intercept: bool = False,
    ) -> "IndexMap":
        """Deterministic build: sorted distinct keys -> [0, n)
        (the collect-distinct-zipWithIndex of GLMSuite.scala:160-187, made
        order-independent by sorting). The intercept, when requested, gets
        the LAST index so feature blocks stay contiguous."""
        distinct = sorted(set(keys) - {intercept_key()})
        fwd = {k: i for i, k in enumerate(distinct)}
        if add_intercept:
            fwd[intercept_key()] = len(distinct)
        return IndexMap(fwd)

    # -- persistence (a light text store; the native mmap store in
    #    utils/native_index.py handles the >200k-vocabulary PalDB case) ----

    def save(self, path: str) -> None:
        from photon_ml_tpu.reliability.artifacts import atomic_writer

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with atomic_writer(path, encoding="utf-8") as f:
            json.dump(self._fwd, f)

    @staticmethod
    def load(path: str):
        """-> IndexMap, or a reopened PartitionedIndexMap when the file is
        an offheap-store pointer written by PartitionedIndexMap.save (the
        driver's feature-index output under --offheap-indexmap-dir)."""
        with open(path, "r", encoding="utf-8") as f:
            data = json.load(f)
        if isinstance(data, dict) and "offheap_index_store" in data:
            from photon_ml_tpu.utils.native_index import PartitionedIndexMap

            return PartitionedIndexMap.from_pointer(data, path)
        if isinstance(data, dict) and "identity_index_map" in data:
            return IdentityIndexMap(
                int(data["identity_index_map"]),
                add_intercept=bool(data.get("add_intercept")),
            )
        return IndexMap(data)


class IdentityIndexMap:
    """Index map for pre-indexed data (IdentityIndexMapLoader analog):
    keys ARE stringified indices. ``add_intercept`` appends the intercept
    at the LAST index (the reference's trueFeatureDimension =
    featureDimension + 1, LibSVMInputDataFormat.scala:39)."""

    def __init__(self, size: int, *, add_intercept: bool = False):
        self._features = size
        self._size = size + (1 if add_intercept else 0)
        self._intercept = size if add_intercept else None

    def __len__(self) -> int:
        return self._size

    @property
    def size(self) -> int:
        return self._size

    def get_index(self, key: str, default: int = -1) -> int:
        if self._intercept is not None and key == intercept_key():
            return self._intercept
        name, _term = split_feature_key(key) if DELIMITER in key else (key, "")
        try:
            i = int(name)
        except ValueError:
            return default
        return i if 0 <= i < self._features else default

    def get_feature_name(self, index: int) -> Optional[str]:
        if self._intercept is not None and index == self._intercept:
            return intercept_key()
        if 0 <= index < self._features:
            return feature_key(str(index))
        return None

    def items(self):
        for i in range(self._features):
            yield feature_key(str(i)), i
        if self._intercept is not None:
            yield intercept_key(), self._intercept

    def save(self, path: str) -> None:
        """A small descriptor instead of materializing stringified
        indices; IndexMap.load reconstructs the identity map from it."""
        from photon_ml_tpu.reliability.artifacts import atomic_writer

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with atomic_writer(path, encoding="utf-8") as f:
            json.dump(
                {
                    "identity_index_map": self._features,
                    "add_intercept": self._intercept is not None,
                },
                f,
            )
