"""Tiny shared helper for the module-level bounded program caches.

Several layers memoize expensive-to-build jitted/compiled program
bundles keyed by hashable config tuples (optim/problem's fit cache, the
feature-sharded fit cache, the RE bucket-solver namespace cache). The
guard-hash + FIFO-evict + insert idiom lives here once so eviction or
key-policy fixes cannot drift between copies.
"""

from __future__ import annotations

from typing import Callable


def get_or_build(cache: dict, max_size: int, key, build: Callable):
    """Return ``cache[key]``, building (and FIFO-inserting) on miss.

    ``key`` may be unhashable (e.g. carries arrays), in which case the
    cache is bypassed and ``build()`` runs uncached. Pass the already-
    constructed key; pass ``None`` to force a bypass.
    """
    if key is not None:
        try:
            hash(key)
        except TypeError:
            key = None
    if key is None:
        return build()
    hit = cache.get(key)
    if hit is not None:
        return hit
    value = build()
    while len(cache) >= max_size:
        cache.pop(next(iter(cache)))
    cache[key] = value
    return value
