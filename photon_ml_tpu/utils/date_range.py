"""Date ranges as dataset coordinates + dated input-path expansion.

Reference: photon-ml .../util/DateRange.scala (range strings
``yyyyMMdd-yyyyMMdd`` and days-ago strings ``start-end``, start must not be
after end) and util/IOUtils.scala:84-130 ``getInputPathsWithinDateRange``
(expand ``<inputDir>/daily/yyyy/MM/dd`` per day, filter missing paths,
require at least one, optionally error on any missing).

Host-side only — this feeds the input pipeline before anything touches a
device.
"""

from __future__ import annotations

import datetime as _dt
import os
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Union

_PATTERN = "%Y%m%d"  # joda "yyyyMMdd"


@dataclass(frozen=True)
class DateRange:
    """Immutable inclusive [start, end] date range."""

    start: _dt.date
    end: _dt.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"Invalid range: start date {self.start} comes after end "
                f"date {self.end}."
            )

    def __str__(self) -> str:
        return f"{self.start.isoformat()}-{self.end.isoformat()}"

    def days(self) -> Iterator[_dt.date]:
        d = self.start
        while d <= self.end:
            yield d
            d += _dt.timedelta(days=1)

    @staticmethod
    def from_dates(range_str: str, pattern: str = _PATTERN) -> "DateRange":
        """Parse ``yyyyMMdd-yyyyMMdd`` (DateRange.fromDates)."""
        start_s, end_s = _split_range(range_str)
        try:
            start = _dt.datetime.strptime(start_s, pattern).date()
            end = _dt.datetime.strptime(end_s, pattern).date()
        except ValueError as e:
            raise ValueError(
                f"Couldn't parse the date range: {range_str}"
            ) from e
        return DateRange(start, end)

    @staticmethod
    def from_days_ago(
        range_str: str, now: Optional[_dt.date] = None
    ) -> "DateRange":
        """Parse ``startDaysAgo-endDaysAgo`` (e.g. ``90-1``),
        relative to ``now`` (DateRange.fromDaysAgo)."""
        start_s, end_s = _split_range(range_str)
        try:
            start_ago, end_ago = int(start_s), int(end_s)
        except ValueError as e:
            raise ValueError(
                f"Start days ago ({start_s}) and end days ago ({end_s}) "
                "must be valid integers."
            ) from e
        if start_ago < 0 or end_ago < 0:
            raise ValueError("Days ago cannot be negative.")
        now = now if now is not None else _dt.date.today()
        return DateRange(
            now - _dt.timedelta(days=start_ago),
            now - _dt.timedelta(days=end_ago),
        )


def _split_range(range_str: str) -> tuple:
    parts = range_str.split("-")
    if len(parts) != 2:
        raise ValueError(
            f"Couldn't parse the range: {range_str}. Be sure to separate "
            "two values with '-'."
        )
    return parts[0], parts[1]


def resolve_date_range(
    date_range: Optional[str],
    date_range_days_ago: Optional[str],
    now: Optional[_dt.date] = None,
) -> Optional[DateRange]:
    """Driver-param policy: at most one of the two forms may be given
    (cli/game/training/Params.scala exposes both; specifying both is
    ambiguous and rejected here)."""
    if date_range and date_range_days_ago:
        raise ValueError(
            "specify at most one of date-range and date-range-days-ago"
        )
    if date_range:
        return DateRange.from_dates(date_range)
    if date_range_days_ago:
        return DateRange.from_days_ago(date_range_days_ago, now=now)
    return None


def daily_path(base_dir: str, day: _dt.date) -> str:
    """``<base>/daily/yyyy/MM/dd`` (IOUtils' dailyDir layout)."""
    return os.path.join(
        base_dir, "daily", f"{day.year:04d}", f"{day.month:02d}",
        f"{day.day:02d}",
    )


def input_paths_within_date_range(
    input_dirs: Union[str, Sequence[str]],
    date_range: DateRange,
    *,
    error_on_missing: bool = False,
) -> List[str]:
    """Expand base dirs to their existing daily paths within the range.

    Mirrors IOUtils.getInputPathsWithinDateRange: one path per day under
    ``<dir>/daily/yyyy/MM/dd``; with ``error_on_missing`` every day must
    exist, otherwise missing days are skipped; zero surviving paths for a
    base dir is an error either way.
    """
    if isinstance(input_dirs, str):
        input_dirs = [input_dirs]
    out: List[str] = []
    for base in input_dirs:
        paths = [daily_path(base, day) for day in date_range.days()]
        if error_on_missing:
            for p in paths:
                if not os.path.exists(p):
                    raise FileNotFoundError(f"Path {p} does not exist!")
        existing = [p for p in paths if os.path.exists(p)]
        if not existing:
            raise FileNotFoundError(
                f"No data folder found between {date_range.start} and "
                f"{date_range.end} in {os.path.join(base, 'daily')}"
            )
        out.extend(existing)
    return out


def expand_dated_paths(dirs, date_range, days_ago, logger=None):
    """Input dirs -> daily paths when a range is configured
    (IOUtils.getInputPathsWithinDateRange), identity otherwise; shared by
    the GLM/GAME training and scoring drivers."""
    rng = resolve_date_range(date_range, days_ago)
    dirs = list(dirs)
    if rng is None:
        return dirs
    paths = input_paths_within_date_range(dirs, rng)
    if logger is not None:
        logger.info(
            "date range %s expanded %d dir(s) to %d daily paths",
            rng, len(dirs), len(paths),
        )
    return paths
