"""Logger + stage timers.

Reference: photon-ml .../util/PhotonLogger.scala:36-105 (SLF4J-style logger
writing to a local file, copied to the job dir on close) and
util/Timer.scala:32-80 (explicit start/stop nanosecond timers wrapping every
driver stage, cli/game/training/Driver.scala:642-712).
"""

from __future__ import annotations

import logging
import os
import time
from contextlib import contextmanager
from typing import Dict, Optional


class PhotonLogger:
    """File+console logger bound to a job output directory."""

    def __init__(self, output_dir: Optional[str] = None, name: str = "photon-ml-tpu",
                 level: int = logging.DEBUG):
        self._logger = logging.getLogger(f"{name}-{id(self)}")
        self._logger.setLevel(level)
        self._logger.propagate = False
        fmt = logging.Formatter("%(asctime)s %(levelname)s %(message)s")
        sh = logging.StreamHandler()
        sh.setFormatter(fmt)
        self._logger.addHandler(sh)
        self._file_handler = None
        if output_dir is not None:
            os.makedirs(output_dir, exist_ok=True)
            fh = logging.FileHandler(os.path.join(output_dir, "photon.log"))
            fh.setFormatter(fmt)
            self._logger.addHandler(fh)
            self._file_handler = fh

    def debug(self, msg, *args):
        self._logger.debug(msg, *args)

    def info(self, msg, *args):
        self._logger.info(msg, *args)

    def warning(self, msg, *args):
        self._logger.warning(msg, *args)

    def error(self, msg, *args):
        self._logger.error(msg, *args)

    def close(self):
        if self._file_handler is not None:
            self._logger.removeHandler(self._file_handler)
            self._file_handler.close()
            self._file_handler = None


class Timer:
    """Named stage timers; durations in seconds (Timer.scala analog)."""

    def __init__(self):
        self._starts: Dict[str, float] = {}
        self.durations: Dict[str, float] = {}

    def start(self, name: str) -> None:
        if name in self._starts:
            raise RuntimeError(f"timer {name!r} already running")
        self._starts[name] = time.perf_counter()

    def stop(self, name: str) -> float:
        if name not in self._starts:
            raise RuntimeError(f"timer {name!r} not running")
        d = time.perf_counter() - self._starts.pop(name)
        self.durations[name] = self.durations.get(name, 0.0) + d
        return d

    @contextmanager
    def time(self, name: str):
        self.start(name)
        try:
            yield
        finally:
            self.stop(name)

    def summary(self) -> str:
        return "\n".join(
            f"  {k}: {v:.3f}s" for k, v in sorted(self.durations.items())
        )
