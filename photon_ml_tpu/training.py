"""GLM training orchestration: regularization-path with warm starts.

Reference: photon-ml ModelTraining.scala:103-215 —
``trainGeneralizedLinearModel`` builds one loss function + one optimization
problem per task (:123-169), sorts the regularization weights DESCENDING
(:172) and folds over them reusing the previous lambda's coefficients as the
warm start (:183-208). One problem object is reused across the grid; here
that means one XLA compilation serves the entire path (reg weight is a
runtime scalar).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.obs.trace import span as obs_span
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.optim.common import BoxConstraints, OptResult
from photon_ml_tpu.optim.config import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_ml_tpu.optim.problem import create_glm_problem, resolve_kernel
from photon_ml_tpu.task import TaskType

Array = jnp.ndarray


# -- λ-grid crash-safe snapshots (reliability.GridCheckpointer) ---------------
#
# One snapshot per COMPLETED λ: warm-start means (optimization space —
# the currency the next λ's solve starts from, so a resumed sweep walks
# bitwise the same iterate chain), the exported model (original space),
# and the OptResult arrays. kill -9 mid-λ loses only that λ's solve; the
# restart re-solves it from the SAME warm start and continues.


def _snapshot_result_arrays(result: OptResult) -> Dict[str, object]:
    import numpy as np

    t = result.tracker
    arrs = {
        "coefficients": np.asarray(result.coefficients),
        "value": np.asarray(result.value),
        "grad_norm": np.asarray(result.grad_norm),
        "iterations": np.asarray(result.iterations),
        "reason": np.asarray(result.reason),
        "tracker_values": np.asarray(t.values),
        "tracker_grad_norms": np.asarray(t.grad_norms),
        "tracker_count": np.asarray(t.count),
    }
    if t.coefs is not None:
        arrs["tracker_coefs"] = np.asarray(t.coefs)
    return arrs


def _result_from_snapshot(d: Dict[str, object]) -> OptResult:
    from photon_ml_tpu.optim.common import Tracker

    coefs = d.get("tracker_coefs")
    return OptResult(
        coefficients=jnp.asarray(d["coefficients"]),
        value=jnp.asarray(d["value"]),
        grad_norm=jnp.asarray(d["grad_norm"]),
        iterations=jnp.asarray(d["iterations"]),
        reason=jnp.asarray(d["reason"]),
        tracker=Tracker(
            values=jnp.asarray(d["tracker_values"]),
            grad_norms=jnp.asarray(d["tracker_grad_norms"]),
            count=jnp.asarray(d["tracker_count"]),
            coefs=jnp.asarray(coefs) if coefs is not None else None,
        ),
    )


def _model_from_snapshot(
    task: TaskType, snap: Dict[str, object]
) -> GeneralizedLinearModel:
    from photon_ml_tpu.models.coefficients import Coefficients

    var = snap.get("model_variances")
    return GeneralizedLinearModel(
        task,
        Coefficients(
            jnp.asarray(snap["model_means"]),
            jnp.asarray(var) if var is not None else None,
        ),
    )


def _save_lambda_snapshot(
    checkpointer, lam: float, warm_means, model, result: OptResult
) -> None:
    import numpy as np

    checkpointer.save(
        lam,
        warm_means=np.asarray(warm_means),
        model_means=np.asarray(model.means),
        model_variances=(
            np.asarray(model.coefficients.variances)
            if model.coefficients.variances is not None
            else None
        ),
        result_arrays=_snapshot_result_arrays(result),
    )


def train_generalized_linear_model(
    batch: Batch,
    task: TaskType,
    dim: int,
    *,
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    regularization_type: RegularizationType = RegularizationType.NONE,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: Optional[float] = None,
    max_iter: Optional[int] = None,
    tolerance: Optional[float] = None,
    normalization: Optional[NormalizationContext] = None,
    warm_start: bool = True,
    compute_variances: bool = False,
    box: Optional[BoxConstraints] = None,
    intercept_index: Optional[int] = None,
    axis_name: Optional[str] = None,
    initial: Optional[Array] = None,
    kernel: str = "scatter",
    mesh=None,
    track_models: bool = False,
    tile_cache_dir: Optional[str] = None,
    grid_checkpointer=None,
    preemption_guard=None,
) -> Tuple[Dict[float, GeneralizedLinearModel], Dict[float, OptResult]]:
    """Train one model per regularization weight with warm starts.

    Returns ({lambda: model}, {lambda: OptResult}) — models are in the
    ORIGINAL feature space (normalization un-done), matching
    ModelTraining.trainGeneralizedLinearModel's contract.

    ``kernel``: "scatter" | "tiled" | "auto" — objective implementation
    (see optim.problem.resolve_kernel). The tiled schedule is built once
    here and amortized across the whole lambda grid.

    ``mesh``: a jax.sharding.Mesh for data-parallel training — the whole
    L-BFGS/OWLQN/TRON loop runs under shard_map with the batch sharded
    over the "data" axis (the treeAggregate analog). The tiled kernel
    composes: per-device-shard schedules are built once and the Pallas
    kernels run unmodified inside shard_map (no scatter fallback).

    ``track_models``: stack per-iteration coefficients into each
    OptResult's ``tracker.coefs`` (ModelTracker analog). Use
    :func:`iteration_models` to turn a result into per-iteration models
    in the original feature space.

    ``tile_cache_dir``: persistent content-addressed schedule cache
    directory (ops/schedule_cache.py) for the tiled conversion — a warm
    rerun over the same dataset loads the schedules instead of
    rebuilding. None falls back to the process configuration /
    PHOTON_TILE_CACHE_DIR env var (unset = off).

    ``grid_checkpointer`` (reliability.GridCheckpointer): per-λ
    crash-safe snapshots — completed λs load instead of re-solving, and
    the resumed sweep warm-starts from the snapshotted means, so the
    final models are bitwise what an uninterrupted run produces.
    ``preemption_guard``: a SIGTERM stops the sweep BEFORE the next λ's
    solve (the λ boundary is the safe point); already-solved λs are
    checkpointed and returned.
    """
    base = OptimizerConfig.default_for(optimizer_type)
    config = OptimizerConfig(
        optimizer_type=optimizer_type,
        max_iter=max_iter if max_iter is not None else base.max_iter,
        tolerance=tolerance if tolerance is not None else base.tolerance,
        lbfgs_history=base.lbfgs_history,
        tron_max_cg=base.tron_max_cg,
    )
    regularization = RegularizationContext(regularization_type, elastic_net_alpha)
    kernel = resolve_kernel(kernel, batch)
    if mesh is not None and kernel != "tiled":
        # shard (and row-pad) once; every lambda reuses the device copies
        from photon_ml_tpu.parallel.mesh import ensure_data_sharded

        batch = ensure_data_sharded(batch, mesh)
    if kernel == "tiled":
        from photon_ml_tpu.data.batch import SparseBatch
        from photon_ml_tpu.ops.schedule_cache import cache_scope
        from photon_ml_tpu.ops.tiled_sparse import (
            TiledSparseBatch,
            ensure_tiled_sharded,
            tiled_batch_from_sparse,
        )

        with cache_scope(tile_cache_dir):
            if mesh is not None:
                # per-device-shard schedules built once here; the whole
                # lambda grid (and problem.run's idempotent ensure) reuses
                # them — tiled and distributed compose, no scatter fallback
                if not isinstance(batch, (SparseBatch, TiledSparseBatch)):
                    raise TypeError(
                        "kernel='tiled' requires a SparseBatch or "
                        f"TiledSparseBatch, got {type(batch).__name__}; use "
                        "kernel='scatter' for dense batches"
                    )
                batch = ensure_tiled_sharded(batch, dim, mesh)
            elif isinstance(batch, SparseBatch):
                batch = tiled_batch_from_sparse(batch, dim)
            elif not isinstance(batch, TiledSparseBatch):
                raise TypeError(
                    "kernel='tiled' requires a SparseBatch or "
                    f"TiledSparseBatch, got {type(batch).__name__}; use "
                    "kernel='scatter' for dense batches"
                )
    problem = create_glm_problem(
        task,
        dim,
        config=config,
        regularization=regularization,
        norm=normalization,
        axis_name=axis_name,
        compute_variances=compute_variances,
        box=box,
        intercept_index=intercept_index,
        kernel=kernel,
    )

    # Descending order: strongest regularization first, so each warm start
    # relaxes an already-shrunk model (ModelTraining.scala:172).
    weights_desc: List[float] = sorted(set(float(w) for w in regularization_weights), reverse=True)

    models: Dict[float, GeneralizedLinearModel] = {}
    results: Dict[float, OptResult] = {}
    current = initial
    for lam in weights_desc:
        snap = (
            grid_checkpointer.load(lam)
            if grid_checkpointer is not None
            else None
        )
        if snap is not None:
            # completed in a previous (interrupted) run: restore instead
            # of re-solving; the snapshotted warm means keep the iterate
            # chain bitwise identical for the λs still to solve
            models[lam] = _model_from_snapshot(task, snap)
            results[lam] = _result_from_snapshot(snap["result"])
            if warm_start:
                current = jnp.asarray(snap["warm_means"])
            continue
        if preemption_guard is not None and preemption_guard.requested:
            # stop at the λ boundary: solved λs are snapshotted; the
            # restarted run resumes the sweep here
            break
        with obs_span("glm.lambda_solve", reg_weight=lam):
            coefficients, result = problem.run(
                batch, initial=current, reg_weight=lam, mesh=mesh,
                track_models=track_models,
            )
        models[lam] = problem.create_model(coefficients, normalization)
        results[lam] = result
        if grid_checkpointer is not None:
            _save_lambda_snapshot(
                grid_checkpointer, lam, coefficients.means,
                models[lam], result,
            )
        if warm_start:
            current = coefficients.means
    return models, results


# Default host-memory budget for the batched grid's coefficient bank +
# vmapped optimizer state ("auto" falls back to the warm-started
# sequential path above it). 1 GiB leaves the usual batch-dominated HBM
# headroom on every supported device class.
DEFAULT_GRID_MEMORY_BUDGET = 1 << 30


def grid_bank_bytes(
    num_weights: int,
    dim: int,
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    history: int = 10,
    entity_shards: int = 1,
) -> int:
    """Estimated PER-DEVICE bytes for the batched grid's [G, d]
    coefficient bank plus the vmapped optimizer's per-member state
    (L-BFGS memory is the dominant term: the [m, d] s/y buffers; TRON
    carries the CG vectors instead). Under the unified mesh's
    P(grid, entity) placement the bank rows split over ``entity_shards``
    devices, so each device holds ~1/N of the replicated-bank
    footprint; ``entity_shards=1`` is the replicated/1-D figure."""
    if optimizer_type == OptimizerType.TRON:
        vectors_per_member = 12  # w, g + CG s/r/d/hd + trial w/g + slack
    else:
        vectors_per_member = 2 * history + 8
    total = int(num_weights) * vectors_per_member * int(dim) * 4
    return -(-total // max(1, int(entity_shards)))


def resolve_grid_mode(
    mode: str,
    *,
    num_weights: int,
    dim: int,
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    history: int = 10,
    memory_budget_bytes: int = DEFAULT_GRID_MEMORY_BUDGET,
    streaming: bool = False,
    entity_shards: int = 1,
) -> str:
    """Resolve ``--grid-mode {batched,sequential,auto}`` to a concrete
    path. ``auto`` picks batched when the grid has >1 member, the data
    fits in memory (not streaming — out-of-core stays the warm-started
    sequential default), and the G×d state bank fits the budget;
    everything else falls back to sequential. An explicit ``batched``
    with streaming input is a configuration error (the host-driven
    streamed optimizers cannot vmap over disk passes).

    ``entity_shards`` feeds the unified-mesh accounting: under
    P(grid, entity) each device holds ~1/N of the bank, so the budget
    comparison uses the per-device figure (grid_bank_bytes)."""
    if mode not in ("batched", "sequential", "auto"):
        raise ValueError(
            f"unknown grid mode {mode!r}; expected batched | sequential "
            "| auto"
        )
    if mode == "sequential":
        return "sequential"
    if streaming:
        if mode == "batched":
            raise ValueError(
                "--grid-mode batched is incompatible with streaming "
                "input: the streamed objectives evaluate through host "
                "IO, which the single vmapped optimizer program cannot "
                "trace; use sequential or auto"
            )
        return "sequential"
    if mode == "batched":
        return "batched"
    if num_weights <= 1:
        return "sequential"
    bank = grid_bank_bytes(
        num_weights, dim, optimizer_type, history, entity_shards
    )
    return "batched" if bank <= memory_budget_bytes else "sequential"


def resolve_entity_shards(
    requested: Optional[int],
    *,
    num_devices: Optional[int] = None,
) -> Optional[int]:
    """Resolve the GAME driver's ``--entity-shards`` to a concrete
    entity-mesh size (pod-scale GAME, game/pod.py), or None for the
    replicated bank path.

    ``None``/``0`` keeps the replicated default (entity sharding is
    opt-in: the sharded path changes the bank's device layout, so the
    operator asks for it explicitly); ``-1`` means "every visible
    device"; an explicit N must fit the device count. N == 1 is valid —
    the single-shard pod path, the parity baseline the weak-scaling
    tests anchor on."""
    if requested is None or requested == 0:
        return None
    import jax

    n_dev = num_devices if num_devices is not None else len(jax.devices())
    if requested == -1:
        return n_dev
    if not 1 <= requested <= n_dev:
        raise ValueError(
            f"--entity-shards {requested} out of range for {n_dev} "
            "visible devices (use -1 for all devices, 0 to disable)"
        )
    return int(requested)


def train_grid_batched(
    batch: Batch,
    task: TaskType,
    dim: int,
    *,
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    regularization_type: RegularizationType = RegularizationType.NONE,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: Optional[float] = None,
    max_iter: Optional[int] = None,
    tolerance: Optional[float] = None,
    normalization: Optional[NormalizationContext] = None,
    compute_variances: bool = False,
    box: Optional[BoxConstraints] = None,
    intercept_index: Optional[int] = None,
    initial: Optional[Array] = None,
    kernel: str = "scatter",
    mesh=None,
    track_models: bool = False,
    tile_cache_dir: Optional[str] = None,
    grid_checkpointer=None,
) -> Tuple[Dict[float, GeneralizedLinearModel], Dict[float, OptResult]]:
    """Batched λ-grid twin of :func:`train_generalized_linear_model`:
    the grid stacks into a [G, d] coefficient bank and ONE jitted
    ``vmap(minimize_lbfgs/owlqn/tron)`` over a grid-batched objective
    solves every λ simultaneously — G compiles + G optimizer loops + G
    readback rounds become 1/1/1 (the final 1 via
    :func:`grid_result_scalars`' single batched fetch).

    The data pass is fused across the grid: the scatter objective's
    sparse matvec batches into one (n×d)@(d×G)-shaped gather/contract
    under vmap, and the tiled objective reuses its tile schedule (and
    the persistent schedule cache) ONCE for the whole grid via the flat
    grid pass (ops.tiled_sparse._grid_bilinear_pass). Box constraints,
    normalization and offsets broadcast across the grid member axis.
    Per-λ convergence is active-masked inside the while_loop carry:
    converged members freeze bit-stable while stragglers run on.

    There are NO warm starts between members (each λ starts from
    ``initial``) — that is the trade against the sequential path; see
    README "Regularization paths". Returns the same
    ({lambda: model}, {lambda: OptResult}) contract as the sequential
    trainer; result scalars stay device-resident for the batched fetch.
    """
    from photon_ml_tpu.optim.common import Tracker

    base = OptimizerConfig.default_for(optimizer_type)
    config = OptimizerConfig(
        optimizer_type=optimizer_type,
        max_iter=max_iter if max_iter is not None else base.max_iter,
        tolerance=tolerance if tolerance is not None else base.tolerance,
        lbfgs_history=base.lbfgs_history,
        tron_max_cg=base.tron_max_cg,
    )
    regularization = RegularizationContext(regularization_type, elastic_net_alpha)
    kernel = resolve_kernel(kernel, batch)
    if mesh is not None and kernel != "tiled":
        from photon_ml_tpu.parallel.mesh import ensure_data_sharded

        batch = ensure_data_sharded(batch, mesh)
    if kernel == "tiled":
        from photon_ml_tpu.data.batch import SparseBatch
        from photon_ml_tpu.ops.schedule_cache import cache_scope
        from photon_ml_tpu.ops.tiled_sparse import (
            TiledSparseBatch,
            ensure_tiled_sharded,
            tiled_batch_from_sparse,
        )

        with cache_scope(tile_cache_dir):
            if mesh is not None:
                if not isinstance(batch, (SparseBatch, TiledSparseBatch)):
                    raise TypeError(
                        "kernel='tiled' requires a SparseBatch or "
                        f"TiledSparseBatch, got {type(batch).__name__}; use "
                        "kernel='scatter' for dense batches"
                    )
                batch = ensure_tiled_sharded(batch, dim, mesh)
            elif isinstance(batch, SparseBatch):
                batch = tiled_batch_from_sparse(batch, dim)
            elif not isinstance(batch, TiledSparseBatch):
                raise TypeError(
                    "kernel='tiled' requires a SparseBatch or "
                    f"TiledSparseBatch, got {type(batch).__name__}; use "
                    "kernel='scatter' for dense batches"
                )
    problem = create_glm_problem(
        task,
        dim,
        config=config,
        regularization=regularization,
        norm=normalization,
        compute_variances=compute_variances,
        box=box,
        intercept_index=intercept_index,
        kernel=kernel,
    )
    # Same descending order as the sequential path, so the returned dict
    # iterates identically — the order is cosmetic here (no warm starts).
    weights_desc: List[float] = sorted(
        set(float(w) for w in regularization_weights), reverse=True
    )
    if grid_checkpointer is not None and all(
        grid_checkpointer.has(lam) for lam in weights_desc
    ):
        # the whole grid solved in ONE vmapped program last run: the
        # snapshot unit is the completed grid (there is no per-λ
        # mid-solve boundary inside a single jitted while_loop), so a
        # restart after the solve skips it entirely
        models = {}
        results = {}
        for lam in weights_desc:
            snap = grid_checkpointer.load(lam)
            models[lam] = _model_from_snapshot(task, snap)
            results[lam] = _result_from_snapshot(snap["result"])
        return models, results
    with obs_span(
        "glm.grid_solve", grid=len(weights_desc), batched=True
    ):
        variances, result = problem.run_grid(
            batch, weights_desc, initial=initial, mesh=mesh,
            track_models=track_models,
        )

    from photon_ml_tpu.models.coefficients import Coefficients

    models: Dict[float, GeneralizedLinearModel] = {}
    results: Dict[float, OptResult] = {}
    for i, lam in enumerate(weights_desc):
        var_i = variances[i] if variances is not None else None
        coefficients = Coefficients(result.coefficients[i], var_i)
        models[lam] = problem.create_model(coefficients, normalization)
        tracker = result.tracker
        results[lam] = OptResult(
            coefficients=result.coefficients[i],
            value=result.value[i],
            grad_norm=result.grad_norm[i],
            iterations=result.iterations[i],
            reason=result.reason[i],
            tracker=Tracker(
                values=tracker.values[i],
                grad_norms=tracker.grad_norms[i],
                count=tracker.count[i],
                coefs=(
                    tracker.coefs[i] if tracker.coefs is not None else None
                ),
            ),
        )
        if grid_checkpointer is not None:
            _save_lambda_snapshot(
                grid_checkpointer, lam, result.coefficients[i],
                models[lam], results[lam],
            )
    return models, results


def train_feature_sharded(
    batch: Batch,
    task: TaskType,
    dim: int,
    *,
    mesh,
    regularization_type: RegularizationType = RegularizationType.NONE,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: Optional[float] = None,
    max_iter: Optional[int] = None,
    tolerance: Optional[float] = None,
    history: int = 10,
    warm_start: bool = True,
    normalization: Optional[NormalizationContext] = None,
    compute_variances: bool = False,
    box: Optional[BoxConstraints] = None,
    intercept_index: Optional[int] = None,
    kernel: str = "scatter",
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    track_models: bool = False,
    tile_cache_dir: Optional[str] = None,
) -> Tuple[Dict[float, GeneralizedLinearModel], Dict[float, OptResult]]:
    """Lambda grid over a FEATURE-SHARDED coefficient vector (the >HBM /
    10B-coefficient path, SURVEY §2.3 "coefficient parallelism").

    The mesh must be 2-D (data, model); the sparse batch is re-laid out
    once into per-feature-block slabs and every lambda reuses it. L1 and
    elastic-net run sharded OWL-QN; L2/none run sharded L-BFGS or (with
    ``optimizer_type=TRON``) sharded trust-region Newton whose truncated
    CG psums every inner product — the reference's
    one-treeAggregate-per-CG-iteration loop (SURVEY §3.2) on ICI. TRON
    runs the tiled kernels too: its Hv pass reuses the z/g schedules
    (tiled_block_local_hvp_factory).

    The reference composes normalization, variances, box constraints and
    per-iteration model tracking freely with distribution
    (NormalizationContext.scala:119-157 inside the aggregators,
    DistributedOptimizationProblem.scala:79-93, LBFGS.scala:77); here the
    shift/factor vectors shard along the feature axis (one extra psum'd
    scalar for the margin shift), the Hessian diagonal and box projection
    are block-local/elementwise, and ``track_models`` shards the
    per-iteration coefficient stack like the coefficients themselves.

    ``kernel``: "scatter" | "tiled" | "auto" — "tiled" lays each
    (data shard x feature block) cell out as block-local Pallas tile
    schedules, so the 10B-coefficient path runs the fast kernels instead
    of serialized gather/scatter (~7ns/element).
    """
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.glm import create_model
    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.parallel.distributed import (
        feature_shard_sparse_batch,
        feature_sharded_glm_fit,
        feature_sharded_hessian_diagonal,
    )
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    if not isinstance(batch, SparseBatch):
        raise TypeError(
            "feature-sharded training requires a SparseBatch, got "
            f"{type(batch).__name__}"
        )
    if MODEL_AXIS not in mesh.axis_names or DATA_AXIS not in mesh.axis_names:
        raise ValueError(
            f"feature-sharded training needs a (data, model) mesh, got "
            f"axes {mesh.axis_names}"
        )
    num_blocks = int(mesh.shape[MODEL_AXIS])
    data_shards = int(mesh.shape[DATA_AXIS])
    from photon_ml_tpu.optim.factory import validate_optimizer_choice

    regularization = RegularizationContext(regularization_type, elastic_net_alpha)
    objective = GLMObjective(loss_for_task(task), dim)
    use_tron = optimizer_type == OptimizerType.TRON
    use_owlqn = regularization.has_l1
    # shared TRON x regularization / loss-smoothness rules
    # (OptimizerFactory.scala:49-86)
    base = OptimizerConfig.default_for(optimizer_type)
    max_iter = max_iter if max_iter is not None else base.max_iter
    tolerance = tolerance if tolerance is not None else base.tolerance
    validate_optimizer_choice(
        OptimizerConfig(optimizer_type=optimizer_type),
        regularization,
        loss_has_hessian=objective.loss.has_hessian,
    )
    kernel = resolve_kernel(kernel, batch)
    with_norm = normalization is not None and not normalization.is_identity

    if kernel == "tiled":
        from photon_ml_tpu.ops.schedule_cache import cache_scope
        from photon_ml_tpu.ops.tiled_sparse import feature_shard_tiled_batch

        with cache_scope(tile_cache_dir):
            sharded, block_dim = feature_shard_tiled_batch(
                batch, dim, data_shards, num_blocks, mesh=mesh,
                data_axis=DATA_AXIS, model_axis=MODEL_AXIS,
            )
        meta = sharded.meta
    else:
        sharded, block_dim = feature_shard_sparse_batch(
            batch, dim, num_blocks, rows_multiple=data_shards
        )
        meta = None
    optimizer = "tron" if use_tron else ("owlqn" if use_owlqn else "lbfgs")
    layout = "tiled" if kernel == "tiled" else "sparse"
    fit = feature_sharded_glm_fit(
        objective, mesh, meta, layout=layout, optimizer=optimizer,
        max_iter=max_iter, tol=tolerance, history=history,
        with_norm=with_norm, with_box=box is not None,
        track_models=track_models,
    )
    d_pad = num_blocks * block_dim
    from photon_ml_tpu.parallel.distributed import feature_sharded_extras

    extras_tail, l1_mask, _ = feature_sharded_extras(
        dim, d_pad, normalization=normalization, box=box,
        use_owlqn=use_owlqn, intercept_index=intercept_index,
    )

    hdiag_fn = None
    if compute_variances:
        hdiag_fn = feature_sharded_hessian_diagonal(
            objective, mesh, meta, layout=layout, with_norm=with_norm,
        )
        norm_extras = extras_tail[:2] if with_norm else []

    def _to_original_space(means):
        """De-normalize back to the raw feature space, exactly like
        GLMOptimizationProblem.create_model
        (GeneralizedLinearOptimizationProblem.scala:89-95)."""
        if not with_norm:
            return means
        orig = normalization.model_to_original_space(means)
        if intercept_index is not None:
            orig = orig.at[intercept_index].add(
                normalization.intercept_adjustment(means)
            )
        return orig

    weights_desc = sorted(set(float(w) for w in regularization_weights), reverse=True)
    models: Dict[float, GeneralizedLinearModel] = {}
    results: Dict[float, OptResult] = {}
    current = jnp.zeros((d_pad,), jnp.float32)
    for lam in weights_desc:
        l1, l2 = regularization.split(lam)
        extras = (
            [jnp.float32(l1), l1_mask] if use_owlqn else []
        ) + extras_tail
        result = fit(current, sharded, jnp.float32(l2), *extras)
        variances = None
        if hdiag_fn is not None:
            from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

            hd = hdiag_fn(
                result.coefficients, sharded, jnp.float32(l2), *norm_extras
            )
            variances = (1.0 / (hd + _VARIANCE_EPSILON))[:dim]
        models[lam] = create_model(
            task,
            Coefficients(
                _to_original_space(result.coefficients[:dim]), variances
            ),
        )
        # Results carry REAL-dimension coefficients (and tracked models),
        # consistent with the replicated path; the padded vector is only
        # the warm-start currency.
        tracker = result.tracker
        if tracker.coefs is not None:
            tracker = tracker._replace(coefs=tracker.coefs[:, :dim])
        results[lam] = result._replace(
            coefficients=result.coefficients[:dim], tracker=tracker
        )
        if warm_start:
            current = result.coefficients
    return models, results


def train_grid_batched_feature_sharded(
    batch: Batch,
    task: TaskType,
    dim: int,
    *,
    mesh,
    regularization_type: RegularizationType = RegularizationType.NONE,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: Optional[float] = None,
    max_iter: Optional[int] = None,
    tolerance: Optional[float] = None,
    history: int = 10,
    normalization: Optional[NormalizationContext] = None,
    compute_variances: bool = False,
    box: Optional[BoxConstraints] = None,
    intercept_index: Optional[int] = None,
    kernel: str = "scatter",
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    track_models: bool = False,
    tile_cache_dir: Optional[str] = None,
) -> Tuple[Dict[float, GeneralizedLinearModel], Dict[float, OptResult]]:
    """Batched λ-grid twin of :func:`train_feature_sharded`: the grid
    stacks into a [G, d_pad] bank whose feature axis shards over the
    (data, model) mesh while the grid axis is vmapped INSIDE the
    shard_map body — one compiled program, one optimizer loop, one
    schedule layout for every λ (sparse and tiled layouts both; the
    tiled cells ride the fused grid pass). No cross-member warm starts
    (each λ starts from zero), same trade as :func:`train_grid_batched`.
    """
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import SparseBatch
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.glm import create_model
    from photon_ml_tpu.ops.losses import loss_for_task
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim.common import Tracker
    from photon_ml_tpu.optim.factory import validate_optimizer_choice
    from photon_ml_tpu.parallel.distributed import (
        feature_shard_sparse_batch,
        feature_sharded_extras,
        feature_sharded_glm_fit,
        feature_sharded_hessian_diagonal,
    )
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    if not isinstance(batch, SparseBatch):
        raise TypeError(
            "feature-sharded training requires a SparseBatch, got "
            f"{type(batch).__name__}"
        )
    if MODEL_AXIS not in mesh.axis_names or DATA_AXIS not in mesh.axis_names:
        raise ValueError(
            f"feature-sharded training needs a (data, model) mesh, got "
            f"axes {mesh.axis_names}"
        )
    num_blocks = int(mesh.shape[MODEL_AXIS])
    data_shards = int(mesh.shape[DATA_AXIS])
    regularization = RegularizationContext(regularization_type, elastic_net_alpha)
    objective = GLMObjective(loss_for_task(task), dim)
    use_tron = optimizer_type == OptimizerType.TRON
    use_owlqn = regularization.has_l1
    base = OptimizerConfig.default_for(optimizer_type)
    max_iter = max_iter if max_iter is not None else base.max_iter
    tolerance = tolerance if tolerance is not None else base.tolerance
    validate_optimizer_choice(
        OptimizerConfig(optimizer_type=optimizer_type),
        regularization,
        loss_has_hessian=objective.loss.has_hessian,
    )
    kernel = resolve_kernel(kernel, batch)
    with_norm = normalization is not None and not normalization.is_identity

    if kernel == "tiled":
        from photon_ml_tpu.ops.schedule_cache import cache_scope
        from photon_ml_tpu.ops.tiled_sparse import feature_shard_tiled_batch

        with cache_scope(tile_cache_dir):
            sharded, block_dim = feature_shard_tiled_batch(
                batch, dim, data_shards, num_blocks, mesh=mesh,
                data_axis=DATA_AXIS, model_axis=MODEL_AXIS,
            )
        meta = sharded.meta
    else:
        sharded, block_dim = feature_shard_sparse_batch(
            batch, dim, num_blocks, rows_multiple=data_shards
        )
        meta = None
    optimizer = "tron" if use_tron else ("owlqn" if use_owlqn else "lbfgs")
    layout = "tiled" if kernel == "tiled" else "sparse"
    fit = feature_sharded_glm_fit(
        objective, mesh, meta, layout=layout, optimizer=optimizer,
        max_iter=max_iter, tol=tolerance, history=history,
        with_norm=with_norm, with_box=box is not None,
        track_models=track_models, grid=True,
    )
    d_pad = num_blocks * block_dim
    extras_tail, l1_mask, _ = feature_sharded_extras(
        dim, d_pad, normalization=normalization, box=box,
        use_owlqn=use_owlqn, intercept_index=intercept_index,
    )

    hdiag_fn = None
    if compute_variances:
        hdiag_fn = feature_sharded_hessian_diagonal(
            objective, mesh, meta, layout=layout, with_norm=with_norm,
        )
        norm_extras = extras_tail[:2] if with_norm else []

    def _to_original_space(means):
        if not with_norm:
            return means
        orig = normalization.model_to_original_space(means)
        if intercept_index is not None:
            orig = orig.at[intercept_index].add(
                normalization.intercept_adjustment(means)
            )
        return orig

    weights_desc = sorted(
        set(float(w) for w in regularization_weights), reverse=True
    )
    G = len(weights_desc)
    splits = [regularization.split(w) for w in weights_desc]
    l1_vec = jnp.asarray([s[0] for s in splits], jnp.float32)
    l2_vec = jnp.asarray([s[1] for s in splits], jnp.float32)
    w0_bank = jnp.zeros((G, d_pad), jnp.float32)
    extras = ([l1_vec, l1_mask] if use_owlqn else []) + extras_tail
    result = fit(w0_bank, sharded, l2_vec, *extras)

    models: Dict[float, GeneralizedLinearModel] = {}
    results: Dict[float, OptResult] = {}
    tracker = result.tracker
    for i, lam in enumerate(weights_desc):
        coefs_pad = result.coefficients[i]
        variances = None
        if hdiag_fn is not None:
            from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

            hd = hdiag_fn(coefs_pad, sharded, l2_vec[i], *norm_extras)
            variances = (1.0 / (hd + _VARIANCE_EPSILON))[:dim]
        models[lam] = create_model(
            task,
            Coefficients(_to_original_space(coefs_pad[:dim]), variances),
        )
        results[lam] = OptResult(
            coefficients=coefs_pad[:dim],
            value=result.value[i],
            grad_norm=result.grad_norm[i],
            iterations=result.iterations[i],
            reason=result.reason[i],
            tracker=Tracker(
                values=tracker.values[i],
                grad_norms=tracker.grad_norms[i],
                count=tracker.count[i],
                coefs=(
                    tracker.coefs[i][:, :dim]
                    if tracker.coefs is not None else None
                ),
            ),
        )
    return models, results


def train_streaming_glm(
    paths,
    task: TaskType,
    *,
    regularization_type: RegularizationType = RegularizationType.NONE,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: Optional[float] = None,
    max_iter: Optional[int] = None,
    tolerance: Optional[float] = None,
    history: int = 10,
    rows_per_chunk: int = 65536,
    cache_bytes: int = 2 << 30,
    prefetch: bool = True,
    kernel: str = "auto",
    tile_params=None,
    add_intercept: bool = True,
    field_names: str = "TRAINING_EXAMPLE",
    warm_start: bool = True,
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    normalization: Optional[NormalizationContext] = None,
    compute_variances: bool = False,
    box: Optional[BoxConstraints] = None,
    track_models: bool = False,
    fmt=None,
    index_map=None,
    stats=None,
    tile_cache_dir: Optional[str] = None,
    grid_checkpointer=None,
    preemption_guard=None,
    initial: Optional[Array] = None,
):
    """Train a GLM over Avro inputs LARGER than host RAM: every objective
    evaluation streams fixed-shape chunks from disk (io/streaming.py), so
    peak memory is bounded by one decoded file + one staged chunk. The
    host-driven L-BFGS (optim/host_lbfgs.py) walks the same iterate
    sequence as the in-memory path.

    The reference's analog is Spark's MEMORY_AND_DISK persist under
    GLMSuite.readLabeledPointsFromAvro (io/GLMSuite.scala:98-131): the
    first evaluation caches staged chunks — device-resident up to
    ``cache_bytes``, the remainder spilled as raw fixed-shape arrays to
    local scratch — so later evaluations never re-decode Avro;
    ``prefetch`` decode-aheads on a worker thread. L1/elastic-net run
    host-driven OWL-QN (minimize_owlqn_host) with the intercept exempt
    from the penalty, exactly like the in-memory path.

    Works over Avro (native chunked column decode) or LibSVM text
    (line-at-a-time) inputs — pass the matching ``fmt``; both formats
    implement the streaming protocol (stream_files/stream_rows/
    stream_scan), like the reference streams both through GLMSuite.

    Under ``jax.distributed`` (process_count > 1) the input FILES split
    across processes (multihost.process_shard — the executor-partition
    analog) and every evaluation's (value, gradient) partials reduce
    across hosts, so each host only ever reads its shard; this requires a
    PREBUILT shared index map (the FeatureIndexingJob store) because no
    single process sees the whole vocabulary.

    Returns ({lambda: model}, {lambda: OptResult}, index_map).
    """
    import numpy as np
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.io.input_format import AvroInputDataFormat
    from photon_ml_tpu.io.streaming import StreamingGLMObjective, scan_stream
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.glm import create_model
    from photon_ml_tpu.optim.factory import validate_optimizer_choice
    from photon_ml_tpu.optim.host_lbfgs import (
        minimize_lbfgs_host,
        minimize_owlqn_host,
    )
    from photon_ml_tpu.optim.host_tron import minimize_tron_host

    regularization = RegularizationContext(
        regularization_type, elastic_net_alpha
    )
    from photon_ml_tpu.ops.losses import loss_for_task as _loss_for_task

    use_tron = optimizer_type == OptimizerType.TRON
    base = OptimizerConfig.default_for(optimizer_type)
    max_iter = max_iter if max_iter is not None else base.max_iter
    tolerance = tolerance if tolerance is not None else base.tolerance
    # shared TRON x regularization / loss-smoothness rules
    validate_optimizer_choice(
        OptimizerConfig(optimizer_type=optimizer_type),
        regularization,
        loss_has_hessian=_loss_for_task(task).has_hessian,
    )
    if fmt is None:
        fmt = AvroInputDataFormat(
            add_intercept=add_intercept, field_names=field_names
        )
    multi = jax.process_count() > 1
    if multi:
        if index_map is None:
            raise ValueError(
                "multi-host streaming requires a prebuilt shared index "
                "map (build one with the feature-indexing job); no single "
                "process sees the whole vocabulary"
            )
        from photon_ml_tpu.io.streaming import shard_stream_files

        paths = shard_stream_files(paths, fmt)
        if stats is None:
            # local stats -> global agreement (max nnz must match across
            # processes: it fixes the compiled staging shape). A process
            # can own zero files when processes outnumber files — it
            # still joins every collective with empty partials. Callers
            # that already hold GLOBAL stats (the driver's preprocess
            # scan) skip this whole per-shard disk pass.
            from photon_ml_tpu.io.streaming import StreamStats

            if paths:
                _, local_stats = scan_stream(
                    paths, fmt, index_map=index_map
                )
            else:
                local_stats = StreamStats(num_rows=0, max_nnz=1)
            from jax.experimental import multihost_utils

            gathered = multihost_utils.process_allgather(
                np.asarray(
                    [local_stats.num_rows, local_stats.max_nnz], np.int64
                )
            )
            stats = StreamStats(
                num_rows=int(gathered[:, 0].sum()),
                max_nnz=int(gathered[:, 1].max()),
            )
    elif index_map is None or stats is None:
        index_map, stats = scan_stream(paths, fmt, index_map=index_map)
    objective = StreamingGLMObjective(
        paths, fmt, index_map, stats, task,
        rows_per_chunk=rows_per_chunk, cache_bytes=cache_bytes,
        prefetch=prefetch, kernel=kernel, tile_params=tile_params,
        norm=normalization, tile_cache_dir=tile_cache_dir,
    )
    from photon_ml_tpu.utils.index_map import intercept_key

    intercept_index = None
    if fmt.add_intercept:
        icept = index_map.get_index(intercept_key())
        if icept >= 0:
            intercept_index = icept
    l1_mask = None
    if regularization.has_l1 and intercept_index is not None:
        l1_mask = (
            jnp.ones((objective.dim,), jnp.float32)
            .at[intercept_index].set(0.0)
        )

    def _to_original_space(means):
        """De-normalize like GLMOptimizationProblem.create_model
        (GeneralizedLinearOptimizationProblem.scala:89-95)."""
        if normalization is None or normalization.is_identity:
            return means
        orig = normalization.model_to_original_space(means)
        if intercept_index is not None:
            orig = orig.at[intercept_index].add(
                normalization.intercept_adjustment(means)
            )
        return orig

    weights_desc = sorted(
        set(float(w) for w in regularization_weights), reverse=True
    )
    models: Dict[float, GeneralizedLinearModel] = {}
    results: Dict[float, OptResult] = {}
    # retrain warm start (registry.warm_start): the aligned parent
    # coefficients seed the FIRST λ exactly like `initial` on the
    # in-memory paths
    current = (
        jnp.asarray(initial, jnp.float32)
        if initial is not None
        else jnp.zeros((objective.dim,), jnp.float32)
    )
    for lam in weights_desc:
        snap = (
            grid_checkpointer.load(lam)
            if grid_checkpointer is not None
            else None
        )
        if snap is not None:
            # λ completed before the crash/preemption: restore model +
            # result and keep the warm-start chain bitwise intact
            models[lam] = _model_from_snapshot(task, snap)
            results[lam] = _result_from_snapshot(snap["result"])
            if warm_start:
                current = jnp.asarray(snap["warm_means"])
            continue
        if preemption_guard is not None and preemption_guard.requested:
            break
        l1, l2 = regularization.split(lam)
        if use_tron:
            # one streamed Hv pass per CG step — the reference's exact
            # second-order pattern (HessianVectorAggregator.scala:137-152)
            result = minimize_tron_host(
                lambda w: objective.value_and_gradient(w, l2),
                lambda w, d_: objective.hessian_vector(w, d_, l2),
                current, max_iter=max_iter, tol=tolerance, box=box,
                track_coefficients=track_models,
            )
        elif l1:
            result = minimize_owlqn_host(
                lambda w: objective.value_and_gradient(w, l2),
                current, l1, max_iter=max_iter, tol=tolerance,
                history=history, l1_mask=l1_mask, box=box,
                track_coefficients=track_models,
            )
        else:
            result = minimize_lbfgs_host(
                lambda w: objective.value_and_gradient(w, l2),
                current, max_iter=max_iter, tol=tolerance, history=history,
                box=box, track_coefficients=track_models,
            )
        variances = None
        if compute_variances:
            from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

            hd = objective.hessian_diagonal(result.coefficients, l2)
            variances = 1.0 / (hd + _VARIANCE_EPSILON)
        models[lam] = create_model(
            task,
            Coefficients(
                _to_original_space(result.coefficients), variances
            ),
        )
        results[lam] = result
        if grid_checkpointer is not None:
            _save_lambda_snapshot(
                grid_checkpointer, lam, result.coefficients,
                models[lam], result,
            )
        if warm_start:
            current = result.coefficients
    return models, results, index_map


def train_streaming_feature_sharded(
    paths,
    task: TaskType,
    *,
    mesh,
    regularization_type: RegularizationType = RegularizationType.NONE,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: Optional[float] = None,
    max_iter: Optional[int] = None,
    tolerance: Optional[float] = None,
    history: int = 10,
    rows_per_chunk: int = 65536,
    cache_bytes: int = 2 << 30,
    sharded_cache_bytes: int = 2 << 30,
    prefetch: bool = True,
    add_intercept: bool = True,
    field_names: str = "TRAINING_EXAMPLE",
    warm_start: bool = True,
    optimizer_type: OptimizerType = OptimizerType.LBFGS,
    compute_variances: bool = False,
    box: Optional[BoxConstraints] = None,
    track_models: bool = False,
    fmt=None,
    index_map=None,
    stats=None,
    spill_dir=None,
):
    """Streaming x feature-sharded GLM: dataset > host RAM AND model >
    single-chip HBM at once. Rows stream through the staged-chunk
    pipeline; every chunk re-stages per feature block on the (data,
    model) mesh (io.streaming.FeatureShardedStreamingObjective); the
    host-driven L-BFGS/OWL-QN/TRON walk the same iterate sequences as
    their in-memory counterparts, with TRON paying one streamed sharded
    Hv pass per CG step (the reference's
    one-treeAggregate-per-CG-iteration loop with chunks standing in for
    executor partitions).

    Single process only (the multi-host composition would need the
    cross-host reduce inside each sharded fold); normalization is not
    supported on this path yet — the driver validates both up front.

    Returns ({lambda: model}, {lambda: OptResult}, index_map).
    """
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.io.input_format import AvroInputDataFormat
    from photon_ml_tpu.io.streaming import (
        FeatureShardedStreamingObjective,
        scan_stream,
    )
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.glm import create_model
    from photon_ml_tpu.optim.factory import validate_optimizer_choice
    from photon_ml_tpu.optim.host_lbfgs import (
        minimize_lbfgs_host,
        minimize_owlqn_host,
    )
    from photon_ml_tpu.optim.host_tron import minimize_tron_host

    if jax.process_count() > 1:
        raise ValueError(
            "streaming feature-sharded training is single-process"
        )
    regularization = RegularizationContext(
        regularization_type, elastic_net_alpha
    )
    from photon_ml_tpu.ops.losses import loss_for_task as _loss_for_task

    use_tron = optimizer_type == OptimizerType.TRON
    base = OptimizerConfig.default_for(optimizer_type)
    max_iter = max_iter if max_iter is not None else base.max_iter
    tolerance = tolerance if tolerance is not None else base.tolerance
    validate_optimizer_choice(
        OptimizerConfig(optimizer_type=optimizer_type),
        regularization,
        loss_has_hessian=_loss_for_task(task).has_hessian,
    )
    if fmt is None:
        fmt = AvroInputDataFormat(
            add_intercept=add_intercept, field_names=field_names
        )
    if index_map is None or stats is None:
        index_map, stats = scan_stream(paths, fmt, index_map=index_map)
    objective = FeatureShardedStreamingObjective(
        paths, fmt, index_map, stats, task, mesh,
        rows_per_chunk=rows_per_chunk, cache_bytes=cache_bytes,
        sharded_cache_bytes=sharded_cache_bytes, prefetch=prefetch,
        spill_dir=spill_dir,
    )
    dim, d_pad = objective.dim, objective.d_pad
    from photon_ml_tpu.utils.index_map import intercept_key

    intercept_index = None
    if fmt.add_intercept:
        icept = index_map.get_index(intercept_key())
        if icept >= 0:
            intercept_index = icept
    l1_mask = None
    if regularization.has_l1:
        # padded tail exempt from the penalty (its gradient is zero and
        # it must stay at exactly 0), intercept exempt like the
        # replicated path
        l1_mask = jnp.concatenate(
            [jnp.ones((dim,), jnp.float32),
             jnp.zeros((d_pad - dim,), jnp.float32)]
        )
        if intercept_index is not None:
            l1_mask = l1_mask.at[intercept_index].set(0.0)
    box_pad = box
    if box is not None:
        from photon_ml_tpu.optim.common import BoxConstraints as _Box

        # padding coordinates get (-inf, inf): projection must not move
        # them off exactly 0
        box_pad = _Box(
            lower=jnp.concatenate(
                [jnp.asarray(box.lower, jnp.float32),
                 jnp.full((d_pad - dim,), -jnp.inf, jnp.float32)]
            ),
            upper=jnp.concatenate(
                [jnp.asarray(box.upper, jnp.float32),
                 jnp.full((d_pad - dim,), jnp.inf, jnp.float32)]
            ),
        )

    weights_desc = sorted(
        set(float(w) for w in regularization_weights), reverse=True
    )
    models: Dict[float, GeneralizedLinearModel] = {}
    results: Dict[float, OptResult] = {}
    current = jnp.zeros((d_pad,), jnp.float32)
    for lam in weights_desc:
        l1, l2 = regularization.split(lam)
        if use_tron:
            result = minimize_tron_host(
                lambda w: objective.value_and_gradient(w, l2),
                lambda w, d_: objective.hessian_vector(w, d_, l2),
                current, max_iter=max_iter, tol=tolerance, box=box_pad,
                track_coefficients=track_models,
            )
        elif l1:
            result = minimize_owlqn_host(
                lambda w: objective.value_and_gradient(w, l2),
                current, l1, max_iter=max_iter, tol=tolerance,
                history=history, l1_mask=l1_mask, box=box_pad,
                track_coefficients=track_models,
            )
        else:
            result = minimize_lbfgs_host(
                lambda w: objective.value_and_gradient(w, l2),
                current, max_iter=max_iter, tol=tolerance, history=history,
                box=box_pad, track_coefficients=track_models,
            )
        variances = None
        if compute_variances:
            from photon_ml_tpu.optim.problem import _VARIANCE_EPSILON

            hd = objective.hessian_diagonal(result.coefficients, l2)
            variances = (1.0 / (hd + _VARIANCE_EPSILON))[:dim]
        models[lam] = create_model(
            task, Coefficients(result.coefficients[:dim], variances)
        )
        tracker = result.tracker
        if tracker.coefs is not None:
            tracker = tracker._replace(coefs=tracker.coefs[:, :dim])
        results[lam] = result._replace(
            coefficients=result.coefficients[:dim], tracker=tracker
        )
        if warm_start:
            current = result.coefficients
    return models, results, index_map


def grid_result_scalars(
    results: Dict[float, OptResult],
) -> Dict[float, Tuple[int, float, int]]:
    """{lambda: (iterations, value, reason)} with ONE batched readback
    for the whole grid (parallel/overlap deferred-readback discipline).

    Every OptResult's scalars are device-resident futures until someone
    forces them; the pre-overlap consumers pulled three scalars per
    lambda serially — each a full host<->device round trip (~100 ms over
    a relay-attached chip), paid once per grid entry. One device_get
    materializes the lot."""
    from photon_ml_tpu.parallel import overlap

    items = list(results.items())
    fetched = overlap.device_get(
        [(res.iterations, res.value, res.reason) for _, res in items]
    )
    return {
        lam: (int(it), float(value), int(reason))
        for (lam, _), (it, value, reason) in zip(items, fetched)
    }


def iteration_models(
    result: OptResult,
    task: TaskType,
    normalization: Optional[NormalizationContext] = None,
    intercept_index: Optional[int] = None,
) -> List[GeneralizedLinearModel]:
    """Per-iteration models from a tracked OptResult (ModelTracker.models
    analog): slot 0 is the initial point, slot i the accepted iterate i.
    Coefficients are de-normalized to the original feature space exactly
    like the final model (GeneralizedLinearOptimizationProblem.scala:89-95).
    """
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.optim.problem import create_glm_problem

    if result.tracker.coefs is None:
        raise ValueError(
            "OptResult has no coefficient history; train with "
            "track_models=True"
        )
    problem = create_glm_problem(
        task, int(result.tracker.coefs.shape[1]),
        intercept_index=intercept_index,
    )
    count = int(result.tracker.count)
    return [
        problem.create_model(
            Coefficients(result.tracker.coefs[i]), normalization
        )
        for i in range(count)
    ]
