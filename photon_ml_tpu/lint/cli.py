"""photon-lint CLI: text (clickable file:line:col) and --json modes.

Exit codes: 0 clean, 1 non-baselined violations, 2 analysis/usage error
(a file that does not parse is an error, not a pass).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from photon_ml_tpu.lint.baseline import (
    BaselineRefused,
    apply_baseline,
    load_baseline,
    write_baseline,
)
from photon_ml_tpu.lint.core import all_rules, analyze_paths

DEFAULT_BASELINE = ".photon-lint-baseline.json"
DEFAULT_PATHS = ("photon_ml_tpu", "bench.py")


def _default_paths() -> List[str]:
    return [p for p in DEFAULT_PATHS if os.path.exists(p)]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.lint",
        description=(
            "AST-based invariant checker for the JAX hot path "
            "(readback seam, recompile hazards, spill/IO hygiene), "
            "the thread plane (guard discipline, lock ordering, "
            "atomicity) and the SPMD plane (mesh-axis discipline, "
            "sharded-bank host gathers, reduction completeness, "
            "donation hygiene) and the determinism plane (unordered "
            "iteration into artifacts, ambient entropy in signatures, "
            "float accumulation order, wire-contract completeness) — "
            "all whole-package passes on by default. Suppress a line "
            "with '# photon: allow(<rule>)'; declare guard discipline "
            "with '# photon: guarded-by(<lock>)', sharding contracts "
            "with '# photon: sharding(axes=..., in=..., out=...)' and "
            "legitimate entropy with '# photon: entropy(<reason>)'."
        ),
    )
    p.add_argument(
        "paths", nargs="*",
        help="files or directories (default: photon_ml_tpu bench.py)",
    )
    p.add_argument(
        "--json", action="store_true", dest="as_json",
        help="machine-readable report (violations, baselined count, "
             "allow-sites with seam accounting, unused baseline entries)",
    )
    p.add_argument(
        "--baseline", default=None, metavar="PATH",
        help=f"baseline file (default: {DEFAULT_BASELINE} when present)",
    )
    p.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="write the current violation set as the new baseline "
             "and exit 0",
    )
    p.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    p.add_argument(
        "--no-concurrency", action="store_true",
        help="skip the whole-package concurrency pass (PL008-PL010); "
             "the pass runs by default",
    )
    p.add_argument(
        "--no-spmd", action="store_true",
        help="skip the whole-package SPMD pass (PL011-PL014 + sharding "
             "contracts); the pass runs by default",
    )
    p.add_argument(
        "--no-determinism", action="store_true",
        help="skip the whole-package determinism pass (PL015-PL018 + "
             "entropy declarations + wire contract); the pass runs by "
             "default",
    )
    p.add_argument(
        "--write-sharding-md", nargs="?", const="SHARDING.md",
        default=None, metavar="PATH",
        help="regenerate the sharding-contract inventory (default "
             "SHARDING.md) from the analyzed paths and exit",
    )
    p.add_argument(
        "--check-sharding-md", nargs="?", const="SHARDING.md",
        default=None, metavar="PATH",
        help="exit 1 if the committed sharding inventory drifted from "
             "a fresh render (the CI drift gate)",
    )
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for rule in sorted(all_rules().values(), key=lambda r: r.id):
            print(f"{rule.id}  {rule.slug:24s}  {rule.doc}")
        return 0

    paths = args.paths or _default_paths()
    if not paths:
        print(
            "photon-lint: no paths given and no default targets found",
            file=sys.stderr,
        )
        return 2

    if args.write_sharding_md or args.check_sharding_md:
        from photon_ml_tpu.lint import sharding_contracts as sc

        pkg = sc.package_context(paths)
        if pkg is None:
            print("photon-lint: no parseable files", file=sys.stderr)
            return 2
        if args.write_sharding_md:
            content = sc.write_sharding_md(args.write_sharding_md, pkg)
            n = len(sc.inventory(pkg))
            print(
                f"photon-lint: wrote {n} sharding contract(s) "
                f"({len(content.splitlines())} lines) to "
                f"{args.write_sharding_md}"
            )
            return 0
        drift = sc.check_sharding_md(args.check_sharding_md, pkg)
        if drift is not None:
            print(f"photon-lint: {drift}", file=sys.stderr)
            return 1
        print(f"photon-lint: {args.check_sharding_md} is up to date")
        return 0

    report = analyze_paths(
        paths,
        package_pass=not args.no_concurrency,
        spmd_pass=not args.no_spmd,
        determinism_pass=not args.no_determinism,
    )

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None
    )
    if args.write_baseline:
        target = args.baseline or DEFAULT_BASELINE
        try:
            data = write_baseline(target, report.violations)
        except BaselineRefused as e:
            print(f"photon-lint: {e}", file=sys.stderr)
            return 2
        print(
            f"photon-lint: wrote {len(data['entries'])} baseline "
            f"entr{'y' if len(data['entries']) == 1 else 'ies'} "
            f"({len(report.violations)} violation(s)) to {target}"
        )
        return 0

    if baseline_path and not args.no_baseline:
        try:
            allow = load_baseline(baseline_path)
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
            print(f"photon-lint: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        apply_baseline(report, allow)

    exit_code = 0
    if report.violations:
        exit_code = 1
    if report.errors:
        exit_code = 2

    if args.as_json:
        payload = {
            "version": 1,
            "files_checked": len(report.files),
            "violations": [v.to_dict() for v in report.violations],
            "baselined": report.baselined,
            "allow_sites": [
                s.to_dict() for s in report.allow_sites
            ],
            "unused_baseline": report.unused_baseline,
            "errors": [
                {"file": f, "message": m} for f, m in report.errors
            ],
            "exit_code": exit_code,
        }
        if report.package is not None and not args.no_spmd:
            from photon_ml_tpu.lint import sharding_contracts as sc

            payload["sharding_contracts"] = sc.inventory(report.package)
            payload["export_scopes"] = sc.export_scopes(report.package)
        if report.package is not None and not args.no_determinism:
            from photon_ml_tpu.lint import determinism

            contract = determinism.wire_contract(report.package)
            payload["wire_contract"] = (
                contract.to_dict() if contract is not None else None
            )
            payload["entropy_declarations"] = (
                determinism.entropy_inventory(report.package)
            )
        print(json.dumps(payload, indent=2))
        return exit_code

    for f, m in report.errors:
        print(f"{f}:1:0: ERROR {m}")
    for v in report.violations:
        print(f"{v.location()}: {v.rule} [{v.slug}] {v.message}")
    for e in report.unused_baseline:
        print(
            f"warning: unused baseline entry {e['file']} {e['rule']} "
            f"{e['snippet']!r} x{e['count']} — fixed? remove it",
        )
    n = len(report.violations)
    print(
        f"photon-lint: {n} violation(s), {report.baselined} baselined, "
        f"{len(report.files)} file(s) checked"
    )
    return exit_code


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())
