"""PL006 reliability-hygiene: artifact writes go through the atomic
write-rename helpers, and swallowed IO failures route through the retry
layer.

Round 11's reliability layer makes two guarantees the rest of the
package must not quietly undermine:

1. **No torn artifacts.** Any ``open(path, "w"/"wb")`` that writes an
   artifact must publish it atomically — via
   ``reliability.artifacts.atomic_writer``/``atomic_write_json`` or an
   explicit same-directory temp + ``os.replace``/``os.rename`` in the
   same scope. A killed process must leave the old file or the new one,
   never a prefix. (Streaming spill writers that append fixed-size
   records behind the ``spill_write`` seam are the grandfathered
   exception — they are progress-manifested, not rename-published.)

2. **No silently swallowed IO failures.** An ``except`` arm that
   catches OSError/IOError (or blanket ``Exception``) around IO work
   and does NOTHING (bare ``pass``/``continue``) hides exactly the
   failures the retry layer exists to handle and account. Route the
   operation through ``reliability.retry.io_call`` (or at minimum
   log/raise). ``__del__``/``close`` teardown scopes are exempt —
   best-effort cleanup is their contract.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from photon_ml_tpu.lint.core import (
    FileContext,
    Rule,
    Violation,
    attr_root,
    call_name,
    register,
)

_ATOMIC_HELPERS = {
    "atomic_writer",
    "atomic_write_json",
    "atomic_write_bytes",
    "atomic_write_text",
}
_IO_CALLEES = {
    "open", "read", "write", "load", "save", "savez", "memmap",
    "rename", "replace", "remove", "unlink", "rmtree", "makedirs",
    "flush", "truncate",
}
_TEARDOWN_SCOPES = {"__del__", "close", "_sweep_spill_dirs"}


def _write_mode(node: ast.Call) -> Optional[str]:
    """The literal write mode of an ``open`` call, or None."""
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        if mode.value.startswith(("w", "x", "a")) and "+" not in mode.value:
            return mode.value
    return None


def _scope_has_atomic_publish(ctx: FileContext, scope: ast.AST) -> bool:
    """Atomic helper used, or an explicit os.replace/os.rename in scope
    (NOT str.replace — the root must be the os module)."""
    for node in ctx.walk_scope(scope):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _ATOMIC_HELPERS:
                return True
            if name in ("replace", "rename"):
                root = attr_root(node.func)
                if root is not None and root.id == "os":
                    return True
        elif isinstance(node, ast.Name) and node.id in _ATOMIC_HELPERS:
            return True
    return False


def _check_atomic_writes(ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call) or call_name(node) != "open":
            continue
        # plain builtin open only (os.fdopen of an atomic_writer tmp fd
        # is the helper's own implementation)
        if not isinstance(node.func, ast.Name):
            continue
        mode = _write_mode(node)
        if mode is None or mode.startswith("a"):
            continue  # appends are the spill-writer protocol, seam-gated
        scope = ctx.scope_of(node)
        if _scope_has_atomic_publish(ctx, scope):
            continue
        yield ctx.violation(
            RULE, node,
            f"open(..., {mode!r}) publishes an artifact non-atomically: "
            "a crash mid-write leaves a torn file the next stage (or a "
            "resumed run) trusts — write through "
            "reliability.artifacts.atomic_writer/atomic_write_json, or "
            "temp + os.replace in this scope",
        )


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """Handler body is ONLY pass/continue (no logging, no raise, no
    fallback work) — the silent-swallow shape."""
    return all(
        isinstance(stmt, (ast.Pass, ast.Continue)) for stmt in handler.body
    )


def _catches_io(handler: ast.ExceptHandler) -> bool:
    names = []
    t = handler.type
    if t is None:
        return True  # bare except
    for node in [t] + (list(t.elts) if isinstance(t, ast.Tuple) else []):
        if isinstance(node, ast.Name):
            names.append(node.id)
        elif isinstance(node, ast.Attribute):
            names.append(node.attr)
    return bool(
        set(names) & {"OSError", "IOError", "EnvironmentError", "Exception"}
    )


def _try_does_io(ctx: FileContext, node: ast.Try) -> bool:
    for stmt in node.body:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call) and call_name(sub) in _IO_CALLEES:
                return True
    return False


def _check_swallowed_io(ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Try):
            continue
        scope = ctx.scope_of(node)
        scope_name = getattr(scope, "name", "")
        if scope_name in _TEARDOWN_SCOPES:
            continue  # best-effort cleanup is the teardown contract
        if not _try_does_io(ctx, node):
            continue
        if ctx.scope_calls(scope, {"io_call"}):
            continue  # already routed through the retry layer
        for handler in node.handlers:
            if _catches_io(handler) and _handler_swallows(handler):
                yield ctx.violation(
                    RULE, handler,
                    "IO failure swallowed (except-and-pass around IO "
                    "work): the retry layer exists so transient errors "
                    "back off and persistent ones are ACCOUNTED — route "
                    "through reliability.retry.io_call, or log/re-raise",
                )


def _check(ctx: FileContext) -> Iterator[Violation]:
    yield from _check_atomic_writes(ctx)
    yield from _check_swallowed_io(ctx)


RULE = register(
    Rule(
        id="PL006",
        slug="reliability-hygiene",
        doc="artifact writes publish atomically (atomic_writer / temp + "
            "os.replace); IO failures are never silently swallowed "
            "outside teardown",
        check=_check,
    )
)
