"""PL015 unordered-iteration-to-artifact: a ``set``/``frozenset``/
``os.listdir``/``glob`` iteration order reaching a serialization or
digest sink is ``PYTHONHASHSEED``- or filesystem-order-dependent, so
the artifact bytes — and every bitwise gate that compares them
(content signatures, chaos parity, swap/rollback restore) — drift
between runs. The fix is always the same: ``sorted()`` before the
bytes are committed. The taint model lives in
``lint/determinism.py``; this rule just reports its PL015 sites.
"""

from __future__ import annotations

from typing import Iterator

from photon_ml_tpu.lint import determinism
from photon_ml_tpu.lint.core import (
    PackageContext,
    PackageRule,
    Violation,
    register_package,
)


def _check(pkg: PackageContext) -> Iterator[Violation]:
    for path in sorted(pkg.contexts):
        ctx = pkg.contexts[path]
        for node, msg in determinism.file_model(ctx).pl015:
            yield ctx.violation(RULE, node, msg)


RULE = register_package(
    PackageRule(
        id="PL015",
        slug="unordered-iteration-to-artifact",
        doc="set/listdir/glob iteration order must not reach a "
            "serialization or digest sink without sorted()",
        check=_check,
        group="determinism",
    )
)
