"""PL018 wire-contract completeness: the binary wire plane's message
inventory is cross-checked end to end, PL011-style. Every ``MSG_*``
constant in ``serving/wire.py`` must have

* an **encoder** — a function calling ``append_frame(buf, MSG_X, ...)``,
* a **decoder** — a ``decode*`` branch referencing the constant,
* a **dispatch** reference in the frontend or the transport
  (``serving/frontend.py`` / ``serving/routing.py``) — a frame type
  nobody routes is either dead or, worse, silently falls through a
  response-side ``decode_message`` and can confuse the protocol,
* a **fuzz-corpus entry** — a ``wire.MSG_X`` key in
  ``tests/test_wire.py``'s ``WIRE_FUZZ_CORPUS`` dict, so the corpus
  can never silently lag a new message type;

and every named ``WireError`` kind must appear in the frontend (the
BAD_REQUEST mapping leg). The inventory is machine-built by
``lint/determinism.py`` and exported under ``--json`` as
``wire_contract``. NEVER_BASELINE: a half-wired message type is a
protocol hole, not debt to inherit. Not allow()-suppressable.
"""

from __future__ import annotations

from typing import Iterator

from photon_ml_tpu.lint import determinism
from photon_ml_tpu.lint.core import (
    PackageContext,
    PackageRule,
    Violation,
    register_package,
)


def _check(pkg: PackageContext) -> Iterator[Violation]:
    contract = determinism.wire_contract(pkg)
    if contract is None:
        return
    ctx = pkg.contexts[contract.path]

    def flag(node, msg):
        return ctx.violation(RULE, node, msg, suppressable=False)

    seen_values = {}
    for msg in contract.messages:
        if msg.value in seen_values:
            yield flag(msg.node, (
                f"{msg.name} reuses wire value 0x{msg.value:02x} "
                f"already taken by {seen_values[msg.value]} — frame "
                "types must be unique"
            ))
        seen_values.setdefault(msg.value, msg.name)
        if not msg.encoders:
            yield flag(msg.node, (
                f"{msg.name} has no encoder — no function calls "
                f"append_frame(buf, {msg.name}, ...); a message type "
                "nobody can emit is dead wire surface"
            ))
        if not msg.decoded:
            yield flag(msg.node, (
                f"{msg.name} has no decoder branch — no decode* "
                "function references it, so peers that emit it get "
                "'unknown message type'"
            ))
        if not msg.dispatch:
            yield flag(msg.node, (
                f"{msg.name} is never dispatched — neither "
                "serving/frontend.py nor serving/routing.py "
                "references it, so frames of this type fall through "
                "the planes that should route or refuse them"
            ))
        if contract.corpus_checked and msg.in_corpus is False:
            yield flag(msg.node, (
                f"{msg.name} has no fuzz-corpus entry — add a "
                f"wire.{msg.name} key to WIRE_FUZZ_CORPUS in "
                "tests/test_wire.py so the corpus tracks the "
                "inventory"
            ))
    if contract.corpus_checked and contract.corpus_node is None:
        yield flag(ctx.tree, (
            "tests/test_wire.py exists but defines no "
            "WIRE_FUZZ_CORPUS dict — the fuzz corpus must be keyed "
            "by wire.MSG_* so PL018 can cross-check coverage"
        ))
    for kind, mapped in sorted(contract.error_kinds.items()):
        if not mapped:
            yield flag(ctx.tree, (
                f"WireError kind '{kind}' has no frontend mapping — "
                "serving/frontend.py never names it, so the error "
                "surfaces as an unclassified failure instead of a "
                "BAD_REQUEST category"
            ))


RULE = register_package(
    PackageRule(
        id="PL018",
        slug="wire-contract-completeness",
        doc="every MSG_* type has encoder+decoder+dispatch+fuzz "
            "corpus entry; every WireError kind a frontend mapping",
        check=_check,
        group="determinism",
    )
)
