"""PL009 lock-order-inversion: the cross-module lock-acquisition graph
is acyclic.

The serving/registry thread plane nests locks on purpose — a dispatch
holds the donation lock while the generation manager flips under its
own, the batcher's queue lock wraps admission bookkeeping — and that is
fine exactly as long as every thread acquires them in one global order.
A cycle in the acquisition-order graph is a deadlock with a schedule
attached: thread A holds L1 wanting L2 while thread B holds L2 wanting
L1, and the whole request path stops beating.

The graph (built by the package pass in ``lint/core.py``):

- **nodes** are lock identities — ``(class, attr)`` for
  ``self._lock``-style attributes (Conditions alias their backing
  lock) and ``(module, global)`` for module-level locks;
- **edges** come from syntactic nesting (``with self.a:`` containing
  ``with self.b:``) and from ONE-HOP calls: invoking a package method
  that itself acquires a lock while holding one. One-hop resolution is
  by method name with a stoplist of generic names (``get``/``put``/
  ``append``...) so dict traffic does not wire the graph to noise.

Every cycle is reported at each participating edge site. Lock
inversions are NEVER baseline-able (``--write-baseline`` refuses, and
``load_baseline`` rejects hand-edited PL009 entries): a potential
deadlock does not get grandfathered, it gets reordered.

Known honest limitation: a lock smuggled through a constructor alias
(``MicroBatcher(swap_lock=model.dispatch_lock)``) is invisible to the
static graph — that is the interleaving harness's job
(``photon_ml_tpu/testing/interleave.py``).
"""

from __future__ import annotations

from typing import Iterator

from photon_ml_tpu.lint.core import (
    PackageContext,
    PackageRule,
    Violation,
    register_package,
)


def _lock_name(node: tuple) -> str:
    if node[0] == "class":
        return f"{node[1]}.{node[2]}"
    return f"{node[2]} ({node[1]})"


def _check(pkg: PackageContext) -> Iterator[Violation]:
    for cycle in pkg.lock_cycles():
        path = " -> ".join(
            [_lock_name(e.src) for e in cycle] + [_lock_name(cycle[0].src)]
        )
        for edge in cycle:
            ctx = pkg.ctx(edge.path)
            if ctx is None:
                continue
            yield ctx.violation(
                RULE,
                _Anchor(edge.line),
                f"lock-order inversion cycle [{path}]: this site "
                f"acquires {_lock_name(edge.dst)} while holding "
                f"{_lock_name(edge.src)} ({edge.via}), but another "
                "site acquires them in the reverse order — pick ONE "
                "global order (inversions are never baseline-able)",
            )


class _Anchor:
    """A bare line anchor for violations whose 'node' is a graph edge."""

    def __init__(self, line: int):
        self.lineno = line
        self.col_offset = 0


RULE = register_package(
    PackageRule(
        id="PL009",
        slug="lock-order-inversion",
        doc="the cross-module lock-acquisition-order graph stays "
            "acyclic — a cycle is a deadlock with a schedule attached",
        check=_check,
    )
)
