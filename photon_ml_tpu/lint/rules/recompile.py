"""PL002 recompile-hazard: jit caches key on callable identity.

``jax.jit(lambda ...)`` mints a fresh callable — and a fresh compilation
cache — every time the line runs; the same applies to a ``@jax.jit`` def
re-executed inside a loop, and to unhashable ``static_argnums``/
``static_argnames`` literals. The pjit/TPUv4 scaling report calls silent
recompilation the dominant wall-clock regression class in XLA training
stacks; this rule catches the three shapes that cause it here. Named
module-level (or build-once factory) defs passed to ``jax.jit`` are
fine — identity is stable across calls to the jitted wrapper.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from photon_ml_tpu.lint.core import (
    FileContext,
    Rule,
    Violation,
    attr_root,
    register,
)

_JIT_NAMES = {"jit", "pjit"}


def is_jit_expr(ctx: FileContext, expr: ast.AST) -> bool:
    """``jax.jit`` / ``pjit`` / ``jax.experimental.pjit.pjit`` as an
    expression (decorator or call target)."""
    if isinstance(expr, ast.Attribute) and expr.attr in _JIT_NAMES:
        root = attr_root(expr)
        return root is not None and root.id in ctx.jax_modules
    if isinstance(expr, ast.Name) and expr.id in _JIT_NAMES:
        return expr.id in ctx.jax_names
    return False


def jit_call_parts(
    ctx: FileContext, node: ast.Call
) -> Optional[ast.Call]:
    """If ``node`` is a jit invocation — ``jax.jit(...)`` or
    ``partial(jax.jit, ...)`` — return the Call carrying jit's args."""
    if is_jit_expr(ctx, node.func):
        return node
    func = node.func
    name = func.attr if isinstance(func, ast.Attribute) else (
        func.id if isinstance(func, ast.Name) else ""
    )
    if name == "partial" and node.args and is_jit_expr(ctx, node.args[0]):
        return node
    return None


def _in_loop(ctx: FileContext, node: ast.AST) -> bool:
    """Is ``node`` lexically inside a loop body, within its own function
    (a def boundary resets the question — calling the inner function in
    a loop is a runtime property, not a lexical one)?"""
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.For, ast.AsyncFor, ast.While)):
            return True
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


def _check(ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            jc = jit_call_parts(ctx, node)
            if jc is None:
                continue
            target = None
            # partial(jax.jit, f, ...) puts the callee at args[1]
            args = jc.args[1:] if jc.args and is_jit_expr(
                ctx, jc.args[0]
            ) else jc.args
            if args:
                target = args[0]
            if isinstance(target, ast.Lambda):
                yield ctx.violation(
                    RULE, node,
                    "jit of a lambda: a fresh callable (and a fresh "
                    "compile cache) every time this line runs — jit a "
                    "module-level def, or close over statics with "
                    "static_argnums on a named function",
                )
            if _in_loop(ctx, node):
                yield ctx.violation(
                    RULE, node,
                    "jit call inside a loop re-wraps (and recompiles) "
                    "per iteration — hoist the jitted callable out of "
                    "the loop",
                )
            for kw in jc.keywords:
                if kw.arg in ("static_argnums", "static_argnames"):
                    if isinstance(kw.value, (ast.List, ast.Set, ast.Dict)):
                        yield ctx.violation(
                            RULE, kw.value,
                            f"{kw.arg} given a "
                            f"{type(kw.value).__name__.lower()} literal: "
                            "unhashable values defeat the jit cache key "
                            "— use a tuple",
                        )
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            jitted = any(
                is_jit_expr(ctx, d)
                or (
                    isinstance(d, ast.Call)
                    and jit_call_parts(ctx, d) is not None
                )
                for d in node.decorator_list
            )
            if jitted and _in_loop(ctx, node):
                yield ctx.violation(
                    RULE, node,
                    "@jit def inside a loop body is re-created (and "
                    "recompiled) every iteration — define it once "
                    "outside the loop",
                )


RULE = register(
    Rule(
        id="PL002",
        slug="recompile-hazard",
        doc="no jit-of-lambda, jit-in-loop, or unhashable static args",
        check=_check,
    )
)
