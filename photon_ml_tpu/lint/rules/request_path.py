"""PL007 request-path-hygiene: no unbounded blocking waits in
``photon_ml_tpu/serving/``.

The serving contract (ISSUE 8) is that EVERY request reaches exactly
one terminal outcome in bounded time — shed, deadline-exceeded,
drain-failed or scored — and that the dispatcher's liveness heartbeat
keeps beating even when idle. Both die the moment any thread on the
request path parks on an untimed primitive: an untimed
``Condition.wait()`` is a dispatcher that cannot observe shutdown, an
untimed ``Future.result()`` is a client thread a lost wakeup hangs
forever. Those are exactly the hangs the drain tests chase, so the
analyzer rejects them at review time instead:

- ``<anything>.wait()`` with no ``timeout`` — ``threading.Condition``,
  ``threading.Event``, or any wait-shaped API — must pass a timeout
  (positionally or by keyword) and re-check its predicate in a loop;
- ``<anything>.result()`` with no ``timeout`` — ``concurrent.futures``
  blocks unbounded by default; pass ``timeout=`` (``timeout=0`` inside
  a done-callback, where the future is already terminal).

Scope: files under a ``serving`` package directory (the request path).
Host-side driver/bench code may still block on its own replay futures;
the SERVICE may not. The baseline for this rule is pinned at ZERO
entries by ``tests/test_lint_clean.py`` — new request-path code starts
bounded or does not land.
"""

from __future__ import annotations

import ast
from typing import Iterator

from photon_ml_tpu.lint.core import (
    FileContext,
    Rule,
    Violation,
    call_name,
    register,
)

_BLOCKING = {"wait", "result"}


def _applies(ctx: FileContext) -> bool:
    return "serving" in ctx.path_parts()


def _has_timeout(node: ast.Call) -> bool:
    if node.args:
        return True  # wait(5.0) / result(2) — positional timeout
    return any(kw.arg == "timeout" for kw in node.keywords)


def _check(ctx: FileContext) -> Iterator[Violation]:
    if not _applies(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name not in _BLOCKING:
            continue
        # method form only (cond.wait() / fut.result()); a bare local
        # helper named wait()/result() is not the stdlib primitive
        if not isinstance(node.func, ast.Attribute):
            continue
        if _has_timeout(node):
            continue
        yield ctx.violation(
            RULE, node,
            f".{name}() without a timeout on the request path: an "
            "untimed blocking wait is a future that can hang and a "
            "dispatcher that cannot observe shutdown — pass timeout= "
            "and re-check the predicate in a loop (the drain/heartbeat "
            "contract, ISSUE 8)",
        )


RULE = register(
    Rule(
        id="PL007",
        slug="request-path-hygiene",
        doc="no untimed Condition.wait()/Future.result() in serving/ — "
            "every request-path wait is bounded",
        check=_check,
    )
)
