"""PL003 tracer-leak: traced values escaping or steering a jitted body.

Inside a jit trace every non-static argument is a tracer. Storing one on
``self``/a global outlives the trace (a leaked tracer errors — or worse,
silently captures a stale constant on re-trace); branching on one with
Python ``if``/``while`` either crashes at trace time or, when the value
happens to be concrete on the first call, bakes one branch in and trains
the wrong model on every later call. veScale's eager-SPMD consistency
work stresses exactly this class: host-visible control flow must not
depend on device values. Static metadata (``.shape``/``.ndim``/
``.dtype``/``len()``/``isinstance``/``is None``) stays legal.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from photon_ml_tpu.lint.core import (
    FileContext,
    Rule,
    Violation,
    register,
)
from photon_ml_tpu.lint.rules.recompile import is_jit_expr, jit_call_parts

_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "sharding", "weak_type", "aval",
}
_STATIC_CALLS = {
    "isinstance", "len", "getattr", "hasattr", "type", "callable", "id",
}


def _jit_static_params(
    fdef: ast.AST, jit_call: ast.Call
) -> Set[str]:
    """Param names marked static via static_argnums/static_argnames
    literals on the jit decorator/call."""
    args = fdef.args
    positional = [
        p.arg for p in list(args.posonlyargs) + list(args.args)
    ]
    static: Set[str] = set()
    for kw in jit_call.keywords:
        vals: List[ast.AST] = (
            list(kw.value.elts)
            if isinstance(kw.value, (ast.Tuple, ast.List))
            else [kw.value]
        )
        if kw.arg == "static_argnums":
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(
                    v.value, int
                ) and 0 <= v.value < len(positional):
                    static.add(positional[v.value])
        elif kw.arg == "static_argnames":
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(
                    v.value, str
                ):
                    static.add(v.value)
    return static


def _jitted_defs(ctx: FileContext):
    """(FunctionDef, static_params) for every def that is jit-compiled:
    decorated with jit (directly or partial-wrapped), or passed by name
    to a jit call anywhere in the module."""
    by_name = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)
    out = []
    seen = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in node.decorator_list:
                if is_jit_expr(ctx, d):
                    out.append((node, set()))
                    seen.add(id(node))
                elif isinstance(d, ast.Call):
                    jc = jit_call_parts(ctx, d)
                    if jc is not None:
                        out.append((node, _jit_static_params(node, jc)))
                        seen.add(id(node))
        elif isinstance(node, ast.Call):
            jc = jit_call_parts(ctx, node)
            if jc is None:
                continue
            cargs = jc.args[1:] if jc.args and is_jit_expr(
                ctx, jc.args[0]
            ) else jc.args
            if cargs and isinstance(cargs[0], ast.Name):
                for fdef in by_name.get(cargs[0].id, []):
                    if id(fdef) not in seen:
                        out.append((fdef, _jit_static_params(fdef, jc)))
                        seen.add(id(fdef))
    return out


def _uses_traced_value(
    ctx: FileContext, expr: ast.AST, tainted: Set[str]
) -> bool:
    """Does the VALUE (not static metadata) of a traced name feed this
    expression?"""
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute):
        if expr.attr in _STATIC_ATTRS:
            return False
        return _uses_traced_value(ctx, expr.value, tainted)
    if isinstance(expr, ast.Call):
        func = expr.func
        if isinstance(func, ast.Name) and func.id in _STATIC_CALLS:
            return False
        if _uses_traced_value(ctx, func, tainted):
            return True  # method on a traced value: x.any(), x.item()
        return any(
            _uses_traced_value(ctx, a, tainted) for a in expr.args
        ) or any(
            _uses_traced_value(ctx, kw.value, tainted)
            for kw in expr.keywords
        )
    if isinstance(expr, ast.Compare):
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return False  # `x is None` is a static identity test
        return _uses_traced_value(ctx, expr.left, tainted) or any(
            _uses_traced_value(ctx, c, tainted)
            for c in expr.comparators
        )
    if isinstance(expr, ast.BoolOp):
        return any(
            _uses_traced_value(ctx, v, tainted) for v in expr.values
        )
    if isinstance(expr, (ast.BinOp,)):
        return _uses_traced_value(
            ctx, expr.left, tainted
        ) or _uses_traced_value(ctx, expr.right, tainted)
    if isinstance(expr, ast.UnaryOp):
        return _uses_traced_value(ctx, expr.operand, tainted)
    if isinstance(expr, ast.Subscript):
        return _uses_traced_value(ctx, expr.value, tainted)
    if isinstance(expr, ast.IfExp):
        return (
            _uses_traced_value(ctx, expr.test, tainted)
            or _uses_traced_value(ctx, expr.body, tainted)
            or _uses_traced_value(ctx, expr.orelse, tainted)
        )
    return False


def _check(ctx: FileContext) -> Iterator[Violation]:
    for fdef, static in _jitted_defs(ctx):
        tainted = ctx.jax_taint(
            fdef, include_params=True, exclude_params=sorted(static)
        )
        for node in ctx.walk_scope(fdef):
            if isinstance(node, ast.Global):
                yield ctx.violation(
                    RULE, node,
                    "global statement inside a jitted body: a traced "
                    "value written to module state outlives the trace "
                    "(leaked tracer / stale capture on re-trace)",
                )
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for tgt in targets:
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        yield ctx.violation(
                            RULE, tgt,
                            "assignment to self.%s inside a jitted body "
                            "stores a tracer on the instance — it "
                            "escapes the trace and is invalid (or "
                            "silently stale) outside it" % tgt.attr,
                        )
            elif isinstance(node, (ast.If, ast.While)):
                if _uses_traced_value(ctx, node.test, tainted):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    yield ctx.violation(
                        RULE, node,
                        f"Python {kind} on a traced value inside a "
                        "jitted body — use jnp.where / lax.cond / "
                        "lax.while_loop (shape/dtype/is-None tests "
                        "stay legal)",
                    )


RULE = register(
    Rule(
        id="PL003",
        slug="tracer-leak",
        doc="no tracers stored on self/globals or Python-branched on "
            "inside jitted bodies",
        check=_check,
    )
)
