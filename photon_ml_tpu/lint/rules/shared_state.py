"""PL008 unguarded-shared-state: every mutable attribute of a
thread-plane class obeys ONE declared (or inferred) guard discipline.

PRs 7-10 made the repo genuinely concurrent — dispatcher, accept loop,
per-connection reader/writer pairs, registry watcher, decode-ahead
workers — and every bitwise-serving invariant now rests on shared
state being touched correctly. This rule turns that discipline into a
machine-checked contract, the way veScale's analyzer treats SPMD
consistency (PAPERS.md): declare the guard once, and the checker proves
every access obeys it.

Per class (package pass, ``lint/core.py``):

- **Inferred guards.** An attribute written under ``with self._lock:``
  anywhere (outside ``__init__``) is lock-guarded; every OTHER access
  outside ``__init__`` must hold the same lock — a bare read of a
  guarded flag is a stale-decision bug waiting for a preemption point.
  Conditions alias the lock they were constructed over, so
  ``with self._nonempty:`` guards what ``with self._lock:`` guards.
- **Declared guards.** ``# photon: guarded-by(<lock>)`` on the
  ``__init__`` assignment pins the discipline explicitly (the analyzer
  enforces the declaration — it is NOT a suppression).
  ``# photon: guarded-by(atomic)`` declares single-writer
  atomic-publish instead: plain reference assignment only (``+=`` and
  in-place container mutation are flagged), reads free. Use it for
  heartbeat timestamps and copy-on-write snapshots, not as an
  escape hatch.
- **Thread-shared bare attrs.** In a class that spawns a thread
  (``Thread(target=self._loop)``), an attribute mutated on one side of
  the thread boundary and touched on the other with NO lock anywhere is
  flagged even though no guard exists to infer — that is exactly the
  ``_watching_swap``-style state flag this rule exists for.
- **Thread escapes.** A closure handed to ``Thread(target=...)`` /
  ``submit_io`` whose captured local is mutated bare on both sides of
  the spawn is an escaped shared object; lambdas as thread targets are
  rejected outright (unanalyzable capture).

Lock/Condition/Event/Queue attributes are exempt (they ARE the
synchronization), as is anything only touched in ``__init__`` /
``__post_init__`` (pre-publication construction).
"""

from __future__ import annotations

from typing import Iterator, Set, Tuple

from photon_ml_tpu.lint.core import (
    ATOMIC,
    ClassModel,
    PackageContext,
    PackageRule,
    Violation,
    register_package,
)


def _class_violations(model: ClassModel) -> Iterator[Violation]:
    lock_like = model.lock_names() | model.safe_attrs
    shared = model.shared_attrs()
    seen: Set[Tuple[int, str]] = set()

    def emit(access, message):
        key = (getattr(access.node, "lineno", 0), access.attr)
        if key in seen:
            return None
        seen.add(key)
        return model.ctx.violation(RULE, access.node, message)

    for attr in sorted(model.accesses):
        if attr in lock_like or attr in model.methods:
            continue
        accs = [a for a in model.accesses[attr] if not a.in_init]
        if not accs:
            continue
        ann = model.annotations.get(attr)
        if ann == ATOMIC:
            for a in accs:
                if a.kind in ("augwrite", "mutate"):
                    v = emit(a, (
                        f"'{model.name}.{attr}' is declared "
                        "guarded-by(atomic) but this is a read-modify-"
                        "write — atomic discipline allows only plain "
                        "reference assignment (publish a fresh object "
                        "instead, or guard with a lock)"
                    ))
                    if v:
                        yield v
            continue
        if ann is not None:
            target = model.resolve_lock(ann)
            if target is None:
                v = emit(accs[0], (
                    f"'{model.name}.{attr}' declares guarded-by({ann}) "
                    f"but '{ann}' is not a lock/condition attribute of "
                    f"{model.name}"
                ))
                if v:
                    yield v
                continue
            for a in accs:
                if target not in a.locks_held:
                    word = "write" if a.is_write else "read"
                    v = emit(a, (
                        f"bare {word} of '{model.name}.{attr}' — "
                        f"declared guarded-by({ann}); hold "
                        f"self.{target} for every access"
                    ))
                    if v:
                        yield v
            continue
        guard = model.inferred_guard(attr)
        if guard is not None:
            for a in accs:
                if guard not in a.locks_held:
                    word = "write" if a.is_write else "read"
                    v = emit(a, (
                        f"bare {word} of '{model.name}.{attr}', which "
                        f"is written under self.{guard} elsewhere — "
                        "hold the lock here too, or declare the "
                        "discipline with '# photon: guarded-by(...)'"
                    ))
                    if v:
                        yield v
        elif attr in shared and any(a.is_write for a in accs):
            for a in accs:
                v = emit(a, (
                    f"'{model.name}.{attr}' crosses the thread "
                    f"boundary (thread entry {sorted(model.thread_targets)}) "
                    "with no guard anywhere — protect it with a lock "
                    "or declare '# photon: guarded-by(atomic)' if it "
                    "is a single-writer published reference"
                ))
                if v:
                    yield v


def _lock_expected_callsites(model: ClassModel) -> Iterator[Violation]:
    """A method annotated guarded-by(<lock>) on its def line is a
    caller-holds-the-lock helper: every self-call must prove it."""
    if not model.lock_expected:
        return
    for mname, sc in model._scanners.items():
        if sc.in_init:
            continue
        for node, callee, held in sc.self_calls:
            need = model.lock_expected.get(callee)
            if need is not None and need not in held:
                yield model.ctx.violation(RULE, node, (
                    f"'{model.name}.{callee}' is declared "
                    f"guarded-by({need}) on its def line but this call "
                    f"site does not hold self.{need} — acquire the "
                    "lock around the call (the helper body is analyzed "
                    "as if the lock were held)"
                ))


def _check(pkg: PackageContext) -> Iterator[Violation]:
    for model in pkg.all_classes():
        if not model.concurrent:
            continue
        yield from _class_violations(model)
        yield from _lock_expected_callsites(model)
    for esc in pkg.thread_escapes:
        ctx = pkg.ctx(esc.path)
        if ctx is not None:
            yield ctx.violation(RULE, esc.node, esc.message)


RULE = register_package(
    PackageRule(
        id="PL008",
        slug="unguarded-shared-state",
        doc="every access to a lock-guarded / thread-shared attribute "
            "holds its declared (or inferred) guard",
        check=_check,
    )
)
