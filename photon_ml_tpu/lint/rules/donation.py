"""PL014 donation-hygiene: a donated argument is dead after the call.

``donate_argnums`` hands the argument's buffers to XLA for aliasing —
on any non-CPU backend the caller's array is INVALIDATED by the call.
The donated-swap (serving/swap.py) and the grid/pod bank paths do this
correctly by hand today (rebind-the-result or defensive-copy-first);
nothing checked it, and the failure mode is a delocalized
"buffer has been deleted" error (or silent garbage under older
runtimes) far from the donating call.

Per file, the rule resolves which callables donate:

- ``@partial(jax.jit, donate_argnums=...)`` decorated defs and
  ``name = jax.jit(f, donate_argnums=...)`` assignments;
- ``donate_argnums`` values through one level of indirection — a
  literal tuple, a local variable bound to one (including the
  ``(0,) if chip else ()`` conditional), or a call to a local helper
  whose returns are literal tuples (the ``_donate_args()`` pattern:
  the union of possible donations is checked, so CPU-only runs don't
  mask the chip hazard);
- **builders**: a local def that returns a donating callable marks
  every name assigned from an expression referencing it (directly or
  through a cache-insert lambda) as donating — the
  ``_cached_program(..., lambda: _build_update_program(...))`` shape.

At each call through a donating name, a donated POSITIONAL argument
that is a plain name must either be rebound by the call's own
assignment targets (the swap idiom: ``bank, stats = fused(bank, ...)``)
or never referenced again in the enclosing scope. Attribute/subscript
arguments are not tracked (aliasing through objects is the interleave
harness's job, not syntax's).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from photon_ml_tpu.lint import spmd
from photon_ml_tpu.lint.core import (
    FileContext,
    PackageContext,
    PackageRule,
    Violation,
    register_package,
)


def _donating_defs(model: spmd.SpmdFileModel) -> Dict[str, List[int]]:
    """def/assign name -> donated argnums, from the SPMD entry scan."""
    out: Dict[str, List[int]] = {}
    for entry in model.entries:
        if entry.donates:
            leaf = entry.qualname.rsplit(".", 1)[-1]
            if leaf and not leaf.startswith("<"):
                out[leaf] = entry.donates
    return out


def _builder_defs(ctx: FileContext, model: spmd.SpmdFileModel,
                  donating: Dict[str, List[int]]) -> Dict[str, List[int]]:
    """Local defs that RETURN a donating callable (by reference)."""
    out: Dict[str, List[int]] = {}
    changed = True
    known = dict(donating)
    while changed:
        changed = False
        for name, fn in model.local_defs.items():
            if name in known:
                continue
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Return) or sub.value is None:
                    continue
                for leaf in ast.walk(sub.value):
                    if isinstance(leaf, ast.Name) and leaf.id in known \
                            and leaf.id != name:
                        out[name] = known[leaf.id]
                        known[name] = known[leaf.id]
                        changed = True
                        break
                if name in known:
                    break
    return out


def _names_in(expr: ast.AST) -> Iterator[str]:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name):
            yield sub.id


def _assign_targets(stmt: ast.AST) -> Set[str]:
    out: Set[str] = set()
    if isinstance(stmt, ast.Assign):
        for tgt in stmt.targets:
            for sub in ast.walk(tgt):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
    return out


def _enclosing_stmt(ctx: FileContext, node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing STATEMENT (the Assign/Expr/... the call sits
    in) — NOT the top-level scope child, so a donating call inside a
    loop pairs with its own assignment's rebinds."""
    cur: Optional[ast.AST] = node
    while cur is not None and not isinstance(cur, ast.stmt):
        cur = ctx.parent(cur)
    if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    return cur if isinstance(cur, ast.stmt) else None


def _file_violations(
    ctx: FileContext, model: spmd.SpmdFileModel,
) -> Iterator[Violation]:
    donating = _donating_defs(model)
    if donating:
        donating = dict(donating)
        donating.update(_builder_defs(ctx, model, donating))
    if not donating:
        return
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    reported = set()  # (call id, argnum) — scopes overlap on nested defs
    for scope in scopes:
        # names in this scope bound from a donating/builder reference
        local_donating: Dict[str, List[int]] = {}
        for node in ctx.walk_scope(scope):
            if not isinstance(node, ast.Assign):
                continue
            argnums: Optional[List[int]] = None
            for ref in _names_in(node.value):
                if ref in donating:
                    argnums = donating[ref]
                    break
            if argnums:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        local_donating[t.id] = argnums
        callmap = dict(donating)
        callmap.update(local_donating)
        for node in ctx.walk_scope(scope):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Name):
                continue
            argnums = callmap.get(node.func.id)
            if not argnums:
                continue
            stmt = _enclosing_stmt(ctx, node)
            if stmt is None:
                continue
            rebound = _assign_targets(stmt)
            end = getattr(stmt, "end_lineno", stmt.lineno)
            for i in argnums:
                if i >= len(node.args) or (id(node), i) in reported:
                    continue
                arg = node.args[i]
                if not isinstance(arg, ast.Name):
                    continue  # attribute/subscript donation untracked
                if arg.id in rebound:
                    continue  # the swap idiom: result replaces donor
                reported.add((id(node), i))
                for later in ctx.walk_scope(scope):
                    if (
                        isinstance(later, ast.Name)
                        and later.id == arg.id
                        and isinstance(later.ctx, ast.Load)
                        and getattr(later, "lineno", 0) > end
                    ):
                        yield ctx.violation(RULE, later, (
                            f"'{arg.id}' was donated to "
                            f"'{node.func.id}' (donate_argnums includes "
                            f"{i}) on line {node.lineno} and is "
                            "referenced afterwards — on a non-CPU "
                            "backend its buffer is invalidated by the "
                            "call; rebind the result over the donor or "
                            "copy before donating"
                        ))
                        break


def _check(pkg: PackageContext) -> Iterator[Violation]:
    idx = spmd.index(pkg)
    for path in sorted(pkg.contexts):
        yield from _file_violations(pkg.contexts[path], idx.models[path])


RULE = register_package(
    PackageRule(
        id="PL014",
        slug="donation-hygiene",
        doc="arguments donated via donate_argnums are never referenced "
            "after the donating call",
        check=_check,
        group="spmd",
    )
)
