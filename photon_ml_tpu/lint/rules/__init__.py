"""Rule modules — importing this package registers every rule."""

from photon_ml_tpu.lint.rules import (  # noqa: F401
    artifact_order,
    atomicity,
    donation,
    entropy,
    float_order,
    host_gather,
    host_sync,
    io_drain,
    lock_order,
    mesh_axis,
    recompile,
    reduction,
    reliability,
    request_path,
    shared_state,
    spill,
    tracer_leak,
    wire_contract,
)
