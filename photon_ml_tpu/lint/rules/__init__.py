"""Rule modules — importing this package registers every rule."""

from photon_ml_tpu.lint.rules import (  # noqa: F401
    host_sync,
    io_drain,
    recompile,
    reliability,
    request_path,
    spill,
    tracer_leak,
)
