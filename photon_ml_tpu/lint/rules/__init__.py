"""Rule modules — importing this package registers every rule."""

from photon_ml_tpu.lint.rules import (  # noqa: F401
    atomicity,
    host_sync,
    io_drain,
    lock_order,
    recompile,
    reliability,
    request_path,
    shared_state,
    spill,
    tracer_leak,
)
