"""PL012 sharded-bank-host-gather: no host (or replicated)
materialization of an entity-/feature-sharded bank outside a declared
export/checkpoint scope.

The ROADMAP's multi-host warm-start rule — "alignment must happen
shard-local, never via a host [E, d] gather" — is currently upheld by
hand: the pod CD path routes residuals device-side and only the export/
checkpoint surfaces call ``ShardedREBank.to_global()``. This rule makes
that structural. Values are tainted as SHARDED when they provably hold a
sharded bank:

- constructed via ``ShardedREBank(...)`` / ``GridShardedREBank(...)``
  (the unified-mesh λ-grid bank, game/unified.py) or their
  ``.zeros(...)`` / ``.from_global(...)`` /
  ``.from_member_globals(...)`` / ``.restore(...)`` classmethods;
- loaded from a ``.sharded_bank`` / ``.variances_sharded`` attribute
  (or ``getattr(x, "sharded_bank", ...)``);
- parameters/returns annotated ``ShardedREBank``;
- guarded by ``isinstance(x, ShardedREBank)``;
- returned by a local function the above taints (one-hop, per file);
- ``self`` inside ``ShardedREBank``'s own methods, and the ``.data``
  attribute / subscripts of any tainted value.

Sinks on a tainted value — ``.to_global()``, ``device_get`` (raw OR the
counted ``overlap`` seam: counting a full-bank gather does not make it
shard-local), ``np.asarray``/``np.array`` — are violations unless the
enclosing def (or an enclosing scope) is declared
``# photon: sharding(export)`` (alias ``checkpoint``), or the file IS
``parallel/overlap.py`` (the seam's own plumbing). The declaration is
an audited inventory entry (SHARDING.md lists every export scope), not
a suppression.

Like PL009, PL012 is **never baseline-able**: a host gather on a
non-export path defeats the sharding story silently at pod scale, so
``--write-baseline`` refuses (exit 2) and hand-edited PL012 baseline
entries are rejected at load. Scope: package code
(``photon_ml_tpu/``) — bench/test parity harnesses legitimately
materialize replicated views to compare against.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from photon_ml_tpu.lint import spmd
from photon_ml_tpu.lint.core import (
    FileContext,
    PackageContext,
    PackageRule,
    Violation,
    attr_root,
    call_name,
    register_package,
)

_BANK_CLASSES = {"ShardedREBank", "GridShardedREBank"}
_SOURCE_ATTRS = {"sharded_bank", "variances_sharded"}
_BANK_CLASSMETHODS = {"zeros", "from_global", "from_member_globals",
                      "restore"}
# jnp reductions produce scalars/rows, not bank-shaped values
_REDUCING_TAILS = {"sum", "mean", "max", "min", "vdot", "dot", "prod"}


def _is_bank_name(expr: ast.AST) -> bool:
    return (
        isinstance(expr, ast.Name) and expr.id in _BANK_CLASSES
    ) or (
        isinstance(expr, ast.Attribute) and expr.attr in _BANK_CLASSES
    )


def _annotation_mentions_bank(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    for sub in ast.walk(ann):
        if isinstance(sub, ast.Name) and sub.id in _BANK_CLASSES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _BANK_CLASSES:
            return True
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and any(c in sub.value for c in _BANK_CLASSES):
            return True
    return False


class _FileTaint:
    """Per-file sharded-bank taint: scope-local name sets plus a
    name-keyed map of local functions/methods whose RETURN is tainted
    (one-hop call resolution, fixpointed twice)."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.tainted_fns: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _annotation_mentions_bank(node.returns):
                    self.tainted_fns.add(node.name)
        for _ in range(2):
            before = len(self.tainted_fns)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if node.name in self.tainted_fns:
                    continue
                env = self.scope_taint(node)
                for sub in self.ctx.walk_scope(node):
                    if isinstance(sub, ast.Return) and sub.value is not \
                            None and self.tainted(sub.value, env):
                        self.tainted_fns.add(node.name)
                        break
            if len(self.tainted_fns) == before:
                break
        self._env_cache = {}

    # -- scope environment ---------------------------------------------------

    def _self_is_bank(self, scope: ast.AST) -> bool:
        for anc in [scope] + list(self.ctx.ancestors(scope)):
            if isinstance(anc, ast.ClassDef):
                return anc.name in _BANK_CLASSES
        return False

    def scope_taint(self, scope: ast.AST) -> Set[str]:  # photon: entropy(id-keyed per-scope env memo; in-memory only)
        key = id(scope)
        cached = self._env_cache.get(key) if hasattr(self, "_env_cache") \
            else None
        if cached is not None:
            return cached
        env: Set[str] = set()
        if self._self_is_bank(scope):
            env.add("self")
        # annotated parameters
        if hasattr(scope, "args"):
            a = scope.args
            for p in list(a.posonlyargs) + list(a.args) + \
                    list(a.kwonlyargs):
                if _annotation_mentions_bank(p.annotation):
                    env.add(p.arg)
        # isinstance guards: inside `if isinstance(x, ShardedREBank):`
        # x is a bank (scope-global over-approximation; the sinks this
        # rule hunts only appear on the guarded path in practice)
        for node in self.ctx.walk_scope(scope):
            if isinstance(node, ast.If) and isinstance(
                node.test, ast.Call
            ) and call_name(node.test) == "isinstance" and len(
                node.test.args
            ) == 2:
                tgt, cls = node.test.args
                if isinstance(tgt, ast.Name) and _is_bank_name_or_tuple(
                    cls
                ):
                    env.add(tgt.id)
        # assignment fixpoint
        for _ in range(6):
            before = len(env)
            for node in self.ctx.walk_scope(scope):
                if isinstance(node, ast.Assign):
                    if self.tainted(node.value, env):
                        for tgt in node.targets:
                            _add_target(tgt, env)
                elif isinstance(node, ast.AnnAssign) and node.value is \
                        not None:
                    if self.tainted(node.value, env) or \
                            _annotation_mentions_bank(node.annotation):
                        _add_target(node.target, env)
            if len(env) == before:
                break
        if hasattr(self, "_env_cache"):
            self._env_cache[key] = env
        return env

    # -- expression classification -------------------------------------------

    def tainted(self, expr: ast.AST, env: Set[str]) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in env
        if isinstance(expr, ast.Attribute):
            if expr.attr in _SOURCE_ATTRS:
                return True
            if expr.attr == "data":
                return self.tainted(expr.value, env)
            return False
        if isinstance(expr, ast.Subscript):
            return self.tainted(expr.value, env)
        if isinstance(expr, ast.Call):
            func = expr.func
            if _is_bank_name(func):
                return True
            if isinstance(func, ast.Attribute) and func.attr in \
                    _BANK_CLASSMETHODS and _is_bank_name(func.value):
                return True
            if call_name(expr) == "getattr" and len(expr.args) >= 2:
                a1 = expr.args[1]
                if isinstance(a1, ast.Constant) and a1.value in \
                        _SOURCE_ATTRS:
                    return True
            # one-hop: local function / self-method with tainted return
            if isinstance(func, ast.Name) and func.id in \
                    self.tainted_fns:
                return True
            if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name
            ) and func.value.id in ("self", "cls") and func.attr in \
                    self.tainted_fns:
                return True
            # jnp/np numeric ops propagate bank shape — except scalar
            # reductions; every other callee is assumed to consume the
            # bank (a function OF a bank usually reduces it)
            root = attr_root(func) if isinstance(func, ast.Attribute) \
                else None
            if root is not None and (
                root.id in self.ctx.jax_modules
                or root.id in self.ctx.numpy_modules
            ):
                tail = func.attr if isinstance(func, ast.Attribute) \
                    else ""
                if tail in _REDUCING_TAILS:
                    return False
                return any(
                    self.tainted(a, env) for a in expr.args
                )
            return False
        if isinstance(expr, ast.IfExp):
            return self.tainted(expr.body, env) or self.tainted(
                expr.orelse, env
            )
        if isinstance(expr, (ast.BoolOp,)):
            return any(self.tainted(v, env) for v in expr.values)
        if isinstance(expr, ast.BinOp):
            return self.tainted(expr.left, env) or self.tainted(
                expr.right, env
            )
        if isinstance(expr, ast.Starred):
            return self.tainted(expr.value, env)
        return False


def _is_bank_name_or_tuple(expr: ast.AST) -> bool:
    if _is_bank_name(expr):
        return True
    if isinstance(expr, ast.Tuple):
        return any(_is_bank_name(e) for e in expr.elts)
    return False


def _add_target(target: ast.AST, env: Set[str]) -> None:
    if isinstance(target, ast.Name):
        env.add(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        # conservative: a tainted RHS tuple taints every target — the
        # common shape is `bank, tracker = update(...)` where only the
        # bank is sharded, but over-tainting a tracker name never
        # reaches a sink
        for e in target.elts:
            _add_target(e, env)


def _file_violations(
    ctx: FileContext, model: spmd.SpmdFileModel,
) -> Iterator[Violation]:
    if ctx.path.endswith("parallel/overlap.py"):
        return
    if "photon_ml_tpu" not in ctx.path_parts():
        return
    src = ctx.source
    if all(c not in src for c in _BANK_CLASSES) and \
            "sharded_bank" not in src:
        return  # fast path: nothing bank-shaped in this file
    taint = _FileTaint(ctx)
    scopes = [ctx.tree] + [
        n for n in ast.walk(ctx.tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]
    seen: Set[int] = set()
    for scope in scopes:
        env = taint.scope_taint(scope)
        if not env and not taint.tainted_fns:
            continue
        for node in ctx.walk_scope(scope):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            sink = _sink_kind(ctx, node, env, taint)
            if sink is None:
                continue
            seen.add(id(node))
            if spmd.in_export_scope(ctx, node, model):
                continue
            yield ctx.violation(RULE, node, (
                f"{sink} materializes an entity-/feature-sharded bank "
                "off its shards — alignment and scoring must stay "
                "shard-local (ROADMAP: never a host [E, d] gather). "
                "If this IS an export/checkpoint surface, declare the "
                "enclosing def '# photon: sharding(export)' so the "
                "scope is inventoried; otherwise route the access "
                "through the sharded program family"
            ))


def _sink_kind(ctx: FileContext, call: ast.Call, env,
               taint: _FileTaint) -> Optional[str]:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "to_global":
        if taint.tainted(func.value, env):
            return ".to_global()"
        return None
    name = call_name(call)
    if name == "device_get" and call.args:
        if taint.tainted(call.args[0], env):
            return "device_get"
        return None
    if isinstance(func, ast.Attribute) and func.attr in (
        "asarray", "array"
    ):
        root = attr_root(func)
        if root is not None and root.id in ctx.numpy_modules and \
                call.args and taint.tainted(call.args[0], env):
            return f"np.{func.attr}"
    return None


def _check(pkg: PackageContext) -> Iterator[Violation]:
    idx = spmd.index(pkg)
    for path in sorted(pkg.contexts):
        yield from _file_violations(pkg.contexts[path], idx.models[path])


RULE = register_package(
    PackageRule(
        id="PL012",
        slug="sharded-bank-host-gather",
        doc="no host/replicated materialization of a sharded bank "
            "outside a declared export/checkpoint scope (never "
            "baseline-able)",
        check=_check,
        group="spmd",
    )
)
