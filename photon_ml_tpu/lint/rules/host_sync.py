"""PL001 hidden-host-sync: every device->host fetch goes through the
counted ``parallel/overlap.py`` seam.

A raw ``jax.device_get`` / ``.block_until_ready()`` / ``np.asarray`` /
``float()``-style cast on a device value is a synchronous host round
trip (~100 ms over a relay-attached chip, regardless of payload) that
the readback-discipline tests cannot count. PR 2 routed the GAME layer
through ``overlap.device_get``; this rule makes that a repo-wide
invariant. ``np.asarray``/``float()``/``int()``/``bool()`` are only
flagged when the argument provably holds a jax value (locally assigned
from a ``jax.*``/``jnp.*`` expression) — low-noise by construction.

The rule also audits ``# photon: allow(hidden-host-sync)`` sites inside
``photon_ml_tpu/``: an allowed raw fetch must still be *accounted* — its
enclosing scope has to touch the seam (``overlap.device_get`` /
``fetch_all``) or the overlap-off serial switch (``overlap_enabled`` /
``overlap_scope``). An allow comment that routes around the counter
without either is itself a violation, and that audit violation cannot be
suppressed by the comment it audits (only baselined or fixed).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from photon_ml_tpu.lint.core import (
    FileContext,
    Rule,
    Violation,
    attr_root,
    register,
)

_CASTS = {"float", "int", "bool"}
_NP_HOST_FUNCS = {"asarray", "array"}
# referencing any of these marks a scope as seam-aware: it either feeds
# the counted readback path or switches on the overlap-off serial path
_SEAM_NAMES = {
    "fetch_all", "overlap_enabled", "overlap_scope", "readback_stats",
}


def _is_overlap_device_get(ctx: FileContext, call: ast.Call) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and func.attr == "device_get":
        return ctx.is_overlap_module(func.value)
    if isinstance(func, ast.Name) and func.id == "device_get":
        return "device_get" in ctx.overlap_names
    return False


def _scope_at_line(ctx: FileContext, line: int) -> ast.AST:
    best: Optional[ast.AST] = None
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    return best if best is not None else ctx.tree


def seam_accounted(ctx: FileContext, line: int) -> bool:
    """Is the allow-site at ``line`` accounted: does its enclosing scope
    reference the counted seam or the overlap on/off switch?"""
    scope = _scope_at_line(ctx, line)
    if ctx.scope_calls(scope, _SEAM_NAMES):
        return True
    for node in ctx.walk_scope(scope):
        if isinstance(node, ast.Call) and _is_overlap_device_get(ctx, node):
            return True
    return False


def _check(ctx: FileContext) -> Iterator[Violation]:
    if ctx.path.endswith("parallel/overlap.py"):
        # the seam itself is the one legitimate home of raw fetches
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "device_get":
            if ctx.is_jax_module(func.value):
                yield ctx.violation(
                    RULE, node,
                    "raw jax.device_get bypasses the counted "
                    "overlap.device_get seam — route the fetch through "
                    "photon_ml_tpu.parallel.overlap.device_get (or batch "
                    "it via Deferred/fetch_all)",
                )
        elif isinstance(func, ast.Name) and func.id == "device_get":
            if (
                "device_get" in ctx.jax_names
                and "device_get" not in ctx.overlap_names
            ):
                yield ctx.violation(
                    RULE, node,
                    "raw device_get (imported from jax) bypasses the "
                    "counted overlap.device_get seam",
                )
        elif (
            isinstance(func, ast.Attribute)
            and func.attr == "block_until_ready"
        ):
            yield ctx.violation(
                RULE, node,
                "block_until_ready() is a hidden host sync — the device "
                "queue drains into a host stall the readback tests "
                "cannot see; prefer Deferred/fetch_all, or allow() a "
                "timing harness explicitly",
            )
        elif isinstance(func, ast.Attribute) and func.attr in _NP_HOST_FUNCS:
            if ctx.is_numpy_module(attr_root(func)) and node.args:
                taint = ctx.jax_taint(ctx.scope_of(node))
                if ctx.expr_tainted(node.args[0], taint):
                    yield ctx.violation(
                        RULE, node,
                        f"np.{func.attr} on a jax value forces a "
                        "device->host copy outside the counted seam — "
                        "fetch through overlap.device_get first",
                    )
        elif (
            isinstance(func, ast.Name)
            and func.id in _CASTS
            and len(node.args) == 1
            and not node.keywords
        ):
            taint = ctx.jax_taint(ctx.scope_of(node))
            if ctx.expr_tainted(node.args[0], taint):
                yield ctx.violation(
                    RULE, node,
                    f"{func.id}() on a jax value is a synchronous "
                    "per-scalar readback — keep it a device scalar "
                    "(Deferred) and batch the fetch",
                )
    # allow-site audit: seam_ok is recorded for EVERY hidden-host-sync
    # allow site (listed in --json); only package code turns an
    # unaccounted site into a violation — bench/test timing harnesses
    # may legitimately sync without feeding the seam.
    in_package = "photon_ml_tpu" in ctx.path_parts()
    audited = set()
    for site in ctx.allow_sites:
        if not (site.rules & {"PL001", "hidden-host-sync"}):
            continue
        site.seam_ok = seam_accounted(ctx, site.applies_to)
        if site.applies_to in audited:
            continue  # stacked comments on one line: audit it once
        audited.add(site.applies_to)
        if in_package and not site.seam_ok:
            yield Violation(
                rule=RULE.id, slug=RULE.slug, path=ctx.path,
                line=site.applies_to, col=0,
                message=(
                    "allow(hidden-host-sync) site is unaccounted: "
                    "the enclosing scope neither routes through "
                    "overlap.device_get/fetch_all nor gates on the "
                    "overlap-off serial path (overlap_enabled/"
                    "overlap_scope) — the readback would be "
                    "invisible to the seam counter"
                ),
                snippet=ctx.snippet(site.applies_to),
                suppressable=False,
            )


RULE = register(
    Rule(
        id="PL001",
        slug="hidden-host-sync",
        doc="device->host fetches must route through overlap.device_get",
        check=_check,
    )
)
