"""PL013 reduction-completeness: a shard_map body's collectives agree
with its specs.

Two converse hazards, both silent until runtime (or worse, silently
wrong under ``check_vma=False``, which every entry point in this repo
passes for compat-shim reasons):

- **Unreduced replication claim.** An ``out_specs`` entry of ``P()``
  promises every device returns the SAME value; a returned value that
  provably derives from a sharded input with no ``psum``/``pmean``/
  ``pmax``/``pmin``/``all_gather`` over the mapped axis on its dataflow
  is device-varying — the per-device partials the replication claim
  papers over.
- **Unbound reduction.** A ``psum``-family call over an axis that the
  site's in/out specs never shard multiplies replicated values by the
  axis size (or binds a stale axis name) — the grid/entity refactors'
  classic copy-paste failure.

The dataflow is deliberately lightweight (the PL010 altitude): a
straight-line taint over the mapped body with three states —
sharded / clean / unknown. Reduction collectives clear taint; calls
into same-file helpers are resolved ONE hop (a helper that psums over
the mapped axis discharges the obligation — the repo's objective
closures do exactly this); any call the analyzer cannot resolve makes
the result UNKNOWN, and unknown is never flagged. Axis identity is
symbolic: ``P(ax)`` in the specs binds the psum over ``ax`` in the body
whether or not ``ax`` resolves to a constant.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from photon_ml_tpu.lint import spmd
from photon_ml_tpu.lint.core import (
    FileContext,
    PackageContext,
    PackageRule,
    Violation,
    attr_root,
    call_name,
    register_package,
)

CLEAN, UNKNOWN, SHARDED = 0, 1, 2


def _axis_key(model: spmd.SpmdFileModel, expr: ast.AST,
              scope: ast.AST) -> Optional[str]:
    """Stable identity for an axis expression: the canonical value when
    resolvable, else the symbol name, else None (unresolvable)."""
    kind, val = model.resolve_axis(expr, scope)
    if kind in ("const", "literal"):
        return val
    if kind == "symbol":
        return val
    return None


def _spec_axis_keys(model: spmd.SpmdFileModel,
                    entry: spmd.SpmdEntry) -> Optional[Set[str]]:
    """Axis identities the site's specs mention; None when the specs
    are not statically analyzable (computed tuples)."""
    keys: Set[str] = set()
    any_known = False
    for expr in (entry.in_spec_exprs, entry.out_spec_exprs):
        if expr is None:
            continue
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and call_name(sub) in (
                "P", "PartitionSpec"
            ):
                any_known = True
                for arg in sub.args:
                    for leaf in ast.walk(arg):
                        if isinstance(leaf, (ast.Name, ast.Constant)):
                            k = _axis_key(model, leaf, sub)
                            if k:
                                keys.add(k)
    if not any_known:
        return None
    return keys


def _out_spec_list(entry: spmd.SpmdEntry) -> Optional[List[ast.AST]]:
    expr = entry.out_spec_exprs
    if expr is None:
        return None
    if isinstance(expr, ast.Call) and call_name(expr) in (
        "P", "PartitionSpec"
    ):
        return [expr]
    if isinstance(expr, (ast.Tuple, ast.List)):
        out = []
        for e in expr.elts:
            if isinstance(e, ast.Call) and call_name(e) in (
                "P", "PartitionSpec"
            ):
                out.append(e)
            else:
                return None
        return out
    return None


def _is_replicated_spec(p_call: ast.Call) -> bool:
    return not p_call.args or all(
        isinstance(a, ast.Constant) and a.value is None
        for a in p_call.args
    )


class _BodyTaint:
    """Three-state taint over one mapped function body."""

    def __init__(self, ctx: FileContext, model: spmd.SpmdFileModel,
                 fn: ast.FunctionDef, sharded_params: Set[str]):
        self.ctx = ctx
        self.model = model
        self.fn = fn
        self.env: Dict[str, int] = {p: SHARDED for p in sharded_params}
        # nested defs are opaque callables (they may close over sharded
        # state); calling one yields UNKNOWN
        self.nested: Set[str] = {
            n.name for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }
        for _ in range(6):
            before = dict(self.env)
            for node in self.ctx.walk_scope(fn):
                if isinstance(node, ast.Assign):
                    st = self.classify(node.value)
                    for tgt in node.targets:
                        self._bind(tgt, st)
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    st = max(
                        self.classify(node.value),
                        self.env.get(node.target.id, CLEAN),
                    )
                    self.env[node.target.id] = st
                elif isinstance(node, (ast.For,)):
                    self._bind(node.target, self.classify(node.iter))
            if self.env == before:
                break

    def _bind(self, target: ast.AST, state: int) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = max(
                self.env.get(target.id, CLEAN), state
            )
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, state)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, state)

    def _helper_reduces(self, name: str) -> Optional[bool]:
        """Does the same-file helper contain a reduction collective?
        None when there is no such helper."""
        target = self.model.local_defs.get(name)
        if target is None or target is self.fn:
            return None
        for sub in ast.walk(target):
            if isinstance(sub, ast.Call) and call_name(sub) in \
                    spmd.REDUCTIONS:
                return True
        return False

    def classify(self, expr: ast.AST) -> int:
        if isinstance(expr, ast.Constant):
            return CLEAN
        if isinstance(expr, ast.Name):
            if expr.id in self.nested:
                return UNKNOWN
            return self.env.get(expr.id, CLEAN)
        if isinstance(expr, ast.Call):
            name = call_name(expr)
            if name in spmd.REDUCTIONS:
                return CLEAN
            if name == "axis_index":
                return SHARDED
            func = expr.func
            if isinstance(func, ast.Name):
                reduces = self._helper_reduces(func.id)
                if reduces is True:
                    return CLEAN
                if reduces is False:
                    # the helper might reduce two hops down — cap at
                    # UNKNOWN rather than over-claim SHARDED
                    return UNKNOWN
                if func.id in self.nested:
                    return UNKNOWN
            root = attr_root(func) if isinstance(func, ast.Attribute) \
                else None
            if root is not None and (
                root.id in self.ctx.jax_modules
                or root.id in self.ctx.numpy_modules
                or root.id in ("lax", "jax", "jnp", "np")
            ):
                states = [self.classify(a) for a in expr.args] + [
                    self.classify(k.value) for k in expr.keywords
                ]
                return max(states) if states else CLEAN
            if isinstance(func, ast.Attribute):
                # method on a value: x.reshape(...), x.at[i].set(v)
                base = self.classify(func.value)
                states = [base] + [self.classify(a) for a in expr.args]
                if base is not UNKNOWN and all(
                    s in (CLEAN, SHARDED) for s in states
                ) and self._is_array_method_chain(func):
                    return max(states)
            return UNKNOWN
        if isinstance(expr, ast.Attribute):
            return self.classify(expr.value)
        if isinstance(expr, ast.Subscript):
            return max(
                self.classify(expr.value), self.classify(expr.slice)
            )
        if isinstance(expr, ast.BinOp):
            return max(
                self.classify(expr.left), self.classify(expr.right)
            )
        if isinstance(expr, ast.UnaryOp):
            return self.classify(expr.operand)
        if isinstance(expr, ast.Compare):
            return max(
                [self.classify(expr.left)]
                + [self.classify(c) for c in expr.comparators]
            )
        if isinstance(expr, ast.BoolOp):
            return max(self.classify(v) for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return max(
                self.classify(expr.body), self.classify(expr.orelse)
            )
        if isinstance(expr, (ast.Tuple, ast.List)):
            return max(
                (self.classify(e) for e in expr.elts), default=CLEAN
            )
        if isinstance(expr, ast.Starred):
            return self.classify(expr.value)
        return UNKNOWN

    def _is_array_method_chain(self, func: ast.Attribute) -> bool:
        """x.reshape / x.at[...].set / x.astype — shape-preserving
        array methods whose taint is their receiver's."""
        return func.attr in {
            "reshape", "astype", "set", "add", "take", "sum", "max",
            "min", "mean", "at", "get", "transpose", "ravel",
        }


def _mapped_params(entry: spmd.SpmdEntry,
                   model: spmd.SpmdFileModel) -> Optional[Set[str]]:
    """Parameter names of the mapped fn whose in_spec mentions an axis;
    None when the pairing is not statically determinable."""
    fn = entry.mapped_fn
    expr = entry.in_spec_exprs
    if fn is None or expr is None:
        return None
    a = fn.args
    params = [p.arg for p in list(a.posonlyargs) + list(a.args)]
    if not isinstance(expr, (ast.Tuple, ast.List)):
        return None
    specs = list(expr.elts)
    if len(specs) != len(params):
        if a.vararg is None:
            return None
        # trailing *rest absorbs the remainder: pair the prefix, and
        # treat *rest as sharded if ANY remaining spec mentions an axis
        pass
    sharded: Set[str] = set()

    def mentions_axis(spec: ast.AST) -> Optional[bool]:
        if not (isinstance(spec, ast.Call) and call_name(spec) in (
            "P", "PartitionSpec"
        )):
            return None
        for arg in spec.args:
            for leaf in ast.walk(arg):
                if isinstance(leaf, ast.Name):
                    return True
                if isinstance(leaf, ast.Constant) and isinstance(
                    leaf.value, str
                ):
                    return True
        return False

    for p, s in zip(params, specs):
        m = mentions_axis(s)
        if m is None:
            return None
        if m:
            sharded.add(p)
    if a.vararg is not None and len(specs) > len(params):
        rest = specs[len(params):]
        for s in rest:
            if mentions_axis(s):
                sharded.add(a.vararg.arg)
                break
    return sharded


def _check_entry(ctx: FileContext, model: spmd.SpmdFileModel,
                 entry: spmd.SpmdEntry) -> Iterator[Violation]:
    if entry.kind != "shard_map":
        return
    fn = entry.mapped_fn
    spec_keys = _spec_axis_keys(model, entry)
    # -- unbound collectives -------------------------------------------------
    if fn is not None and spec_keys is not None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or not spmd.is_collective(
                node
            ):
                continue
            axis_arg = spmd.collective_axis_arg(node)
            if axis_arg is None:
                continue
            # tuple axis args: check each element
            elems = axis_arg.elts if isinstance(
                axis_arg, (ast.Tuple, ast.List)
            ) else [axis_arg]
            for el in elems:
                key = _axis_key(model, el, node)
                if key is None:
                    continue
                if key not in spec_keys:
                    yield ctx.violation(RULE, node, (
                        f"{call_name(node)} over axis '{key}' inside "
                        f"'{entry.qualname}', whose in/out specs never "
                        "shard that axis — a reduction over a "
                        "replicated (or stale) axis multiplies by the "
                        "axis size or fails to bind"
                    ))
    # -- unreduced replication claims ----------------------------------------
    if fn is None:
        return
    out_specs = _out_spec_list(entry)
    sharded_params = _mapped_params(entry, model)
    if out_specs is None or sharded_params is None:
        return
    taint = _BodyTaint(ctx, model, fn, sharded_params)
    for node in ctx.walk_scope(fn):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        ret = node.value
        rets: List[ast.AST]
        if isinstance(ret, ast.Tuple) and len(ret.elts) == len(
            out_specs
        ):
            rets = list(ret.elts)
        elif len(out_specs) == 1:
            rets = [ret]
        else:
            continue
        for pos, (expr, spec) in enumerate(zip(rets, out_specs)):
            if not _is_replicated_spec(spec):
                continue
            if taint.classify(expr) == SHARDED:
                yield ctx.violation(RULE, expr, (
                    f"output {pos} of '{entry.qualname}' claims "
                    "replication (out_specs P()) but derives from a "
                    "sharded input with no psum/pmean/all_gather over "
                    "the mapped axis on its path — every device "
                    "returns a DIFFERENT value under check_vma=False"
                ))


def _check(pkg: PackageContext) -> Iterator[Violation]:
    idx = spmd.index(pkg)
    for path in sorted(pkg.contexts):
        ctx = pkg.contexts[path]
        model = idx.models[path]
        for entry in model.entries:
            yield from _check_entry(ctx, model, entry)


RULE = register_package(
    PackageRule(
        id="PL013",
        slug="reduction-completeness",
        doc="shard_map bodies psum what their out_specs claim "
            "replicated, and only over axes the specs shard",
        check=_check,
        group="spmd",
    )
)
