"""PL011 mesh-axis-discipline: axis names are constants, and every mesh
entry point carries a machine-checked sharding contract.

Two failure families this rule turns from runtime XLA errors (or silent
drift) into lint failures:

1. **Axis-name literals.** Every axis-name string passed to
   ``lax.psum``/``pmean``/``all_to_all``/``all_gather``/``P(...)``/
   ``shard_map(..., mesh=...)``/``Mesh(axis_names=...)`` — or bound as
   an axis-parameter default — must reference a ``parallel/mesh.py``
   constant (``DATA_AXIS``/``MODEL_AXIS``/``ENTITY_AXIS``). A literal
   that matches a canonical axis is a drift hazard (renaming the
   constant silently strands it); a literal that matches nothing is a
   stale or typo'd axis that would only fail at mesh-binding time.
   ``parallel/mesh.py`` itself — the one legitimate home of the literal
   spellings — is exempt.

2. **Sharding contracts.** Every jit/shard_map mesh entry point in
   package code must carry a ``# photon: sharding(axes=..., in=...,
   out=...)`` declaration on its def line, and the declaration is
   CROSS-CHECKED against the code: declared axes must be canonical,
   importable in the module, cover every axis the specs resolve, and
   match the number of distinct axis bindings; literal in/out spec
   lists and resolvable donate_argnums are compared element-wise. A
   declaration is a contract, never a suppression — a contract that
   drifts from the code is itself the violation, which is what keeps
   the generated SHARDING.md (lint/sharding_contracts.py) a trustworthy
   map for the unified-mesh refactor.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set, Tuple

from photon_ml_tpu.lint import spmd
from photon_ml_tpu.lint.core import (
    FileContext,
    PackageContext,
    PackageRule,
    Violation,
    call_name,
    register_package,
)

_CONST_HINT = (
    "DATA_AXIS/MODEL_AXIS/ENTITY_AXIS/GRID_AXIS "
    "(photon_ml_tpu.parallel.mesh)"
)


def _literal_violations(ctx: FileContext) -> Iterator[Violation]:
    if ctx.path.endswith("parallel/mesh.py"):
        return
    seen: Set[Tuple[int, int]] = set()

    def flag(node: ast.AST, literal: str):
        key = (getattr(node, "lineno", 0), getattr(node, "col_offset", 0))
        if key in seen or not literal:
            return None
        seen.add(key)
        if literal in spmd.CANONICAL_AXES:
            msg = (
                f"axis-name literal '{literal}' — reference the mesh "
                f"constant instead ({_CONST_HINT}) so a renamed or "
                "retired axis fails at lint time, not at runtime"
            )
        else:
            msg = (
                f"unknown mesh axis literal '{literal}' — not one of "
                f"{'/'.join(spmd.CANONICAL_AXES)}; a stale or typo'd "
                "axis string binds to nothing and only fails when XLA "
                "rejects the collective"
            )
        return ctx.violation(RULE, node, msg)

    def flag_strings_in(expr: ast.AST):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Constant) and isinstance(
                sub.value, str
            ):
                v = flag(sub, sub.value)
                if v:
                    yield v

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("P", "PartitionSpec"):
                for arg in node.args:
                    yield from flag_strings_in(arg)
            elif spmd.is_collective(node):
                axis_arg = spmd.collective_axis_arg(node)
                if axis_arg is not None:
                    yield from flag_strings_in(axis_arg)
            elif name in ("shard_map", "Mesh", "make_mesh",
                          "entity_mesh"):
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        yield from flag_strings_in(kw.value)
                if name == "Mesh" and len(node.args) > 1:
                    yield from flag_strings_in(node.args[1])
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            params = list(a.posonlyargs) + list(a.args)
            defaults = list(a.defaults)
            pairs = list(zip(params[-len(defaults):], defaults)) if \
                defaults else []
            pairs += [
                (p, d) for p, d in zip(a.kwonlyargs, a.kw_defaults)
                if d is not None
            ]
            for p, d in pairs:
                if spmd.is_axis_param_name(p.arg) and isinstance(
                    d, ast.Constant
                ) and isinstance(d.value, str) and d.value:
                    v = flag(d, d.value)
                    if v:
                        yield v
        elif isinstance(node, ast.BoolOp):
            names = [
                v for v in node.values
                if isinstance(v, ast.Name)
                and spmd.is_axis_param_name(v.id)
            ]
            if names:
                for v_ in node.values:
                    if isinstance(v_, ast.Constant) and isinstance(
                        v_.value, str
                    ) and v_.value:
                        v = flag(v_, v_.value)
                        if v:
                            yield v


def _contract_violations(
    ctx: FileContext, model: spmd.SpmdFileModel,
) -> Iterator[Violation]:
    in_package = "photon_ml_tpu" in ctx.path_parts()
    available = set(model.axis_env.values())
    for entry in model.entries:
        decl = entry.decl
        if decl is None:
            if in_package and entry.kind != "declared":
                yield ctx.violation(RULE, entry.node, (
                    f"mesh entry point '{entry.qualname}' "
                    f"({entry.kind}) has no '# photon: sharding(...)' "
                    "declaration — declare axes/in/out on the def line "
                    "so the contract is machine-checked and SHARDING.md "
                    "stays a complete inventory"
                ))
            continue
        for err in decl.errors:
            yield ctx.violation(RULE, entry.node, (
                f"sharding declaration on '{entry.qualname}': {err}"
            ))
        if decl.axes is None:
            if not decl.export:
                yield ctx.violation(RULE, entry.node, (
                    f"sharding declaration on '{entry.qualname}' names "
                    "no axes — declare axes=[...] ([] for a mesh-less "
                    "donation/program entry)"
                ))
            continue
        declared = list(decl.axes)
        for a in declared:
            if a not in spmd.CANONICAL_AXES:
                yield ctx.violation(RULE, entry.node, (
                    f"sharding declaration on '{entry.qualname}' names "
                    f"unknown axis '{a}' — not one of "
                    f"{'/'.join(spmd.CANONICAL_AXES)} (stale or typo'd)"
                ))
            elif available and a not in available and \
                    a not in entry.axes_resolved:
                # availability is only checkable in modules that bind
                # at least one axis constant; axis-generic modules
                # (e.g. the residual router, which takes the axis from
                # the mesh) declare their conventional axis freely
                yield ctx.violation(RULE, entry.node, (
                    f"sharding declaration on '{entry.qualname}' names "
                    f"axis '{a}' but this module neither imports its "
                    "mesh constant nor binds it — a contract for an "
                    "axis the code cannot reference is drift"
                ))
        declared_ok = [a for a in declared if a in spmd.CANONICAL_AXES]
        missing = sorted(entry.axes_resolved - set(declared_ok))
        if missing:
            yield ctx.violation(RULE, entry.node, (
                f"'{entry.qualname}' binds ax{'es' if len(missing) > 1 else 'is'} "
                f"{'/'.join(missing)} that the sharding declaration "
                "does not name — the declared contract drifted from "
                "the code"
            ))
        if entry.kind == "shard_map" and entry.in_rendered is not None \
                and entry.out_rendered is not None:
            # only fully-literal specs pin the axis count statically —
            # helper-built specs (and jit out_shardings) contribute to
            # axes_resolved but can hide axes the body reduces over
            used = len(entry.axes_resolved) + len(
                entry.axis_symbols - set(entry.axes_resolved)
            )
            if used != len(set(declared_ok)) and not missing:
                yield ctx.violation(RULE, entry.node, (
                    f"'{entry.qualname}' declares "
                    f"{len(set(declared_ok))} ax(es) but the code "
                    f"binds {used} distinct ax(es)/symbol(s) — the "
                    "contract drifted from the code"
                ))
        mapping = entry.symbol_mapping()
        for declared_list, rendered, label in (
            (decl.in_specs, spmd.substitute(entry.in_rendered, mapping),
             "in"),
            (decl.out_specs, spmd.substitute(entry.out_rendered, mapping),
             "out"),
        ):
            if declared_list is None or rendered is None:
                continue
            if not spmd.specs_match(declared_list, rendered):
                yield ctx.violation(RULE, entry.node, (
                    f"'{entry.qualname}' declares {label}="
                    f"[{','.join(declared_list)}] but the code's "
                    f"{label}_specs render as [{','.join(rendered)}] — "
                    "the contract drifted from the code"
                ))
        if decl.donates is not None and entry.donates is not None:
            if decl.donates != entry.donates:
                yield ctx.violation(RULE, entry.node, (
                    f"'{entry.qualname}' declares donates="
                    f"{decl.donates} but the code donates "
                    f"{entry.donates}"
                ))


def _check(pkg: PackageContext) -> Iterator[Violation]:
    idx = spmd.index(pkg)
    for path in sorted(pkg.contexts):
        ctx = pkg.contexts[path]
        yield from _literal_violations(ctx)
        yield from _contract_violations(ctx, idx.models[path])


RULE = register_package(
    PackageRule(
        id="PL011",
        slug="mesh-axis-discipline",
        doc="axis names reference mesh constants; every jit/shard_map "
            "entry point carries a cross-checked sharding contract",
        check=_check,
        group="spmd",
    )
)
