"""PL004 spill-hygiene: scratch dirs must register for the atexit sweep.

The disk-spill stores (GLM chunk cache, GAME chunk/score/bucket
segments) can hold multi-GB scratch; ``__del__`` is not a cleanup
contract (PR 3: a driver exception pinning the objective in a traceback
skips finalizers and leaks the scratch). Every spill directory created
under ``io/`` or the GAME streaming layer must go through
``make_spill_dir`` or pair its ``mkdtemp``/``TemporaryDirectory`` with
``register_spill_dir`` in the same scope, so ``_sweep_spill_dirs`` can
reclaim it at interpreter exit.
"""

from __future__ import annotations

import ast
from typing import Iterator

from photon_ml_tpu.lint.core import (
    FileContext,
    Rule,
    Violation,
    call_name,
    register,
)

_TMP_FACTORIES = {"mkdtemp", "TemporaryDirectory"}
_REGISTRARS = {"register_spill_dir", "make_spill_dir"}


def _applies(ctx: FileContext) -> bool:
    return "io" in ctx.path_parts() or ctx.path.endswith(
        "game/streaming.py"
    )


def _check(ctx: FileContext) -> Iterator[Violation]:
    if not _applies(ctx):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) not in _TMP_FACTORIES:
            continue
        scope = ctx.scope_of(node)
        if ctx.scope_calls(scope, _REGISTRARS):
            continue
        yield ctx.violation(
            RULE, node,
            f"{call_name(node)} in the spill layer without "
            "register_spill_dir: the scratch dir dodges the atexit "
            "sweep and leaks on driver exceptions — use "
            "io.streaming.make_spill_dir (or register explicitly in "
            "this scope)",
        )


RULE = register(
    Rule(
        id="PL004",
        slug="spill-hygiene",
        doc="spill scratch dirs under io// game streaming register for "
            "the atexit sweep",
        check=_check,
    )
)
