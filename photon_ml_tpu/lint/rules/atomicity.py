"""PL010 atomicity-hygiene: critical sections stay small, private, and
actually atomic.

Three failure shapes the serving/registry thread plane is prone to,
each one a bug the chaos runs can only catch probabilistically:

- **check-then-act across a lock release.** A guarded field is read
  under the lock, the lock is released (slow work happens), then the
  field is written under the lock again in the same method — the
  decision is stale by the time it lands (the watcher's
  read-live/stage/write-live rollback shape). Flagged unless some
  OUTER lock is provably held across both sections (that is the
  sanctioned serialize-the-whole-protocol fix).
- **foreign work under a condition-backed lock.** While holding a lock
  that backs a ``Condition`` (the batcher's queue lock — the one
  submitters and the dispatcher park on), calling a user callback
  (``on_*``/``*_hook``/``*_handler``/``*_provider``), a known-blocking
  primitive (``sendall``/``recv``/``sleep``...), or another package
  component's lock-taking method stretches everyone's wakeup latency
  and invites reentrancy deadlocks. Move the call outside the critical
  section; capture what it needs under the lock.
- **notify without the condition's lock.** ``cond.notify()`` /
  ``notify_all()`` without holding the condition's backing lock raises
  at runtime at best and loses wakeups at worst (the missed-wakeup
  hang the drain tests chase).
"""

from __future__ import annotations

import ast
from typing import Iterator, Set, Tuple

from photon_ml_tpu.lint.core import (
    _BLOCKING_TAILS,
    _CALLBACK_NAME_RE,
    _ONE_HOP_STOPLIST,
    ClassModel,
    PackageContext,
    PackageRule,
    Violation,
    call_name,
    register_package,
)


def _check_then_act(model: ClassModel) -> Iterator[Violation]:
    """Read-under-lock then write-under-same-lock-later with the lock
    released in between (and no outer lock held across)."""
    by_method: dict = {}
    for attr, accs in model.accesses.items():
        if attr in model.lock_names() | model.safe_attrs:
            continue
        for a in accs:
            if not a.in_init and a.locks_held:
                by_method.setdefault((a.method, attr), []).append(a)
    emitted: Set[Tuple[int, str]] = set()
    for (method, attr), accs in sorted(by_method.items()):
        accs.sort(key=lambda a: getattr(a.node, "lineno", 0))
        for i, first in enumerate(accs):
            if first.is_write:
                continue
            for later in accs[i + 1:]:
                if not later.is_write:
                    continue
                shared = first.locks_held & later.locks_held
                if not shared:
                    continue
                # a (lock, acquisition-site) pair present at BOTH
                # accesses means that lock was held continuously
                if first.lock_acqs & later.lock_acqs:
                    continue
                line = getattr(later.node, "lineno", 0)
                key = (line, attr)
                if key in emitted:
                    continue
                emitted.add(key)
                lk = sorted(shared)[0]
                yield model.ctx.violation(
                    RULE, later.node,
                    f"check-then-act across a lock release: "
                    f"'{model.name}.{attr}' was read under "
                    f"self.{lk} at line "
                    f"{getattr(first.node, 'lineno', '?')}, the lock "
                    "was released, and this write re-acquires it — "
                    "the decision is stale; hold one lock across the "
                    "whole protocol or re-check under the lock here",
                )


def _under_lock_calls(
    model: ClassModel, pkg: PackageContext,
) -> Iterator[Violation]:
    index = pkg._method_lock_index()
    emitted: Set[int] = set()
    for mname, sc in model._scanners.items():
        for call, held in sc.calls_under_lock:
            hot = held & model.cond_backed
            if not hot:
                continue
            line = getattr(call, "lineno", 0)
            if line in emitted:
                continue
            name = call_name(call)
            func = call.func
            lk = sorted(hot)[0]
            if isinstance(func, ast.Attribute) and _CALLBACK_NAME_RE.match(
                name
            ):
                emitted.add(line)
                yield model.ctx.violation(
                    RULE, call,
                    f"user callback '{name}' invoked while holding "
                    f"self.{lk} (a Condition-backed lock): arbitrary "
                    "code inside the critical section stalls every "
                    "parked waiter — capture under the lock, call "
                    "after release",
                )
                continue
            if name in _BLOCKING_TAILS:
                emitted.add(line)
                yield model.ctx.violation(
                    RULE, call,
                    f"blocking call '{name}' while holding self.{lk} "
                    "(a Condition-backed lock) — waiters park behind "
                    "real IO/sleep time; move it outside the critical "
                    "section",
                )
                continue
            if (
                isinstance(func, ast.Attribute)
                and not isinstance(func.value, ast.Name)
                or (
                    isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id != "self"
                )
            ):
                if name not in _ONE_HOP_STOPLIST and index.get(name):
                    targets = sorted(
                        {f"{t[1]}.{t[2]}" for t in index[name]}
                    )
                    emitted.add(line)
                    yield model.ctx.violation(
                        RULE, call,
                        f"'{name}' (which acquires {', '.join(targets)}) "
                        f"called while holding self.{lk}, a Condition-"
                        "backed lock — foreign critical sections do "
                        "not belong inside the wait lock; record the "
                        "fact under the lock, call after release",
                    )


def _notify_discipline(model: ClassModel) -> Iterator[Violation]:
    for mname, sc in model._scanners.items():
        for call, cond, held in sc.notifies:
            backing = model.cond_alias.get(cond, cond)
            if backing not in held:
                yield model.ctx.violation(
                    RULE, call,
                    f"{call_name(call)}() on self.{cond} without "
                    f"holding its lock (self.{backing}) — notify "
                    "outside the condition's lock races the waiter's "
                    "predicate re-check and loses wakeups",
                )


def _check(pkg: PackageContext) -> Iterator[Violation]:
    for model in pkg.all_classes():
        if not model.concurrent:
            continue
        yield from _check_then_act(model)
        yield from _under_lock_calls(model, pkg)
        yield from _notify_discipline(model)


RULE = register_package(
    PackageRule(
        id="PL010",
        slug="atomicity-hygiene",
        doc="no stale check-then-act across a lock release, no "
            "callbacks/blocking/foreign locks inside a Condition-backed "
            "critical section, notify only under the condition's lock",
        check=_check,
    )
)
