"""PL017 float-accumulation-order: host-side ``sum()`` /
``math.fsum`` / ``np.sum`` over an unordered collection. Float
addition is not associative, so the result's low bits follow the
iteration order — which for sets and listdir results follows
``PYTHONHASHSEED`` or the filesystem. Any bitwise-gated value fed by
such a sum (the router's f32 re-sum, conservation-ledger joins, gate
verdicts) then flaps between runs. The contract: accumulate in a
declared canonical order — ``sum(sorted(xs))`` — or keep the
collection ordered end to end.
"""

from __future__ import annotations

from typing import Iterator

from photon_ml_tpu.lint import determinism
from photon_ml_tpu.lint.core import (
    PackageContext,
    PackageRule,
    Violation,
    register_package,
)


def _check(pkg: PackageContext) -> Iterator[Violation]:
    for path in sorted(pkg.contexts):
        ctx = pkg.contexts[path]
        for node, msg in determinism.file_model(ctx).pl017:
            yield ctx.violation(RULE, node, msg)


RULE = register_package(
    PackageRule(
        id="PL017",
        slug="float-accumulation-order",
        doc="host-side sum()/fsum/np.sum over unordered collections "
            "must iterate a declared canonical order",
        check=_check,
        group="determinism",
    )
)
