"""PL005 undrained-io: every ``submit_io`` scope reaches a ``drain_io``.

``submit_io`` queues artifact writes (checkpoints, metrics, score
parts) on the overlap IO worker; nothing guarantees they hit disk until
``drain_io`` — the barrier before preemption stop, restore, or process
exit. A scope that submits and never drains can exit with writes still
queued: silently truncated artifacts. A function that hands the drain
responsibility to its caller (the driver ``preprocess``/``run`` split)
documents it with ``# photon: allow(undrained-io)`` at the submit site.
"""

from __future__ import annotations

import ast
from typing import Iterator

from photon_ml_tpu.lint.core import (
    FileContext,
    Rule,
    Violation,
    call_name,
    register,
)


def _check(ctx: FileContext) -> Iterator[Violation]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) != "submit_io":
            continue
        scope = ctx.scope_of(node)
        if ctx.scope_calls(scope, {"drain_io"}):
            continue
        yield ctx.violation(
            RULE, node,
            "submit_io with no reachable drain_io in this scope: queued "
            "artifact writes may still be in flight at exit — call "
            "overlap.drain_io() before this scope returns, or allow() "
            "the site if a caller owns the barrier",
        )


RULE = register(
    Rule(
        id="PL005",
        slug="undrained-io",
        doc="submit_io scopes must reach drain_io (or hand the barrier "
            "to a documented caller)",
        check=_check,
    )
)
