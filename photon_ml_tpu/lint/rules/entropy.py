"""PL016 ambient-entropy-in-artifact: wall clocks, pids, hostnames,
``uuid``, unseeded ``random`` and the hash-randomized builtins
``hash()``/``id()`` must not reach content signatures, manifests,
wire payloads, cache keys or RNG seeds undeclared. Legitimate sites
(the tracer's boot nonce, span epochs, live telemetry timestamps)
carry a ``# photon: entropy(<reason>)`` declaration — an enforced
claim, like ``guarded-by`` and ``sharding()``: a reasonless or stale
declaration is itself a violation, and the rule refuses the baseline
(NEVER_BASELINE) because an inherited entropy leak in a signature is
exactly the drift the bitwise gates exist to catch.

Violations are not ``# photon: allow(...)``-suppressable: the only
ways out are deriving the value from content or declaring the
entropy.
"""

from __future__ import annotations

from typing import Iterator

from photon_ml_tpu.lint import determinism
from photon_ml_tpu.lint.core import (
    PackageContext,
    PackageRule,
    Violation,
    register_package,
)


def _check(pkg: PackageContext) -> Iterator[Violation]:
    for path in sorted(pkg.contexts):
        ctx = pkg.contexts[path]
        model = determinism.file_model(ctx)
        for node, msg in model.pl016:
            yield ctx.violation(RULE, node, msg, suppressable=False)
        for line, msg in model.stale:
            yield Violation(
                rule=RULE.id, slug=RULE.slug, path=ctx.path,
                line=line, col=0, message=msg,
                snippet=ctx.snippet(line), suppressable=False,
            )


RULE = register_package(
    PackageRule(
        id="PL016",
        slug="ambient-entropy-in-artifact",
        doc="clocks/pids/uuids/hash() must not reach signatures, "
            "manifests, cache keys or wire payloads without a "
            "'# photon: entropy(reason)' declaration",
        check=_check,
        group="determinism",
    )
)
