"""Whole-package determinism model for PL015-PL018.

Every load-bearing gate in this repo is a *bitwise* check — chaos-arm
parity, swap/rollback restore, registry content signatures, crash
resume, the conservation ledger. This module is the static view of the
discipline those checks silently assume, built the same way
``spmd.py`` builds the sharding view: pure stdlib ``ast``, never
importing the code under analysis.

Three per-file site families plus one package-wide inventory:

* **Unordered-order taint (PL015).** ``set``/``frozenset`` literals
  and constructors, ``os.listdir``/``os.scandir``/``os.walk``,
  ``glob.glob`` and set-algebra results mint *unordered* values; the
  taint follows scope-local assignments and order-preserving wrappers
  (``list``/``tuple``/``join``/comprehensions — wrapping a set in a
  list freezes an arbitrary order, it does not impose one) and is
  erased only by ``sorted()``/``min``/``max``. A site is an unordered
  value reaching a serialization or digest sink, or a bare ``for``
  over one inside an artifact-writing scope.

* **Ambient-entropy taint (PL016).** Wall clocks, pids, hostnames,
  ``uuid``, unseeded ``random``, ``os.urandom`` and the
  hash-randomized builtins ``hash()``/``id()`` taint names; the taint
  flows through calls, f-strings and container literals. Sites are
  entropy reaching a serialization/wire/digest sink, a cache-key
  position, an RNG seed, or a ``return`` payload. The *difference of
  two clock readings* is deliberately clean: an elapsed-time
  measurement is the artifact's data, not ambient identity leaking
  into it. Sites are governed by the ``# photon: entropy(<reason>)``
  declaration grammar (see core.py) — a declaration is an enforced
  claim (stale or reasonless ones are themselves violations), never a
  suppression, which is why PL016 also refuses the baseline.

* **Float-accumulation order (PL017).** Host-side ``sum()`` /
  ``math.fsum`` / ``np.sum`` over an unordered-tainted iterable: the
  float result depends on iteration order, so every bitwise gate
  downstream inherits ``PYTHONHASHSEED``. Sort first.

* **Wire-contract inventory (PL018).** A cross-check table over
  ``serving/wire.py``: every ``MSG_*`` constant must have an encoder
  (an ``append_frame`` caller), a decoder branch, a frontend/transport
  dispatch reference, and a fuzz-corpus entry in
  ``tests/test_wire.py`` (the ``WIRE_FUZZ_CORPUS`` dict keyed by
  ``wire.MSG_*``); every named ``WireError`` kind must appear in the
  frontend's error mapping. Like PL011's entry-point table, the
  inventory is machine-built so a new message type cannot ship
  half-wired — and the corpus leg makes a missing fuzz entry a lint
  failure, not a forgotten test.

Taint is scope-local (module globals flow into functions; attributes
and cross-function returns do not) — the ``return`` leg is what makes
producer functions declare their entropy at the source instead.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from photon_ml_tpu.lint.core import (
    FileContext,
    PackageContext,
    attr_root,
    call_name,
)

# -- taxonomies ---------------------------------------------------------------

# Ambient entropy sources, by dotted call name. Kinds:
#   clock   — wall/monotonic clocks (a Sub of two clock reads is clean)
#   ambient — process/host/random identity (pid, uuid, urandom, ...)
#   hash    — builtin hash(): PYTHONHASHSEED-dependent for str/bytes
#   id      — builtin id(): address-dependent, process-local
ENTROPY_CALLS: Dict[str, Tuple[str, str]] = {
    "time.time": ("clock", "time.time()"),
    "time.time_ns": ("clock", "time.time_ns()"),
    "time.monotonic": ("clock", "time.monotonic()"),
    "time.monotonic_ns": ("clock", "time.monotonic_ns()"),
    "time.perf_counter": ("clock", "time.perf_counter()"),
    "time.perf_counter_ns": ("clock", "time.perf_counter_ns()"),
    "time.process_time": ("clock", "time.process_time()"),
    "datetime.now": ("clock", "datetime.now()"),
    "datetime.utcnow": ("clock", "datetime.utcnow()"),
    "datetime.datetime.now": ("clock", "datetime.now()"),
    "datetime.datetime.utcnow": ("clock", "datetime.utcnow()"),
    "date.today": ("clock", "date.today()"),
    "datetime.date.today": ("clock", "date.today()"),
    "os.getpid": ("ambient", "os.getpid()"),
    "os.getppid": ("ambient", "os.getppid()"),
    "os.urandom": ("ambient", "os.urandom()"),
    "os.uname": ("ambient", "os.uname()"),
    "uuid.uuid1": ("ambient", "uuid.uuid1()"),
    "uuid.uuid4": ("ambient", "uuid.uuid4()"),
    "socket.gethostname": ("ambient", "socket.gethostname()"),
    "socket.getfqdn": ("ambient", "socket.getfqdn()"),
    "platform.node": ("ambient", "platform.node()"),
    "secrets.token_hex": ("ambient", "secrets.token_hex()"),
    "secrets.token_bytes": ("ambient", "secrets.token_bytes()"),
    "secrets.token_urlsafe": ("ambient", "secrets.token_urlsafe()"),
}

# module-level functions of the global (unseeded) random instance
_RANDOM_MODULE_FNS = {
    "random", "randint", "uniform", "choice", "choices", "randrange",
    "getrandbits", "sample", "gauss", "shuffle", "random_sample",
}

# Unordered-iteration mints, by dotted call name.
UNORDERED_CALLS: Dict[str, str] = {
    "set": "set(...)",
    "frozenset": "frozenset(...)",
    "os.listdir": "os.listdir(...)",
    "os.scandir": "os.scandir(...)",
    "os.walk": "os.walk(...)",
    "glob.glob": "glob.glob(...)",
    "glob.iglob": "glob.iglob(...)",
}

# set-algebra methods: the result is a set regardless of the receiver
_SET_METHODS = {
    "union", "intersection", "difference", "symmetric_difference",
}

# order-erasing calls: the unordered taint stops here
_ORDER_ERASERS = {"sorted", "min", "max", "len", "any", "all", "bool",
                  "count"}

# order-preserving wrappers: list(set(...)) freezes an arbitrary order
_ORDER_KEEPERS = {"list", "tuple", "iter", "enumerate", "reversed",
                  "join", "map", "filter", "chain"}

# -- sinks --------------------------------------------------------------------

# repo writer/serializer helpers, by trailing call name
SERIALIZE_SINKS = {
    "atomic_write_json", "atomic_write_text", "atomic_write_bytes",
    "write_manifest", "write_container", "write_datum",
    "write_models_in_text", "save_glm_models_avro", "write_sharding_md",
    "write_html_report", "_write_lines", "_write_parts", "build_store",
    "save_name_and_term_feature_sets",
}

# wire-plane encoders (serving/wire.py)
WIRE_SINKS = {
    "append_frame", "append_json", "append_score_request",
    "append_response",
}

# digest constructors: their args are sink positions, and names bound
# to them become digest objects whose .update() is a sink
DIGEST_CALLS = {"blake2b", "sha256", "sha1", "md5", "sha384", "sha512"}

# names whose presence marks a scope as artifact-writing (the PL015
# bare-for-loop leg only fires inside such scopes)
_SINK_SCOPE_NAMES = (
    SERIALIZE_SINKS | WIRE_SINKS | DIGEST_CALLS
    | {"dump", "dumps", "atomic_writer"}
)

_CACHE_KEY_METHODS = {"get", "setdefault", "pop"}
_SEED_SINKS = {"seed", "Random", "default_rng", "PRNGKey"}


def _dotted(call: ast.Call) -> str:
    parts: List[str] = []
    f = call.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if not isinstance(f, ast.Name):
        return ""
    parts.append(f.id)
    return ".".join(reversed(parts))


def _is_json_dump(call: ast.Call) -> bool:
    name = call_name(call)
    if name not in ("dump", "dumps"):
        return False
    func = call.func
    if isinstance(func, ast.Name):  # from json import dumps
        return True
    root = attr_root(func)
    return root is not None and root.id in ("json", "pickle", "marshal")


# -- per-file model -----------------------------------------------------------

Site = Tuple[ast.AST, str]  # (node, message)


class DeterminismFileModel:
    """Scope-local taint + determinism sites for one file."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.pl015: List[Site] = []
        self.pl016: List[Site] = []
        self.pl017: List[Site] = []
        self.stale: List[Tuple[int, str]] = []
        self.consumed: Set[int] = set()
        # module-scope taints seed every function scope
        self._module_et: Dict[str, Tuple[str, str]] = {}
        self._module_ut: Dict[str, str] = {}
        self._seen: Set[Tuple[int, str]] = set()
        self._build()

    # -- entropy expression walk ---------------------------------------------

    def _entropy_call(
        self, call: ast.Call, et: Dict[str, Tuple[str, str]]
    ) -> Optional[Tuple[str, str]]:
        dotted = _dotted(call)
        hit = ENTROPY_CALLS.get(dotted)
        if hit:
            return hit
        func = call.func
        if isinstance(func, ast.Name):
            if func.id == "hash":
                return ("hash", "hash() (PYTHONHASHSEED-dependent)")
            if func.id == "id":
                return ("id", "id() (address-dependent)")
        root = attr_root(func)
        if root is not None and root.id == "random" and isinstance(
            func, ast.Attribute
        ) and func.attr in _RANDOM_MODULE_FNS:
            return ("ambient", f"unseeded random.{func.attr}()")
        # Random()/default_rng() with no seed argument
        if call_name(call) in ("Random", "default_rng") and not call.args \
                and not call.keywords:
            return ("ambient", f"unseeded {call_name(call)}()")
        return None

    def _edesc(
        self, e: Optional[ast.AST], et: Dict[str, Tuple[str, str]]
    ) -> Optional[Tuple[str, str]]:
        if e is None or isinstance(e, (ast.Constant, ast.Lambda,
                                       ast.Compare)):
            # a comparison yields a decision, not entropy content
            return None
        if isinstance(e, ast.Name):
            return et.get(e.id)
        if isinstance(e, ast.Call):
            src = self._entropy_call(e, et)
            if src:
                return src
            if isinstance(e.func, ast.Attribute) and \
                    e.func.attr in _CACHE_KEY_METHODS:
                # the value looked up BY an entropic key is not itself
                # entropy — the cache-key leg flags the lookup
                return None
            for sub in list(e.args) + [kw.value for kw in e.keywords]:
                d = self._edesc(sub, et)
                if d:
                    return d
            if isinstance(e.func, ast.Attribute):
                # tainted.hex(), tainted.isoformat(), ...
                return self._edesc(e.func.value, et)
            return None
        if isinstance(e, ast.BinOp):
            left = self._edesc(e.left, et)
            right = self._edesc(e.right, et)
            if isinstance(e.op, ast.Sub) and (
                (left and left[0] == "clock")
                or (right and right[0] == "clock")
            ):
                # clock minus anything (or anything minus clock) is an
                # elapsed/remaining interval — a measurement, not
                # ambient identity; any non-clock entropy still flows
                for d in (left, right):
                    if d and d[0] != "clock":
                        return d
                return None
            return left or right
        if isinstance(e, ast.Subscript):
            # element access: the container's taint, not the key's
            return self._edesc(e.value, et)
        if isinstance(e, ast.Attribute):
            return self._edesc(e.value, et)
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                d = self._edesc(child, et)
            elif isinstance(child, ast.keyword):
                d = self._edesc(child.value, et)
            elif isinstance(child, ast.comprehension):
                d = self._edesc(child.iter, et)
            else:
                d = None
            if d:
                return d
        return None

    # -- unordered expression walk -------------------------------------------

    def _udesc(
        self, e: Optional[ast.AST], ut: Dict[str, str]
    ) -> Optional[str]:
        if e is None or isinstance(e, (ast.Constant, ast.Lambda)):
            return None
        if isinstance(e, ast.Name):
            return ut.get(e.id)
        if isinstance(e, ast.Set):
            return "set literal"
        if isinstance(e, ast.SetComp):
            return "set comprehension"
        if isinstance(e, ast.Call):
            name = call_name(e)
            if name in _ORDER_ERASERS or name == "sum":
                return None  # sorted()/min()/... erase; sum is PL017's
            dotted = _dotted(e)
            if dotted in UNORDERED_CALLS:
                return UNORDERED_CALLS[dotted]
            if isinstance(e.func, ast.Name) and e.func.id in (
                "set", "frozenset"
            ):
                return f"{e.func.id}(...)"
            if isinstance(e.func, ast.Attribute) and \
                    e.func.attr in _SET_METHODS:
                return f".{e.func.attr}(...)"
            if name in _ORDER_KEEPERS:
                subs = list(e.args) + [kw.value for kw in e.keywords]
                if name == "join" and isinstance(e.func, ast.Attribute):
                    pass  # sep.join(unordered): check args only
                for sub in subs:
                    d = self._udesc(sub, ut)
                    if d:
                        return d
            return None
        if isinstance(e, ast.BinOp) and isinstance(
            e.op, (ast.BitAnd, ast.BitOr, ast.BitXor, ast.Sub)
        ):
            return self._udesc(e.left, ut) or self._udesc(e.right, ut)
        if isinstance(e, (ast.ListComp, ast.GeneratorExp, ast.DictComp)):
            for gen in e.generators:
                d = self._udesc(gen.iter, ut)
                if d:
                    return d
            return None
        if isinstance(e, ast.IfExp):
            return self._udesc(e.body, ut) or self._udesc(e.orelse, ut)
        if isinstance(e, (ast.Tuple, ast.List)):
            for el in e.elts:
                d = self._udesc(el, ut)
                if d:
                    return d
            return None
        if isinstance(e, ast.Dict):
            # a dict literal payload: unordered order leaks through its
            # VALUES (and ** spreads, keys=None); dict insertion order
            # itself is stable
            for k, v in zip(e.keys, e.values):
                d = self._udesc(v, ut)
                if d:
                    return d
                if k is not None:
                    d = self._udesc(k, ut)
                    if d:
                        return d
            return None
        if isinstance(e, ast.Starred):
            return self._udesc(e.value, ut)
        return None

    # -- strict scope walk ----------------------------------------------------

    _SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef, ast.Lambda)

    def _scope_walk(self, scope: ast.AST) -> Iterator[ast.AST]:
        """Every node of ``scope``'s own body, never entering a nested
        def/class/lambda — including ones that sit directly in the
        body (which ``FileContext.walk_scope`` descends into)."""
        body = scope.body if hasattr(scope, "body") else []
        stack = [c for c in body
                 if not isinstance(c, self._SCOPE_BARRIERS)]
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if not isinstance(child, self._SCOPE_BARRIERS):
                    stack.append(child)

    # -- declaration plumbing -------------------------------------------------

    def _stmt_of(self, node: ast.AST, scope: ast.AST) -> ast.AST:
        cur, last = node, node
        while cur is not None and cur is not scope:
            last = cur
            cur = self.ctx.parent(cur)
        return last

    def _declared(self, node: ast.AST, scope: ast.AST) -> Optional[int]:
        """The entropy-declaration line covering this site, if any:
        the site's own line, its enclosing statement's first line, or
        the scope's def line (or the line just above it/its first
        decorator)."""
        ann = self.ctx.entropy_annotations
        cand = {getattr(node, "lineno", 0)}
        stmt = self._stmt_of(node, scope)
        cand.add(getattr(stmt, "lineno", 0))
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cand.add(scope.lineno)
            cand.add(scope.lineno - 1)
            if scope.decorator_list:
                cand.add(scope.decorator_list[0].lineno - 1)
        for ln in sorted(cand):
            if ln in ann:
                return ln
        return None

    # -- scope passes ---------------------------------------------------------

    def _targets(self, tgt: ast.AST) -> Iterator[str]:
        if isinstance(tgt, ast.Name):
            yield tgt.id
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                yield from self._targets(el)
        elif isinstance(tgt, ast.Starred):
            yield from self._targets(tgt.value)

    def _taint_pass(
        self,
        scope: ast.AST,
        et: Dict[str, Tuple[str, str]],
        ut: Dict[str, str],
        dt: Set[str],
        module_scope: bool,
    ) -> None:
        ann = self.ctx.entropy_annotations
        stmts = [
            n for n in self._scope_walk(scope)
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                              ast.For))
        ]
        stmts.sort(key=lambda n: (n.lineno, n.col_offset))
        for _ in range(2):
            for st in stmts:
                if isinstance(st, ast.For):
                    if self._udesc(st.iter, ut) is None and \
                            self._edesc(st.iter, et) is None:
                        for name in self._targets(st.target):
                            et.pop(name, None)
                            ut.pop(name, None)
                    continue
                if isinstance(st, ast.AugAssign):
                    if isinstance(st.target, ast.Name):
                        d = self._edesc(st.value, et)
                        if d:
                            et[st.target.id] = d
                    continue
                value = st.value
                if value is None:
                    continue
                targets = st.targets if isinstance(st, ast.Assign) \
                    else [st.target]
                declared = st.lineno in ann
                ed = self._edesc(value, et)
                ud = self._udesc(value, ut)
                is_digest = isinstance(value, ast.Call) and \
                    call_name(value) in DIGEST_CALLS
                for name in (n for t in targets
                             for n in self._targets(t)):
                    if ed and declared:
                        # a declared mint: the name is clean downstream
                        self.consumed.add(st.lineno)
                        et.pop(name, None)
                    elif ed:
                        et[name] = ed
                    else:
                        et.pop(name, None)
                    if ud:
                        ut[name] = ud
                    else:
                        ut.pop(name, None)
                    if is_digest:
                        dt.add(name)

    def _site_pass(
        self,
        scope: ast.AST,
        et: Dict[str, Tuple[str, str]],
        ut: Dict[str, str],
        dt: Set[str],
    ) -> None:
        ctx = self.ctx
        nodes = list(self._scope_walk(scope))
        sink_scope = any(
            (isinstance(n, ast.Name) and n.id in _SINK_SCOPE_NAMES)
            or (isinstance(n, ast.Attribute)
                and n.attr in _SINK_SCOPE_NAMES)
            for n in nodes
        )

        def flag(rule_sites: List[Site], node: ast.AST, msg: str,
                 declarable: bool = False) -> None:
            key = (getattr(node, "lineno", 0), id(rule_sites))
            if key in self._seen:
                return
            if declarable:
                ln = self._declared(node, scope)
                if ln is not None:
                    self.consumed.add(ln)
                    return
            self._seen.add(key)
            rule_sites.append((node, msg))

        for node in nodes:
            if isinstance(node, ast.Call):
                name = call_name(node)
                args = list(node.args) + [kw.value for kw in node.keywords]
                is_sink = (
                    name in SERIALIZE_SINKS or name in WIRE_SINKS
                    or name in DIGEST_CALLS or _is_json_dump(node)
                    or (name == "update"
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in dt)
                )
                if is_sink:
                    sink = name if name != "update" else "digest.update"
                    for a in args:
                        ud = self._udesc(a, ut)
                        if ud:
                            flag(self.pl015, a, (
                                f"unordered {ud} reaches the "
                                f"{sink}(...) sink — artifact bytes "
                                "inherit hash/filesystem order; apply "
                                "sorted() before serializing"
                            ))
                        ed = self._edesc(a, et)
                        if ed:
                            flag(self.pl016, a, (
                                f"{ed[1]} flows into {sink}(...) — "
                                "artifact bytes inherit ambient "
                                "entropy; derive the value from "
                                "content or declare it with "
                                "'# photon: entropy(<reason>)'"
                            ), declarable=True)
                if name in _SEED_SINKS:
                    for a in args:
                        ed = self._edesc(a, et)
                        if ed:
                            flag(self.pl016, a, (
                                f"{ed[1]} seeds {name}(...) — "
                                "downstream draws depend on ambient "
                                "state; seed from stable content "
                                "(e.g. zlib.crc32/blake2b of the key) "
                                "or declare it"
                            ), declarable=True)
                if name in _CACHE_KEY_METHODS and node.args:
                    ed = self._edesc(node.args[0], et)
                    if ed and ed[0] != "hash":
                        flag(self.pl016, node, (
                            f"{ed[1]} used as a cache/map key via "
                            f".{name}(...) — entries can never be "
                            "re-keyed across runs; key by content or "
                            "declare the identity-keying"
                        ), declarable=True)
                # PL017: order-dependent float accumulation
                is_sum = (
                    (isinstance(node.func, ast.Name)
                     and node.func.id in ("sum", "fsum"))
                    or _dotted(node) == "math.fsum"
                    or (isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("sum", "fsum")
                        and attr_root(node.func) is not None
                        and (ctx.is_numpy_module(attr_root(node.func))
                             or attr_root(node.func).id
                             in ("np", "numpy", "math")))
                )
                if is_sum and node.args:
                    ud = self._udesc(node.args[0], ut)
                    if ud:
                        flag(self.pl017, node, (
                            f"{name}() over unordered {ud} — float "
                            "accumulation order follows hash order, "
                            "so the result is not bitwise stable; "
                            "iterate sorted()"
                        ))
            elif isinstance(node, ast.For):
                ud = self._udesc(node.iter, ut)
                if ud and sink_scope:
                    flag(self.pl015, node, (
                        f"iterating unordered {ud} in a scope that "
                        "writes artifacts/digests — emit in sorted() "
                        "order so the bytes are reproducible"
                    ))
            elif isinstance(node, ast.Subscript):
                ed = self._edesc(node.slice, et)
                if ed and ed[0] != "hash":
                    flag(self.pl016, node, (
                        f"{ed[1]} used as a subscript cache key — "
                        "entries can never be re-keyed across runs; "
                        "key by content or declare the "
                        "identity-keying"
                    ), declarable=True)
            elif isinstance(node, ast.Return):
                ed = self._edesc(node.value, et)
                if ed:
                    flag(self.pl016, node, (
                        f"{ed[1]} in a return payload — callers "
                        "serialize this; declare the entropy at its "
                        "source with '# photon: entropy(<reason>)' "
                        "or derive it from content"
                    ), declarable=True)

    def _build(self) -> None:
        ctx = self.ctx
        # module scope first: declared module mints clear their names
        et: Dict[str, Tuple[str, str]] = {}
        ut: Dict[str, str] = {}
        dt: Set[str] = set()
        self._taint_pass(ctx.tree, et, ut, dt, module_scope=True)
        self._module_et, self._module_ut = dict(et), dict(ut)
        self._site_pass(ctx.tree, et, ut, dt)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fet = dict(self._module_et)
                fut = dict(self._module_ut)
                # parameters shadow module taints
                a = node.args
                for p in (list(a.posonlyargs) + list(a.args)
                          + list(a.kwonlyargs)):
                    fet.pop(p.arg, None)
                    fut.pop(p.arg, None)
                fdt: Set[str] = set()
                self._taint_pass(node, fet, fut, fdt, module_scope=False)
                self._site_pass(node, fet, fut, fdt)
        # enforced-claim audit: reasonless or unconsumed declarations
        for line, reason in sorted(ctx.entropy_annotations.items()):
            if not reason.strip():
                self.stale.append((line, (
                    "entropy declaration without a reason — the "
                    "grammar is '# photon: entropy(<why this site "
                    "must be nondeterministic>)'"
                )))
            elif line not in self.consumed:
                self.stale.append((line, (
                    "stale entropy declaration — no ambient entropy "
                    "reaches an artifact from this line; delete the "
                    "declaration so the contract stays trustworthy"
                )))

    def declarations(self) -> List[dict]:
        out = []
        for line, reason in sorted(self.ctx.entropy_annotations.items()):
            out.append({
                "file": self.ctx.path,
                "line": line,
                "reason": reason,
                "status": "active" if line in self.consumed else "stale",
            })
        return out


def file_model(ctx: FileContext) -> DeterminismFileModel:
    cached = getattr(ctx, "_det_model", None)
    if cached is None:
        cached = DeterminismFileModel(ctx)
        ctx._det_model = cached
    return cached


# -- wire-contract inventory (PL018) ------------------------------------------

@dataclass
class WireMessage:
    name: str
    value: int
    node: ast.AST
    encoders: List[str] = field(default_factory=list)
    decoded: bool = False
    dispatch: List[str] = field(default_factory=list)
    in_corpus: Optional[bool] = None  # None: corpus not checkable

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "value": self.value,
            "encoders": sorted(self.encoders),
            "decoded": self.decoded,
            "dispatch": sorted(self.dispatch),
            "in_corpus": self.in_corpus,
        }


@dataclass
class WireContract:
    path: str
    messages: List[WireMessage]
    error_kinds: Dict[str, bool]  # kind -> mapped in frontend
    corpus_path: Optional[str]
    corpus_checked: bool
    corpus_node: Optional[ast.AST] = None

    def to_dict(self) -> dict:
        return {
            "wire_module": self.path,
            "messages": [m.to_dict() for m in self.messages],
            "error_kinds": dict(sorted(self.error_kinds.items())),
            "corpus": self.corpus_path,
            "corpus_checked": self.corpus_checked,
        }


_CORPUS_NAME = "WIRE_FUZZ_CORPUS"


def _msg_names(tree: ast.AST) -> Iterator[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and node.id.startswith("MSG_"):
            yield node.id
        elif isinstance(node, ast.Attribute) and \
                node.attr.startswith("MSG_"):
            yield node.attr


def build_wire_contract(pkg: PackageContext) -> Optional[WireContract]:
    wire_ctx = None
    for path in sorted(pkg.contexts):
        if path.endswith("serving/wire.py"):
            wire_ctx = pkg.contexts[path]
            break
    if wire_ctx is None:
        return None
    messages: Dict[str, WireMessage] = {}
    for node in wire_ctx.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                node.targets[0].id.startswith("MSG_") and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, int):
            name = node.targets[0].id
            messages[name] = WireMessage(
                name=name, value=node.value.value, node=node,
            )
    error_kinds: Dict[str, bool] = {}
    for node in ast.walk(wire_ctx.tree):
        if isinstance(node, ast.FunctionDef):
            # encoder leg: append_frame(buf, MSG_X, ...) inside a def
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        call_name(sub) == "append_frame" and \
                        len(sub.args) >= 2 and \
                        isinstance(sub.args[1], ast.Name):
                    msg = messages.get(sub.args[1].id)
                    if msg is not None and node.name not in msg.encoders:
                        msg.encoders.append(node.name)
            # decoder leg: MSG_X referenced inside a decode* function
            if "decode" in node.name:
                for ref in _msg_names(node):
                    if ref in messages:
                        messages[ref].decoded = True
        elif isinstance(node, ast.Call) and \
                call_name(node) == "WireError":
            for kw in node.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                    error_kinds.setdefault(str(kw.value.value), False)
        elif isinstance(node, ast.ClassDef) and node.name == "WireError":
            # default kind from __init__'s keyword default
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef,)) and \
                        sub.name == "__init__":
                    for p, d in zip(sub.args.kwonlyargs,
                                    sub.args.kw_defaults):
                        if p.arg == "kind" and \
                                isinstance(d, ast.Constant):
                            error_kinds.setdefault(str(d.value), False)
    # dispatch leg: MSG_* referenced by the frontend or the transport
    frontend_consts: Set[str] = set()
    for path in sorted(pkg.contexts):
        if path.endswith("serving/frontend.py") or \
                path.endswith("serving/routing.py"):
            short = path.rsplit("/", 1)[-1]
            for ref in _msg_names(pkg.contexts[path].tree):
                if ref in messages and \
                        short not in messages[ref].dispatch:
                    messages[ref].dispatch.append(short)
            if path.endswith("serving/frontend.py"):
                for node in ast.walk(pkg.contexts[path].tree):
                    if isinstance(node, ast.Constant) and \
                            isinstance(node.value, str):
                        frontend_consts.add(node.value)
    for kind in error_kinds:
        error_kinds[kind] = kind in frontend_consts
    # corpus leg: tests/test_wire.py's WIRE_FUZZ_CORPUS dict, resolved
    # relative to the analyzed wire module (only checkable when the
    # tests tree is reachable — fixture runs skip this leg)
    corpus_path = None
    corpus_checked = False
    corpus_node = None
    corpus_keys: Set[str] = set()
    if wire_ctx.path.endswith("photon_ml_tpu/serving/wire.py"):
        root = wire_ctx.path[: -len("photon_ml_tpu/serving/wire.py")]
        cand = os.path.join(root, "tests", "test_wire.py") if root \
            else os.path.join("tests", "test_wire.py")
        if os.path.exists(cand):
            corpus_path = cand.replace(os.sep, "/")
            try:
                with open(cand, "r", encoding="utf-8") as fh:
                    test_tree = ast.parse(fh.read())
            except (OSError, SyntaxError):
                test_tree = None
            if test_tree is not None:
                corpus_checked = True
                for node in ast.walk(test_tree):
                    if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == _CORPUS_NAME
                        for t in node.targets
                    ):
                        corpus_node = node
                        if isinstance(node.value, ast.Dict):
                            for k in node.value.keys:
                                for ref in _msg_names(k):
                                    corpus_keys.add(ref)
    if corpus_checked:
        for msg in messages.values():
            msg.in_corpus = msg.name in corpus_keys
    return WireContract(
        path=wire_ctx.path,
        messages=[messages[k] for k in sorted(messages)],
        error_kinds=error_kinds,
        corpus_path=corpus_path,
        corpus_checked=corpus_checked,
        corpus_node=corpus_node,
    )


def wire_contract(pkg: PackageContext) -> Optional[WireContract]:
    cached = getattr(pkg, "_det_wire", False)
    if cached is False:
        cached = build_wire_contract(pkg)
        pkg._det_wire = cached
    return cached


def entropy_inventory(pkg: PackageContext) -> List[dict]:
    """The --json entropy-declaration table: every declaration in the
    run, with whether the analyzer saw it consumed."""
    out: List[dict] = []
    for path in sorted(pkg.contexts):
        out.extend(file_model(pkg.contexts[path]).declarations())
    return out


__all__ = [
    "DIGEST_CALLS",
    "DeterminismFileModel",
    "ENTROPY_CALLS",
    "SERIALIZE_SINKS",
    "UNORDERED_CALLS",
    "WIRE_SINKS",
    "WireContract",
    "WireMessage",
    "build_wire_contract",
    "entropy_inventory",
    "file_model",
    "wire_contract",
]
