"""Analyzer core: file contexts, the rule registry, suppressions.

Everything here is stdlib-only (``ast`` + ``tokenize``) — the analyzer
must run in any environment the package runs in, including the minimal
CI container, without importing jax or the package under analysis.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import (
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

# -- violations and the rule registry ----------------------------------------


@dataclass
class Violation:
    rule: str  # "PL001"
    slug: str  # "hidden-host-sync"
    path: str  # normalized (posix, relative when possible)
    line: int
    col: int
    message: str
    snippet: str = ""  # stripped source line, the baseline matching key
    # The allow-site audit emits violations AT suppression comments; those
    # must not be swallowed by the very comment they audit.
    suppressable: bool = True

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "slug": self.slug,
            "file": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }


@dataclass
class Rule:
    id: str
    slug: str
    doc: str
    check: Callable[["FileContext"], Iterable[Violation]]


@dataclass
class PackageRule:
    """A rule that needs the WHOLE analyzed file set at once — the
    concurrency pass (PL008-PL010) builds per-class guard maps and a
    cross-module lock-acquisition graph, and the SPMD pass (PL011-PL014)
    builds the package-wide mesh-entry-point inventory; neither exists
    at single-file granularity. ``group`` names the pass so the CLI can
    opt out of one without the other (--no-concurrency / --no-spmd)."""

    id: str
    slug: str
    doc: str
    check: Callable[["PackageContext"], Iterable[Violation]]
    group: str = "concurrency"


RULES: Dict[str, Rule] = {}
PACKAGE_RULES: Dict[str, PackageRule] = {}


def register(rule: Rule) -> Rule:
    RULES[rule.id] = rule
    return rule


def register_package(rule: PackageRule) -> PackageRule:
    PACKAGE_RULES[rule.id] = rule
    return rule


def all_rules():
    """Every registered rule (file-scoped + package-scoped), by id."""
    _load_rules()
    out: Dict[str, object] = {}
    out.update(RULES)
    out.update(PACKAGE_RULES)
    return out


def _load_rules() -> None:
    """Import the rule modules (each registers itself on import)."""
    if not RULES:
        import photon_ml_tpu.lint.rules  # noqa: F401


# -- suppression comments ----------------------------------------------------

_ALLOW_RE = re.compile(r"#\s*photon:\s*allow\(\s*([A-Za-z0-9_\-,\s]*?)\s*\)")

# The guard-discipline declaration (concurrency pass, PL008):
#   self._flag = False  # photon: guarded-by(_lock)
# declares that every access to ``self._flag`` outside __init__ must
# hold ``self._lock``. The special token ``atomic`` declares a
# single-writer atomic-publish discipline instead: plain reference
# assignments only (no ``+=``, no in-place mutation), reads allowed
# anywhere. Annotations are DECLARATIONS the analyzer enforces, not
# suppressions — a violated declaration is a violation.
_GUARDED_RE = re.compile(
    r"#\s*photon:\s*guarded-by\(\s*([A-Za-z_][A-Za-z0-9_]*)\s*\)"
)

# The sharding-contract declaration (SPMD pass, PL011/PL012):
#   # photon: sharding(axes=[entity], in=[entity,r], out=[r])
# on (or directly above) the def line of a jit/shard_map mesh entry
# point declares which mesh axes it maps over and the per-argument
# partition specs; the bare token ``export`` declares an export/
# checkpoint scope in which host-materializing a sharded bank (PL012)
# is legitimate. Like guarded-by, these are DECLARATIONS the analyzer
# cross-checks against the code — never suppressions.
_SHARDING_RE = re.compile(r"#\s*photon:\s*sharding\(([^)]*)\)")

# The entropy declaration (determinism pass, PL016):
#   def snapshot(self):  # photon: entropy(live wall-clock timestamp)
#   _PROC_NONCE = ...    # photon: entropy(per-boot trace-id nonce)
# on the def line of a function (or on a module-level statement)
# declares that ambient entropy — wall clocks, pids, uuids, hash
# randomization, object identity — reaching an artifact, digest, cache
# key or wire payload in that scope is INTENTIONAL, and names why.
# Like guarded-by and sharding(), this is an enforced claim, never a
# suppression: a declaration whose scope mints no entropy that reaches
# a sink is itself a violation (stale declaration).
_ENTROPY_RE = re.compile(r"#\s*photon:\s*entropy\(([^)]*)\)")


@dataclass
class AllowSite:
    line: int  # line the comment is ON
    applies_to: int  # line the suppression covers
    rules: Set[str]  # tokens as written (ids and/or slugs)
    path: str = ""
    # set by the PL001 audit for hidden-host-sync sites: does the
    # enclosing scope feed the counted seam / serial switch?
    seam_ok: Optional[bool] = None

    def to_dict(self) -> dict:
        d = {
            "file": self.path,
            "line": self.line,
            "applies_to": self.applies_to,
            "rules": sorted(self.rules),
        }
        if self.seam_ok is not None:
            d["seam_ok"] = self.seam_ok
        return d


# -- per-file analysis context -----------------------------------------------

# module roots whose values are device arrays (taint sources)
_JAX_ROOT_MODULES = ("jax",)
_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "sharding", "weak_type", "aval",
}
# jax.* calls returning host metadata, not device arrays
_JAX_METADATA_FUNCS = {
    "devices", "local_devices", "device_count", "local_device_count",
    "process_index", "process_count", "default_backend", "make_mesh",
}


class FileContext:
    """Parsed source + the cross-rule queries every check needs: parent
    links, enclosing scopes, import aliases, suppressions, and a local
    (per-scope) jax-value taint."""

    def __init__(self, path: str, source: str):  # photon: entropy(id-keyed AST parent links; in-memory analysis index, never serialized)
        self.path = norm_path(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
        self.allow_sites: List[AllowSite] = []
        self._suppressed: Dict[int, Set[str]] = {}
        # line -> guard token from '# photon: guarded-by(<lock>|atomic)'
        self.guard_annotations: Dict[int, str] = {}
        # line -> raw arg string from '# photon: sharding(<args>)'
        self.sharding_annotations: Dict[int, str] = {}
        # line -> reason string from '# photon: entropy(<reason>)'
        self.entropy_annotations: Dict[int, str] = {}
        self._scan_comments()
        # import aliases
        self.jax_modules: Set[str] = set()  # names aliasing jax[. ...]
        self.numpy_modules: Set[str] = set()  # names aliasing numpy
        self.jax_names: Set[str] = set()  # from jax import <name>
        self.overlap_modules: Set[str] = set()  # names aliasing ...overlap
        self.overlap_names: Set[str] = set()  # from ...overlap import <n>
        self._scan_imports()
        self._taint_cache: Dict[int, Set[str]] = {}

    # -- structure ----------------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:  # photon: entropy(id-keyed AST parent lookup; in-memory only)
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def scope_of(self, node: ast.AST) -> ast.AST:
        """Innermost function scope, or the module itself."""
        return self.enclosing_function(node) or self.tree

    def path_parts(self) -> Tuple[str, ...]:
        return tuple(p for p in self.path.split("/") if p)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def violation(
        self, rule: "Rule", node: ast.AST, message: str, **kw
    ) -> Violation:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Violation(
            rule=rule.id, slug=rule.slug, path=self.path, line=line,
            col=col, message=message, snippet=self.snippet(line), **kw,
        )

    # -- scope queries -------------------------------------------------------

    def scope_calls(self, scope: ast.AST, names: Set[str]) -> bool:
        """Does ``scope`` directly call (or reference) any of ``names``
        (bare name or attribute), not counting nested function bodies?"""
        for node in self.walk_scope(scope):
            if isinstance(node, ast.Name) and node.id in names:
                return True
            if isinstance(node, ast.Attribute) and node.attr in names:
                return True
        return False

    def walk_scope(self, scope: ast.AST) -> Iterator[ast.AST]:
        """Walk a function/module body without descending into nested
        function/class definitions."""
        body = scope.body if hasattr(scope, "body") else []
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                     ast.Lambda),
                ):
                    continue
                stack.append(child)

    # -- imports -------------------------------------------------------------

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    top = alias.name.split(".")[0]
                    name = alias.asname or alias.name.split(".")[0]
                    if top in _JAX_ROOT_MODULES:
                        self.jax_modules.add(alias.asname or top)
                    if top == "numpy":
                        self.numpy_modules.add(alias.asname or top)
                    if alias.name.endswith("parallel.overlap"):
                        self.overlap_modules.add(name)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    name = alias.asname or alias.name
                    if mod.split(".")[0] in _JAX_ROOT_MODULES:
                        if alias.name == "numpy":
                            self.jax_modules.add(name)
                        else:
                            self.jax_names.add(name)
                    if mod == "numpy":
                        self.numpy_modules.add(name)  # from numpy import *
                    if mod.endswith("parallel.overlap"):
                        self.overlap_names.add(name)
                    if mod.endswith("parallel") and alias.name == "overlap":
                        self.overlap_modules.add(name)

    def is_jax_module(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.jax_modules

    def is_numpy_module(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.numpy_modules

    def is_overlap_module(self, node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Name) and node.id in self.overlap_modules
        )

    # -- suppressions --------------------------------------------------------

    def _scan_comments(self) -> None:
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(self.source).readline)
            )
        except tokenize.TokenError:
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            g = _GUARDED_RE.search(tok.string)
            if g:
                self.guard_annotations[tok.start[0]] = g.group(1)
            sh = _SHARDING_RE.search(tok.string)
            if sh:
                self.sharding_annotations[tok.start[0]] = sh.group(1)
            # anchored: the comment must BE the declaration — prose that
            # merely mentions the grammar is not a claim
            en = _ENTROPY_RE.match(tok.string)
            if en:
                self.entropy_annotations[tok.start[0]] = en.group(1).strip()
            m = _ALLOW_RE.search(tok.string)
            if not m:
                continue
            rules = {
                r.strip() for r in m.group(1).split(",") if r.strip()
            }
            line = tok.start[0]
            text_before = self.lines[line - 1][: tok.start[1]].strip()
            applies_to = line if text_before else self._next_code_line(line)
            site = AllowSite(
                line=line, applies_to=applies_to, rules=rules,
                path=self.path,
            )
            self.allow_sites.append(site)
            self._suppressed.setdefault(applies_to, set()).update(rules)

    def _next_code_line(self, comment_line: int) -> int:
        for ln in range(comment_line + 1, len(self.lines) + 1):
            text = self.lines[ln - 1].strip()
            if text and not text.startswith("#"):
                return ln
        return comment_line

    def suppressed(self, violation: Violation) -> bool:
        if not violation.suppressable:
            return False
        toks = self._suppressed.get(violation.line)
        if not toks:
            return False
        return bool(
            toks & {violation.rule, violation.slug, "*", "all"}
        )

    # -- local jax-value taint ----------------------------------------------

    def jax_taint(  # photon: entropy(id-keyed per-scope taint memo; in-memory only)
        self, scope: ast.AST, include_params: bool = False,
        exclude_params: Sequence[str] = (),
    ) -> Set[str]:
        """Names in ``scope`` that provably hold jax values: assigned from
        ``jax.*``/``jnp.*`` expressions (or derived from such names).
        With ``include_params`` the scope's own parameters seed the set —
        the right semantics inside a jitted body, where every non-static
        argument is a tracer."""
        key = (id(scope), include_params, tuple(exclude_params))
        cached = self._taint_cache.get(key)
        if cached is not None:
            return cached
        tainted: Set[str] = set()
        if include_params and hasattr(scope, "args"):
            a = scope.args
            params = [
                p.arg
                for p in (
                    list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                )
            ]
            if a.vararg:
                params.append(a.vararg.arg)
            if a.kwarg:
                params.append(a.kwarg.arg)
            tainted.update(
                p for p in params
                if p not in exclude_params and p != "self"
            )
        # fixpoint over straight-line assignments (monotone, so a couple
        # of passes converge; bound defensively)
        for _ in range(10):
            before = len(tainted)
            for node in self.walk_scope(scope):
                if isinstance(node, ast.Assign):
                    if self.expr_tainted(node.value, tainted):
                        for tgt in node.targets:
                            self._taint_target(tgt, tainted)
                elif isinstance(node, ast.AnnAssign) and node.value:
                    if self.expr_tainted(node.value, tainted):
                        self._taint_target(node.target, tainted)
                elif isinstance(node, ast.AugAssign):
                    if self.expr_tainted(node.value, tainted):
                        self._taint_target(node.target, tainted)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    if self.expr_tainted(node.iter, tainted):
                        self._taint_target(node.target, tainted)
            if len(tainted) == before:
                break
        self._taint_cache[key] = tainted
        return tainted

    def _taint_target(self, target: ast.AST, tainted: Set[str]) -> None:
        if isinstance(target, ast.Name):
            tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._taint_target(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value, tainted)

    def expr_tainted(self, expr: ast.AST, tainted: Set[str]) -> bool:
        """Does this expression (conservatively, low-false-positive)
        evaluate to a jax value?"""
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            root = _attr_root(expr.func)
            if root is not None and root.id in self.jax_modules:
                tail = (
                    expr.func.attr
                    if isinstance(expr.func, ast.Attribute)
                    else ""
                )
                return tail not in _JAX_METADATA_FUNCS
            if isinstance(expr.func, ast.Name) and expr.func.id in tainted:
                return True  # calling a jitted/taint-derived callable
            if isinstance(expr.func, ast.Attribute):
                # method on a tainted value: x.sum(), x.astype(...)
                return self.expr_tainted(expr.func.value, tainted)
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            return self.expr_tainted(expr.value, tainted)
        if isinstance(expr, ast.Subscript):
            return self.expr_tainted(expr.value, tainted)
        if isinstance(expr, ast.BinOp):
            return self.expr_tainted(
                expr.left, tainted
            ) or self.expr_tainted(expr.right, tainted)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_tainted(expr.operand, tainted)
        if isinstance(expr, ast.Compare):
            return self.expr_tainted(expr.left, tainted) or any(
                self.expr_tainted(c, tainted) for c in expr.comparators
            )
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_tainted(v, tainted) for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return self.expr_tainted(
                expr.body, tainted
            ) or self.expr_tainted(expr.orelse, tainted)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e, tainted) for e in expr.elts)
        if isinstance(expr, ast.Starred):
            return self.expr_tainted(expr.value, tainted)
        return False


def _attr_root(node: ast.AST) -> Optional[ast.Name]:
    """Root Name of a dotted chain: ``jax.numpy.asarray`` -> Name(jax)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node if isinstance(node, ast.Name) else None


def attr_root(node: ast.AST) -> Optional[ast.Name]:
    return _attr_root(node)


def call_name(node: ast.Call) -> str:
    """Trailing callee name: ``overlap.submit_io(...)`` -> ``submit_io``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


# -- whole-package concurrency model (PL008-PL010) ----------------------------
#
# The second analysis pass. Per class: a GUARD MAP — which ``self._*``
# attributes are written under ``with self._lock``-style context
# managers vs. touched bare, seeded by ``# photon: guarded-by(<lock>)``
# annotations. Per package: a LOCK-ACQUISITION-ORDER GRAPH (nested
# ``with`` blocks + one-hop calls into lock-taking package methods) and
# a THREAD-ESCAPE view (closures handed to ``Thread(target=...)`` /
# ``submit_io``). Everything stays stdlib-``ast``: no imports of the
# package under analysis, so the pass runs in the minimal CI container.

_LOCK_FACTORIES = {"Lock", "RLock"}
_SAFE_FACTORIES = {
    # primitives that are themselves synchronized (or synchronization):
    # calling their methods from several threads is their whole point
    "Event", "Semaphore", "BoundedSemaphore", "Barrier",
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
}
_THREAD_ENTRY_CALLS = {"Thread", "submit_io", "start_new_thread"}
# method names too generic to resolve one-hop by name (dict/list/set and
# primitive protocol collisions would wire the lock graph to noise)
_ONE_HOP_STOPLIST = {
    "get", "pop", "append", "appendleft", "extend", "items", "keys",
    "values", "update", "clear", "copy", "setdefault", "remove",
    "discard", "add", "put", "put_nowait", "get_nowait", "join", "set",
    "is_set", "wait", "notify", "notify_all", "acquire", "release",
    "result", "done", "cancel", "close", "flush", "write", "read",
    "sort", "index", "count", "split", "strip", "startswith", "endswith",
}
# callback-shaped attribute names: user code invoked through these while
# a lock is held runs arbitrary code inside the critical section
_CALLBACK_NAME_RE = re.compile(
    r"^(on_[a-z0-9_]+|[a-z0-9_]*callback[a-z0-9_]*|[a-z0-9_]*hook[a-z0-9_]*"
    r"|[a-z0-9_]+_handler|[a-z0-9_]+_provider)$"
)
# calls that can block for real time: parking on these inside a
# critical section extends everyone's wait, not just the caller's
_BLOCKING_TAILS = {"sendall", "recv", "accept", "connect", "sleep"}

ATOMIC = "atomic"


@dataclass
class AttrAccess:
    """One ``self.<attr>`` touch inside a method."""

    attr: str
    node: ast.AST
    method: str
    kind: str  # "read" | "write" | "augwrite" | "mutate"
    locks_held: frozenset  # class-local base-lock attr names
    # (lock, id-of-acquiring-With) pairs: two accesses sharing a lock
    # NAME but not an acquisition SITE saw the lock released between
    # them — the check-then-act gap PL010 hunts
    lock_acqs: frozenset
    in_init: bool

    @property
    def is_write(self) -> bool:
        return self.kind != "read"


@dataclass
class LockEdge:
    """held -> acquired, with the site that proves it."""

    src: tuple
    dst: tuple
    path: str
    line: int
    via: str  # "nested-with" | "call:<name>"


@dataclass
class ClassModel:
    name: str
    ctx: "FileContext"
    node: ast.ClassDef
    lock_attrs: Set[str] = field(default_factory=set)
    # condition attr -> backing lock attr (itself when constructed bare)
    cond_alias: Dict[str, str] = field(default_factory=dict)
    # base locks that back at least one Condition: their critical
    # sections gate wait/notify wakeups (PL010's "hot" locks)
    cond_backed: Set[str] = field(default_factory=set)
    safe_attrs: Set[str] = field(default_factory=set)
    annotations: Dict[str, str] = field(default_factory=dict)
    accesses: Dict[str, List[AttrAccess]] = field(default_factory=dict)
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    thread_targets: Set[str] = field(default_factory=set)
    thread_reachable: Set[str] = field(default_factory=set)
    acquired_by_method: Dict[str, Set[str]] = field(default_factory=dict)
    # methods annotated '# photon: guarded-by(<lock>)' on their def
    # line: the body is analyzed AS IF the lock were held, and every
    # self-call site must provably hold it (the caller-holds-the-lock
    # helper convention, enforced not trusted)
    lock_expected: Dict[str, str] = field(default_factory=dict)

    _INIT_METHODS = ("__init__", "__post_init__", "__new__")

    @property
    def concurrent(self) -> bool:
        """Does this class participate in the thread plane at all?
        Owning a lock OR spawning a thread both count — a lock with no
        discipline is as suspicious as a thread with no lock."""
        return bool(self.lock_attrs or self.cond_alias or
                    self.thread_targets)

    def resolve_lock(self, attr: str) -> Optional[str]:
        """Lock identity an attr acquisition maps to: a Condition
        constructed over ``self._lock`` guards the SAME critical
        sections as the lock itself."""
        if attr in self.lock_attrs:
            return attr
        if attr in self.cond_alias:
            return self.cond_alias[attr]
        return None

    def lock_names(self) -> Set[str]:
        return set(self.lock_attrs) | set(self.cond_alias)

    def inferred_guard(self, attr: str) -> Optional[str]:
        """The lock this attr's locked writes agree on (None when no
        write outside __init__ ever holds a lock)."""
        counts: Dict[str, int] = {}
        for a in self.accesses.get(attr, ()):
            if a.in_init or not a.is_write:
                continue
            for lk in a.locks_held:
                counts[lk] = counts.get(lk, 0) + 1
        if not counts:
            return None
        return max(sorted(counts), key=lambda k: counts[k])

    def shared_attrs(self) -> Set[str]:
        """Attrs touched on BOTH sides of the thread boundary: by a
        method reachable from a ``Thread(target=self.<m>)`` entry and by
        a method that is not (the external-caller plane)."""
        if not self.thread_targets:
            return set()
        shared: Set[str] = set()
        for attr, accs in self.accesses.items():
            in_thread = any(
                a.method in self.thread_reachable for a in accs
                if not a.in_init
            )
            outside = any(
                a.method not in self.thread_reachable
                and not a.in_init
                for a in accs
            )
            if in_thread and outside:
                shared.add(attr)
        return shared


@dataclass
class ThreadEscape:
    """A closure handed to a thread entry point whose captured name is
    mutated bare on both sides of the spawn."""

    node: ast.AST
    path: str
    name: str  # captured variable
    target: str  # closure/function name (or "<lambda>")
    message: str


class _MethodScanner(ast.NodeVisitor):
    """Walks one method carrying the set of class-local locks held via
    enclosing ``with self.<lock>`` managers. Nested function bodies run
    at an unknown later time, so the held set RESETS inside them."""

    def __init__(self, model: ClassModel, method: str):
        self.m = model
        self.method = method
        self.held: Tuple[Tuple[str, int], ...] = ()  # (lock, with-id)
        self.in_init = method in ClassModel._INIT_METHODS
        self.acquired: Set[str] = set()
        # (node, held-lock-names) pairs for PL010's under-lock call audit
        self.calls_under_lock: List[Tuple[ast.Call, frozenset]] = []
        self.notifies: List[Tuple[ast.Call, str, frozenset]] = []
        # (node, callee, held-lock-names): self.<m>() call sites, for
        # enforcing lock-expected helper methods
        self.self_calls: List[Tuple[ast.Call, str, frozenset]] = []

    # -- helpers -------------------------------------------------------------

    def _self_attr(self, node: ast.AST) -> Optional[str]:
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        return None

    def _record(self, attr: str, node: ast.AST, kind: str) -> None:
        self.m.accesses.setdefault(attr, []).append(AttrAccess(
            attr=attr, node=node, method=self.method, kind=kind,
            locks_held=frozenset(lk for lk, _ in self.held),
            lock_acqs=frozenset(self.held), in_init=self.in_init,
        ))

    # -- traversal -----------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        got: List[Tuple[str, int]] = []
        for item in node.items:
            attr = self._self_attr(item.context_expr)
            lock = self.m.resolve_lock(attr) if attr else None
            if lock is not None:
                got.append((lock, id(node)))
                self.acquired.add(lock)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held = self.held + tuple(got)
        for stmt in node.body:
            self.visit(stmt)
        if got:
            self.held = self.held[: len(self.held) - len(got)]

    visit_AsyncWith = visit_With

    def _visit_nested(self, node) -> None:
        held, self.held = self.held, ()
        self.generic_visit(node)
        self.held = held

    def visit_FunctionDef(self, node):  # nested def
        self._visit_nested(node)

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._self_attr(node)
        if attr is not None:
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                self._record(attr, node, "write")
            elif isinstance(node.ctx, ast.Load):
                # self.attr[k] = v / self.attr.x = v / self.attr += v:
                # a LOAD that feeds an in-place mutation of the object
                parent = self.m.ctx.parent(node)
                kind = "read"
                if isinstance(parent, ast.AugAssign) and parent.target is node:
                    kind = "augwrite"
                elif (
                    isinstance(parent, ast.Subscript)
                    and parent.value is node
                    and isinstance(parent.ctx, (ast.Store, ast.Del))
                ):
                    kind = "mutate"
                elif (
                    isinstance(parent, ast.Attribute)
                    and parent.value is node
                    and isinstance(parent.ctx, (ast.Store, ast.Del))
                ):
                    kind = "mutate"
                self._record(attr, node, kind)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self._self_attr(node.target)
        if attr is not None:
            self._record(attr, node.target, "augwrite")
            self.visit(node.value)
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            self.calls_under_lock.append(
                (node, frozenset(lk for lk, _ in self.held))
            )
        if (
            isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self"
        ):
            self.self_calls.append((
                node, node.func.attr,
                frozenset(lk for lk, _ in self.held),
            ))
        name = call_name(node)
        if name in ("notify", "notify_all") and isinstance(
            node.func, ast.Attribute
        ):
            cond = self._self_attr(node.func.value)
            if cond is not None and cond in self.m.cond_alias:
                self.notifies.append(
                    (node, cond, frozenset(lk for lk, _ in self.held))
                )
        if name in _THREAD_ENTRY_CALLS:
            tgt = None
            for kw in node.keywords:
                if kw.arg == "target":
                    tgt = kw.value
            if tgt is None and name != "Thread" and node.args:
                tgt = node.args[0]
            attr = self._self_attr(tgt) if tgt is not None else None
            if attr is not None:
                self.m.thread_targets.add(attr)
        self.generic_visit(node)


def _build_class_model(ctx: "FileContext", node: ast.ClassDef) -> ClassModel:
    model = ClassModel(name=node.name, ctx=ctx, node=node)
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            model.methods[stmt.name] = stmt
    # pass 1: lock / condition / safe-type attrs + guard annotations
    for meth in model.methods.values():
        for sub in ast.walk(meth):
            if not isinstance(sub, ast.Assign):
                continue
            for tgt in sub.targets:
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                ann = ctx.guard_annotations.get(sub.lineno)
                if ann is not None:
                    model.annotations.setdefault(tgt.attr, ann)
                if not isinstance(sub.value, ast.Call):
                    continue
                tail = call_name(sub.value)
                if tail in _LOCK_FACTORIES:
                    model.lock_attrs.add(tgt.attr)
                elif tail == "Condition":
                    backing = tgt.attr  # bare Condition() owns its lock
                    if sub.value.args:
                        a0 = sub.value.args[0]
                        if (
                            isinstance(a0, ast.Attribute)
                            and isinstance(a0.value, ast.Name)
                            and a0.value.id == "self"
                        ):
                            backing = a0.attr
                    model.cond_alias[tgt.attr] = backing
                elif tail in _SAFE_FACTORIES:
                    model.safe_attrs.add(tgt.attr)
    # a bare Condition IS its own lock identity
    for cattr, backing in model.cond_alias.items():
        if backing == cattr:
            model.lock_attrs.add(cattr)
        model.cond_backed.add(backing)
    # pass 2: accesses, held-lock context, thread targets, acquisitions
    model._scanners = {}
    for name, meth in model.methods.items():
        sc = _MethodScanner(model, name)
        expect = ctx.guard_annotations.get(meth.lineno)
        if expect is not None:
            lk = model.resolve_lock(expect)
            if lk is not None:
                # caller-holds-the-lock helper: body analyzed with the
                # lock held; call sites are checked by PL008
                model.lock_expected[name] = lk
                sc.held = ((lk, -meth.lineno),)
        for stmt in meth.body:
            sc.visit(stmt)
        model.acquired_by_method[name] = sc.acquired
        model._scanners[name] = sc
    # pass 3: thread reachability (closure over self-method calls)
    reach = set(model.thread_targets)
    frontier = list(reach)
    while frontier:
        m = frontier.pop()
        meth = model.methods.get(m)
        if meth is None:
            continue
        for sub in ast.walk(meth):
            if isinstance(sub, ast.Call):
                callee = sub.func
                if (
                    isinstance(callee, ast.Attribute)
                    and isinstance(callee.value, ast.Name)
                    and callee.value.id == "self"
                    and callee.attr in model.methods
                    and callee.attr not in reach
                ):
                    reach.add(callee.attr)
                    frontier.append(callee.attr)
    model.thread_reachable = reach
    return model


class PackageContext:
    """All FileContexts of one analyzer run + the lazily-built
    concurrency model (class guard maps, the cross-module lock graph,
    thread escapes). Package rules (PL008-PL010) check THIS."""

    def __init__(self, contexts: Sequence["FileContext"]):
        self.contexts: Dict[str, FileContext] = {
            ctx.path: ctx for ctx in contexts
        }
        self._classes: Optional[Dict[str, List[ClassModel]]] = None
        self._module_locks: Optional[Dict[str, Dict[str, tuple]]] = None
        self._edges: Optional[List[LockEdge]] = None
        self._escapes: Optional[List[ThreadEscape]] = None

    def ctx(self, path: str) -> Optional["FileContext"]:
        return self.contexts.get(path)

    # -- class models --------------------------------------------------------

    @property
    def classes(self) -> Dict[str, List[ClassModel]]:
        """path -> class models (module-level classes only)."""
        if self._classes is None:
            self._classes = {}
            for path, ctx in self.contexts.items():
                models = []
                for node in ctx.tree.body:
                    if isinstance(node, ast.ClassDef):
                        models.append(_build_class_model(ctx, node))
                self._classes[path] = models
        return self._classes

    def all_classes(self) -> Iterator[ClassModel]:
        for models in self.classes.values():
            yield from models

    # -- module-level locks --------------------------------------------------

    @property
    def module_locks(self) -> Dict[str, Dict[str, tuple]]:
        """path -> {global name: lock id} for module-scope Lock()s."""
        if self._module_locks is None:
            self._module_locks = {}
            for path, ctx in self.contexts.items():
                found: Dict[str, tuple] = {}
                for node in ctx.tree.body:
                    if not isinstance(node, ast.Assign):
                        continue
                    if not isinstance(node.value, ast.Call):
                        continue
                    if call_name(node.value) not in _LOCK_FACTORIES:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            found[tgt.id] = ("module", path, tgt.id)
                self._module_locks[path] = found
        return self._module_locks

    # -- the lock-acquisition-order graph ------------------------------------

    def _method_lock_index(self) -> Dict[str, List[tuple]]:
        """method name -> lock ids it acquires, across every package
        class (the one-hop call resolution; generic names stoplisted)."""
        index: Dict[str, List[tuple]] = {}
        for model in self.all_classes():
            for mname, acquired in model.acquired_by_method.items():
                if mname in _ONE_HOP_STOPLIST or mname.startswith("__"):
                    continue
                for lk in acquired:
                    index.setdefault(mname, []).append(
                        ("class", model.name, lk)
                    )
        return index

    @property
    def lock_edges(self) -> List[LockEdge]:
        if self._edges is not None:
            return self._edges
        edges: List[LockEdge] = []
        seen: Set[tuple] = set()
        index = self._method_lock_index()

        def add(src, dst, path, line, via):
            key = (src, dst, via.split(":")[0])
            if src == dst and via.startswith("call"):
                # name-resolved self-recursion is usually a different
                # object of the same class; only a syntactic nested
                # with on the same lock is a provable self-deadlock
                return
            if key in seen:
                return
            seen.add(key)
            edges.append(LockEdge(src, dst, path, line, via))

        for path, ctx in self.contexts.items():
            mlocks = self.module_locks.get(path, {})
            for model in self.classes[path]:
                for mname, sc in model._scanners.items():
                    self._edges_in_method(
                        model, mname, mlocks, index, add
                    )
            self._edges_in_module_funcs(ctx, mlocks, index, add)
        self._edges = edges
        return edges

    def _lock_id(self, model: Optional[ClassModel], mlocks, node):
        """Lock identity of a with-item context expr, or None."""
        if model is not None and isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                lk = model.resolve_lock(node.attr)
                if lk is not None:
                    return ("class", model.name, lk)
        if isinstance(node, ast.Name) and node.id in mlocks:
            return mlocks[node.id]
        return None

    def _walk_lock_scope(self, model, mlocks, index, add, body, path,
                         held):
        """Recursive with-nesting walk shared by methods and module
        functions: emits held->acquired and held->callee-lock edges."""
        for node in body:
            self._walk_lock_node(model, mlocks, index, add, node, path,
                                 held)

    def _walk_lock_node(self, model, mlocks, index, add, node, path,
                        held):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            inner = node.body if isinstance(node.body, list) else [node.body]
            self._walk_lock_scope(
                model, mlocks, index, add, inner, path, [])
            return
        if isinstance(node, ast.ClassDef):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            got = []
            for item in node.items:
                lid = self._lock_id(model, mlocks, item.context_expr)
                if lid is not None:
                    for h in held:
                        add(h, lid, path, node.lineno, "nested-with")
                    got.append(lid)
            self._walk_lock_scope(
                model, mlocks, index, add, node.body, path, held + got)
            return
        if isinstance(node, ast.Call) and held:
            name = call_name(node)
            callee_locks: List[tuple] = []
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and model is not None
            ):
                for lk in (model.acquired_by_method.get(name) or ()):
                    callee_locks.append(("class", model.name, lk))
            elif isinstance(func, ast.Attribute):
                callee_locks.extend(index.get(name, ()))
            for lid in callee_locks:
                for h in held:
                    add(h, lid, path, node.lineno, f"call:{name}")
        for child in ast.iter_child_nodes(node):
            self._walk_lock_node(
                model, mlocks, index, add, child, path, held)

    def _edges_in_method(self, model, mname, mlocks, index, add):
        meth = model.methods[mname]
        self._walk_lock_scope(
            model, mlocks, index, add, meth.body, model.ctx.path, [])

    def _edges_in_module_funcs(self, ctx, mlocks, index, add):
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_lock_scope(
                    None, mlocks, index, add, node.body, ctx.path, [])

    def lock_cycles(self) -> List[List[LockEdge]]:
        """Cycles in the acquisition-order graph — each one a potential
        deadlock interleaving (thread A holds L1 wanting L2, thread B
        holds L2 wanting L1). Also surfaces syntactic self-nesting of a
        non-reentrant lock."""
        adj: Dict[tuple, List[LockEdge]] = {}
        for e in self.lock_edges:
            adj.setdefault(e.src, []).append(e)
        cycles: List[List[LockEdge]] = []
        seen_cycles: Set[frozenset] = set()
        for start in sorted(adj):
            stack: List[LockEdge] = []
            on_path: Set[tuple] = set()

            def dfs(nid):
                if len(cycles) > 32:  # defensive bound
                    return
                on_path.add(nid)
                for e in adj.get(nid, ()):
                    if e.dst == start and stack is not None:
                        cyc = stack + [e]
                        key = frozenset(
                            (c.src, c.dst) for c in cyc
                        )
                        if key not in seen_cycles:
                            seen_cycles.add(key)
                            cycles.append(list(cyc))
                    elif e.dst not in on_path:
                        stack.append(e)
                        dfs(e.dst)
                        stack.pop()
                on_path.discard(nid)

            dfs(start)
        return cycles

    # -- thread escapes ------------------------------------------------------

    @property
    def thread_escapes(self) -> List[ThreadEscape]:
        if self._escapes is None:
            self._escapes = []
            for path, ctx in self.contexts.items():
                self._escapes.extend(_find_thread_escapes(ctx))
        return self._escapes


def _mutated_names(body_nodes, *, bare_only: bool,
                   lock_names: Set[str]) -> Set[str]:
    """Names whose OBJECT is mutated (x[k]=, x.a=, x+=) in these nodes.
    With ``bare_only`` the mutation must not sit under any ``with``
    over a known lock name."""
    out: Set[str] = set()

    def walk(node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            got = any(
                isinstance(i.context_expr, ast.Name)
                and i.context_expr.id in lock_names
                for i in node.items
            ) or any(
                isinstance(i.context_expr, ast.Attribute)
                for i in node.items
            )
            for child in node.body:
                walk(child, held or got)
            return
        if isinstance(node, ast.AugAssign) and isinstance(
            node.target, ast.Name
        ):
            if not (bare_only and held):
                out.add(node.target.id)
        if isinstance(node, (ast.Subscript, ast.Attribute)) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            root = _attr_root(node if isinstance(node, ast.Attribute)
                              else node.value)
            if isinstance(node, ast.Subscript) and isinstance(
                node.value, ast.Name
            ):
                root = node.value
            if root is not None and not (bare_only and held):
                out.add(root.id)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for n in body_nodes:
        walk(n, False)
    return out


def _find_thread_escapes(ctx: "FileContext") -> List[ThreadEscape]:
    """Closures handed to thread entry points whose captured mutable
    state is also mutated by the spawning scope, with no lock on the
    closure side — the classic escaped-shared-local race."""
    out: List[ThreadEscape] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name not in _THREAD_ENTRY_CALLS:
            continue
        tgt = None
        for kw in node.keywords:
            if kw.arg == "target":
                tgt = kw.value
        if tgt is None and name in ("submit_io", "start_new_thread") \
                and node.args:
            tgt = node.args[0]
        if tgt is None:
            continue
        if isinstance(tgt, ast.Lambda):
            out.append(ThreadEscape(
                node=node, path=ctx.path, name="", target="<lambda>",
                message=(
                    "thread target is a lambda — hoist it to a named "
                    "function so its captured state is analyzable "
                    "(and guard anything it shares)"
                ),
            ))
            continue
        if not isinstance(tgt, ast.Name):
            continue  # self.<method> targets are the class model's job
        scope = ctx.scope_of(node)
        target_def = None
        for sub in ctx.walk_scope(scope):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub.name == tgt.id:
                target_def = sub
        # walk_scope skips nested defs; look one level down explicitly
        if target_def is None and hasattr(scope, "body"):
            for sub in scope.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name == tgt.id:
                    target_def = sub
        if target_def is None:
            continue
        # names bound to safe factories (queues, events, locks) in the
        # spawning scope are synchronization, not shared state
        safe: Set[str] = set()
        lock_names: Set[str] = set()
        for sub in ctx.walk_scope(scope):
            if isinstance(sub, ast.Assign) and isinstance(
                sub.value, ast.Call
            ):
                tail = call_name(sub.value)
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        if tail in _SAFE_FACTORIES:
                            safe.add(t.id)
                        elif tail in _LOCK_FACTORIES or tail == "Condition":
                            safe.add(t.id)
                            lock_names.add(t.id)
        closure_locals = {
            a.arg for a in (
                list(target_def.args.posonlyargs)
                + list(target_def.args.args)
                + list(target_def.args.kwonlyargs)
            )
        }
        for sub in ast.walk(target_def):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        closure_locals.add(t.id)
        bare_in_closure = _mutated_names(
            target_def.body, bare_only=True, lock_names=lock_names,
        ) - closure_locals - safe
        outer_nodes = [
            n for n in (scope.body if hasattr(scope, "body") else [])
            if n is not target_def
        ]
        outer_mutated = _mutated_names(
            outer_nodes, bare_only=False, lock_names=lock_names,
        ) - safe
        for nm in sorted(bare_in_closure & outer_mutated):
            out.append(ThreadEscape(
                node=node, path=ctx.path, name=nm, target=tgt.id,
                message=(
                    f"'{nm}' is mutated bare inside thread target "
                    f"'{tgt.id}' AND by the spawning scope — an "
                    "escaped shared local; guard both sides with one "
                    "lock or hand results over a queue"
                ),
            ))
    return out


# -- file walking and reports ------------------------------------------------


def norm_path(path: str) -> str:
    p = os.path.normpath(path)
    try:
        rel = os.path.relpath(p)
        # only relativize when it stays inside the tree (no ../ escapes)
        if not rel.startswith(".."):
            p = rel
    except ValueError:
        pass
    return p.replace(os.sep, "/")


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs
                if d != "__pycache__" and not d.startswith(".")
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


@dataclass
class Report:
    files: List[str] = field(default_factory=list)
    violations: List[Violation] = field(default_factory=list)
    allow_sites: List[AllowSite] = field(default_factory=list)
    errors: List[Tuple[str, str]] = field(default_factory=list)
    # filled by baseline application (cli)
    baselined: int = 0
    unused_baseline: List[dict] = field(default_factory=list)
    # the PackageContext of the run's second pass (when any package
    # group ran): the sharding-contract inventory reads it back out
    package: Optional["PackageContext"] = None


def _package_groups(
    package_pass: bool, spmd_pass: bool, determinism_pass: bool = True,
) -> Set[str]:
    groups: Set[str] = set()
    if package_pass:
        groups.add("concurrency")
    if spmd_pass:
        groups.add("spmd")
    if determinism_pass:
        groups.add("determinism")
    return groups


def _run_package_rules(
    report: Report, contexts: Sequence[FileContext], groups: Set[str],
) -> Optional["PackageContext"]:
    """The second pass: rules that need every file at once (the
    concurrency analyzer, the SPMD/sharding-contract analyzer).
    Suppressions are honored per owning file."""
    if not contexts or not groups:
        return None
    pkg = PackageContext(contexts)
    by_path = {ctx.path: ctx for ctx in contexts}
    for rule in PACKAGE_RULES.values():
        if rule.group not in groups:
            continue
        for v in rule.check(pkg):
            ctx = by_path.get(v.path)
            if ctx is None or not ctx.suppressed(v):
                report.violations.append(v)
    return pkg


def analyze_source(
    path: str, source: str, package_pass: bool = True,
    spmd_pass: bool = True, determinism_pass: bool = True,
) -> Report:
    """Run every registered rule over one in-memory source blob (the
    package pass runs degenerately over the single file)."""
    _load_rules()
    report = Report(files=[norm_path(path)])
    try:
        ctx = FileContext(path, source)
    except SyntaxError as e:
        report.errors.append((norm_path(path), f"syntax error: {e}"))
        return report
    for rule in RULES.values():
        for v in rule.check(ctx):
            if not ctx.suppressed(v):
                report.violations.append(v)
    report.package = _run_package_rules(
        report, [ctx],
        _package_groups(package_pass, spmd_pass, determinism_pass),
    )
    report.allow_sites.extend(ctx.allow_sites)
    report.violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return report


def analyze_paths(
    paths: Sequence[str], package_pass: bool = True,
    spmd_pass: bool = True, determinism_pass: bool = True,
) -> Report:
    _load_rules()
    report = Report()
    contexts: List[FileContext] = []
    for fp in iter_python_files(paths):
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            report.errors.append((norm_path(fp), str(e)))
            continue
        report.files.append(norm_path(fp))
        try:
            ctx = FileContext(fp, source)
        except SyntaxError as e:
            report.errors.append((norm_path(fp), f"syntax error: {e}"))
            continue
        for rule in RULES.values():
            for v in rule.check(ctx):
                if not ctx.suppressed(v):
                    report.violations.append(v)
        report.allow_sites.extend(ctx.allow_sites)
        contexts.append(ctx)
    report.package = _run_package_rules(
        report, contexts,
        _package_groups(package_pass, spmd_pass, determinism_pass),
    )
    report.violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return report
