"""Analyzer core: file contexts, the rule registry, suppressions.

Everything here is stdlib-only (``ast`` + ``tokenize``) — the analyzer
must run in any environment the package runs in, including the minimal
CI container, without importing jax or the package under analysis.
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import (
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

# -- violations and the rule registry ----------------------------------------


@dataclass
class Violation:
    rule: str  # "PL001"
    slug: str  # "hidden-host-sync"
    path: str  # normalized (posix, relative when possible)
    line: int
    col: int
    message: str
    snippet: str = ""  # stripped source line, the baseline matching key
    # The allow-site audit emits violations AT suppression comments; those
    # must not be swallowed by the very comment they audit.
    suppressable: bool = True

    def location(self) -> str:
        return f"{self.path}:{self.line}:{self.col}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "slug": self.slug,
            "file": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }


@dataclass
class Rule:
    id: str
    slug: str
    doc: str
    check: Callable[["FileContext"], Iterable[Violation]]


RULES: Dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    RULES[rule.id] = rule
    return rule


def _load_rules() -> None:
    """Import the rule modules (each registers itself on import)."""
    if not RULES:
        import photon_ml_tpu.lint.rules  # noqa: F401


# -- suppression comments ----------------------------------------------------

_ALLOW_RE = re.compile(r"#\s*photon:\s*allow\(\s*([A-Za-z0-9_\-,\s]*?)\s*\)")


@dataclass
class AllowSite:
    line: int  # line the comment is ON
    applies_to: int  # line the suppression covers
    rules: Set[str]  # tokens as written (ids and/or slugs)
    path: str = ""
    # set by the PL001 audit for hidden-host-sync sites: does the
    # enclosing scope feed the counted seam / serial switch?
    seam_ok: Optional[bool] = None

    def to_dict(self) -> dict:
        d = {
            "file": self.path,
            "line": self.line,
            "applies_to": self.applies_to,
            "rules": sorted(self.rules),
        }
        if self.seam_ok is not None:
            d["seam_ok"] = self.seam_ok
        return d


# -- per-file analysis context -----------------------------------------------

# module roots whose values are device arrays (taint sources)
_JAX_ROOT_MODULES = ("jax",)
_STATIC_ATTRS = {
    "shape", "ndim", "dtype", "size", "sharding", "weak_type", "aval",
}
# jax.* calls returning host metadata, not device arrays
_JAX_METADATA_FUNCS = {
    "devices", "local_devices", "device_count", "local_device_count",
    "process_index", "process_count", "default_backend", "make_mesh",
}


class FileContext:
    """Parsed source + the cross-rule queries every check needs: parent
    links, enclosing scopes, import aliases, suppressions, and a local
    (per-scope) jax-value taint."""

    def __init__(self, path: str, source: str):
        self.path = norm_path(path)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._parents: Dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node
        self.allow_sites: List[AllowSite] = []
        self._suppressed: Dict[int, Set[str]] = {}
        self._scan_comments()
        # import aliases
        self.jax_modules: Set[str] = set()  # names aliasing jax[. ...]
        self.numpy_modules: Set[str] = set()  # names aliasing numpy
        self.jax_names: Set[str] = set()  # from jax import <name>
        self.overlap_modules: Set[str] = set()  # names aliasing ...overlap
        self.overlap_names: Set[str] = set()  # from ...overlap import <n>
        self._scan_imports()
        self._taint_cache: Dict[int, Set[str]] = {}

    # -- structure ----------------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def scope_of(self, node: ast.AST) -> ast.AST:
        """Innermost function scope, or the module itself."""
        return self.enclosing_function(node) or self.tree

    def path_parts(self) -> Tuple[str, ...]:
        return tuple(p for p in self.path.split("/") if p)

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def violation(
        self, rule: "Rule", node: ast.AST, message: str, **kw
    ) -> Violation:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Violation(
            rule=rule.id, slug=rule.slug, path=self.path, line=line,
            col=col, message=message, snippet=self.snippet(line), **kw,
        )

    # -- scope queries -------------------------------------------------------

    def scope_calls(self, scope: ast.AST, names: Set[str]) -> bool:
        """Does ``scope`` directly call (or reference) any of ``names``
        (bare name or attribute), not counting nested function bodies?"""
        for node in self.walk_scope(scope):
            if isinstance(node, ast.Name) and node.id in names:
                return True
            if isinstance(node, ast.Attribute) and node.attr in names:
                return True
        return False

    def walk_scope(self, scope: ast.AST) -> Iterator[ast.AST]:
        """Walk a function/module body without descending into nested
        function/class definitions."""
        body = scope.body if hasattr(scope, "body") else []
        stack = list(body)
        while stack:
            node = stack.pop()
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                     ast.Lambda),
                ):
                    continue
                stack.append(child)

    # -- imports -------------------------------------------------------------

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    top = alias.name.split(".")[0]
                    name = alias.asname or alias.name.split(".")[0]
                    if top in _JAX_ROOT_MODULES:
                        self.jax_modules.add(alias.asname or top)
                    if top == "numpy":
                        self.numpy_modules.add(alias.asname or top)
                    if alias.name.endswith("parallel.overlap"):
                        self.overlap_modules.add(name)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for alias in node.names:
                    name = alias.asname or alias.name
                    if mod.split(".")[0] in _JAX_ROOT_MODULES:
                        if alias.name == "numpy":
                            self.jax_modules.add(name)
                        else:
                            self.jax_names.add(name)
                    if mod == "numpy":
                        self.numpy_modules.add(name)  # from numpy import *
                    if mod.endswith("parallel.overlap"):
                        self.overlap_names.add(name)
                    if mod.endswith("parallel") and alias.name == "overlap":
                        self.overlap_modules.add(name)

    def is_jax_module(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.jax_modules

    def is_numpy_module(self, node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in self.numpy_modules

    def is_overlap_module(self, node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Name) and node.id in self.overlap_modules
        )

    # -- suppressions --------------------------------------------------------

    def _scan_comments(self) -> None:
        try:
            tokens = list(
                tokenize.generate_tokens(io.StringIO(self.source).readline)
            )
        except tokenize.TokenError:
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _ALLOW_RE.search(tok.string)
            if not m:
                continue
            rules = {
                r.strip() for r in m.group(1).split(",") if r.strip()
            }
            line = tok.start[0]
            text_before = self.lines[line - 1][: tok.start[1]].strip()
            applies_to = line if text_before else self._next_code_line(line)
            site = AllowSite(
                line=line, applies_to=applies_to, rules=rules,
                path=self.path,
            )
            self.allow_sites.append(site)
            self._suppressed.setdefault(applies_to, set()).update(rules)

    def _next_code_line(self, comment_line: int) -> int:
        for ln in range(comment_line + 1, len(self.lines) + 1):
            text = self.lines[ln - 1].strip()
            if text and not text.startswith("#"):
                return ln
        return comment_line

    def suppressed(self, violation: Violation) -> bool:
        if not violation.suppressable:
            return False
        toks = self._suppressed.get(violation.line)
        if not toks:
            return False
        return bool(
            toks & {violation.rule, violation.slug, "*", "all"}
        )

    # -- local jax-value taint ----------------------------------------------

    def jax_taint(
        self, scope: ast.AST, include_params: bool = False,
        exclude_params: Sequence[str] = (),
    ) -> Set[str]:
        """Names in ``scope`` that provably hold jax values: assigned from
        ``jax.*``/``jnp.*`` expressions (or derived from such names).
        With ``include_params`` the scope's own parameters seed the set —
        the right semantics inside a jitted body, where every non-static
        argument is a tracer."""
        key = (id(scope), include_params, tuple(exclude_params))
        cached = self._taint_cache.get(key)
        if cached is not None:
            return cached
        tainted: Set[str] = set()
        if include_params and hasattr(scope, "args"):
            a = scope.args
            params = [
                p.arg
                for p in (
                    list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                )
            ]
            if a.vararg:
                params.append(a.vararg.arg)
            if a.kwarg:
                params.append(a.kwarg.arg)
            tainted.update(
                p for p in params
                if p not in exclude_params and p != "self"
            )
        # fixpoint over straight-line assignments (monotone, so a couple
        # of passes converge; bound defensively)
        for _ in range(10):
            before = len(tainted)
            for node in self.walk_scope(scope):
                if isinstance(node, ast.Assign):
                    if self.expr_tainted(node.value, tainted):
                        for tgt in node.targets:
                            self._taint_target(tgt, tainted)
                elif isinstance(node, ast.AnnAssign) and node.value:
                    if self.expr_tainted(node.value, tainted):
                        self._taint_target(node.target, tainted)
                elif isinstance(node, ast.AugAssign):
                    if self.expr_tainted(node.value, tainted):
                        self._taint_target(node.target, tainted)
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    if self.expr_tainted(node.iter, tainted):
                        self._taint_target(node.target, tainted)
            if len(tainted) == before:
                break
        self._taint_cache[key] = tainted
        return tainted

    def _taint_target(self, target: ast.AST, tainted: Set[str]) -> None:
        if isinstance(target, ast.Name):
            tainted.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._taint_target(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._taint_target(target.value, tainted)

    def expr_tainted(self, expr: ast.AST, tainted: Set[str]) -> bool:
        """Does this expression (conservatively, low-false-positive)
        evaluate to a jax value?"""
        if isinstance(expr, ast.Name):
            return expr.id in tainted
        if isinstance(expr, ast.Call):
            root = _attr_root(expr.func)
            if root is not None and root.id in self.jax_modules:
                tail = (
                    expr.func.attr
                    if isinstance(expr.func, ast.Attribute)
                    else ""
                )
                return tail not in _JAX_METADATA_FUNCS
            if isinstance(expr.func, ast.Name) and expr.func.id in tainted:
                return True  # calling a jitted/taint-derived callable
            if isinstance(expr.func, ast.Attribute):
                # method on a tainted value: x.sum(), x.astype(...)
                return self.expr_tainted(expr.func.value, tainted)
            return False
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return False
            return self.expr_tainted(expr.value, tainted)
        if isinstance(expr, ast.Subscript):
            return self.expr_tainted(expr.value, tainted)
        if isinstance(expr, ast.BinOp):
            return self.expr_tainted(
                expr.left, tainted
            ) or self.expr_tainted(expr.right, tainted)
        if isinstance(expr, ast.UnaryOp):
            return self.expr_tainted(expr.operand, tainted)
        if isinstance(expr, ast.Compare):
            return self.expr_tainted(expr.left, tainted) or any(
                self.expr_tainted(c, tainted) for c in expr.comparators
            )
        if isinstance(expr, ast.BoolOp):
            return any(self.expr_tainted(v, tainted) for v in expr.values)
        if isinstance(expr, ast.IfExp):
            return self.expr_tainted(
                expr.body, tainted
            ) or self.expr_tainted(expr.orelse, tainted)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e, tainted) for e in expr.elts)
        if isinstance(expr, ast.Starred):
            return self.expr_tainted(expr.value, tainted)
        return False


def _attr_root(node: ast.AST) -> Optional[ast.Name]:
    """Root Name of a dotted chain: ``jax.numpy.asarray`` -> Name(jax)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node if isinstance(node, ast.Name) else None


def attr_root(node: ast.AST) -> Optional[ast.Name]:
    return _attr_root(node)


def call_name(node: ast.Call) -> str:
    """Trailing callee name: ``overlap.submit_io(...)`` -> ``submit_io``."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


# -- file walking and reports ------------------------------------------------


def norm_path(path: str) -> str:
    p = os.path.normpath(path)
    try:
        rel = os.path.relpath(p)
        # only relativize when it stays inside the tree (no ../ escapes)
        if not rel.startswith(".."):
            p = rel
    except ValueError:
        pass
    return p.replace(os.sep, "/")


def iter_python_files(paths: Sequence[str]) -> Iterator[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs
                if d != "__pycache__" and not d.startswith(".")
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


@dataclass
class Report:
    files: List[str] = field(default_factory=list)
    violations: List[Violation] = field(default_factory=list)
    allow_sites: List[AllowSite] = field(default_factory=list)
    errors: List[Tuple[str, str]] = field(default_factory=list)
    # filled by baseline application (cli)
    baselined: int = 0
    unused_baseline: List[dict] = field(default_factory=list)


def analyze_source(path: str, source: str) -> Report:
    """Run every registered rule over one in-memory source blob."""
    _load_rules()
    report = Report(files=[norm_path(path)])
    try:
        ctx = FileContext(path, source)
    except SyntaxError as e:
        report.errors.append((norm_path(path), f"syntax error: {e}"))
        return report
    for rule in RULES.values():
        for v in rule.check(ctx):
            if not ctx.suppressed(v):
                report.violations.append(v)
    report.allow_sites.extend(ctx.allow_sites)
    report.violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return report


def analyze_paths(paths: Sequence[str]) -> Report:
    _load_rules()
    report = Report()
    for fp in iter_python_files(paths):
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                source = fh.read()
        except OSError as e:
            report.errors.append((norm_path(fp), str(e)))
            continue
        sub = analyze_source(fp, source)
        report.files.extend(sub.files)
        report.violations.extend(sub.violations)
        report.allow_sites.extend(sub.allow_sites)
        report.errors.extend(sub.errors)
    report.violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return report
