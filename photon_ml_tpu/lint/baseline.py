"""Baseline handling: grandfathered violations, keyed content-wise.

Entries match on ``(file, rule, stripped source line)`` rather than line
numbers, so unrelated edits above a grandfathered site don't churn the
baseline. Each key carries a count — two identical raw readback lines in
one file need two entries' worth of allowance, and FIXING one of them
makes the spare allowance visible as an unused entry."""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import List, Sequence, Tuple

from photon_ml_tpu.lint.core import Report, Violation

BASELINE_VERSION = 1

# Rules whose violations may never be grandfathered. A lock-order
# inversion (PL009) is a deadlock with a schedule attached, and a host
# gather of a sharded bank (PL012) silently un-shards the pod story on
# exactly the paths that only fail at fleet scale — baselining either
# ships the failure; write_baseline refuses and load_baseline rejects
# hand-edited entries. Ambient entropy in an artifact (PL016) rots the
# very signatures the bitwise gates compare, and a half-wired message
# type (PL018) is a protocol hole — both have declaration/contract
# mechanisms instead of grandfathering.
NEVER_BASELINE = frozenset({"PL009", "PL012", "PL016", "PL018"})

_NEVER_BASELINE_WHY = {
    "PL009": "lock-order inversions are never baseline-able; fix the "
             "acquisition order instead",
    "PL012": "sharded-bank host gathers are never baseline-able; make "
             "the access shard-local or declare a sharding(export) "
             "scope instead",
    "PL016": "ambient entropy in artifacts is never baseline-able; "
             "derive the value from content or declare it with "
             "'# photon: entropy(<reason>)' instead",
    "PL018": "wire-contract holes are never baseline-able; wire the "
             "missing encoder/decoder/dispatch/corpus leg instead",
}

Key = Tuple[str, str, str]


class BaselineRefused(ValueError):
    """Raised when a violation set contains never-baseline-able rules."""


def baseline_key(v: Violation) -> Key:
    return (v.path, v.rule, v.snippet)


def load_baseline(path: str) -> Counter:
    """Baseline file -> Counter of (file, rule, snippet) allowances."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"unsupported baseline version {data.get('version')!r} "
            f"in {path} (expected {BASELINE_VERSION})"
        )
    allow: Counter = Counter()
    for e in data.get("entries", []):
        if e["rule"] in NEVER_BASELINE:
            raise ValueError(
                f"baseline {path} grandfathers {e['rule']} "
                f"({e['file']}) — {_NEVER_BASELINE_WHY[e['rule']]}"
            )
        allow[(e["file"], e["rule"], e["snippet"])] += int(
            e.get("count", 1)
        )
    return allow


def write_baseline(path: str, violations: Sequence[Violation]) -> dict:
    refused = [v for v in violations if v.rule in NEVER_BASELINE]
    if refused:
        sites = ", ".join(v.location() for v in refused[:5])
        why = "; ".join(sorted({
            _NEVER_BASELINE_WHY[v.rule] for v in refused
        }))
        raise BaselineRefused(
            f"{len(refused)} {sorted({v.rule for v in refused})} "
            f"violation(s) cannot be grandfathered ({sites}"
            f"{', ...' if len(refused) > 5 else ''}) — {why}; no "
            "baseline was written"
        )
    counts: Counter = Counter(baseline_key(v) for v in violations)
    entries: List[dict] = [
        {"file": f, "rule": r, "snippet": s, "count": c}
        for (f, r, s), c in sorted(counts.items())
    ]
    data = {"version": BASELINE_VERSION, "entries": entries}
    # temp + os.replace (PL006's own contract) without importing the
    # reliability helpers: the analyzer stays stdlib-only by design
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return data


def apply_baseline(report: Report, allow: Counter) -> Report:
    """Filter baselined violations out of ``report`` (in place): each
    (file, rule, snippet) key absorbs up to its count. Leftover
    allowances are surfaced as ``unused_baseline`` so stale entries are
    visible (and removable) instead of silently masking future
    regressions at the same key."""
    remaining = Counter(allow)
    kept: List[Violation] = []
    baselined = 0
    for v in report.violations:
        k = baseline_key(v)
        if remaining.get(k, 0) > 0:
            remaining[k] -= 1
            baselined += 1
        else:
            kept.append(v)
    report.violations = kept
    report.baselined = baselined
    report.unused_baseline = [
        {"file": f, "rule": r, "snippet": s, "count": c}
        for (f, r, s), c in sorted(remaining.items())
        if c > 0
    ]
    return report
