"""The whole-package SPMD model behind PL011-PL014 and SHARDING.md.

Per file this builds, from stdlib ``ast`` alone (no jax import — the
analyzer keeps running in the minimal CI container):

- an **axis environment**: names that provably hold one of the three
  canonical mesh axis names (``data`` / ``model`` / ``entity``) — via
  ``from ...parallel.mesh import DATA_AXIS``-style imports, module
  constants, local ``ax = axis`` chains and axis-parameter defaults;
- the **mesh entry points**: every ``shard_map(...)`` site (decorator,
  direct-call and ``partial(shard_map, ...)(f)`` forms) and every
  ``jax.jit`` site that pins sharding behavior (``out_shardings`` /
  ``in_shardings`` / ``donate_argnums`` / ``donate_argnames``, or a
  module-level jit assignment — the serving program family);
- the **sharding declarations**: ``# photon: sharding(...)`` comments
  attached to def lines (or the assignment line for module-level jits).
  Grammar: comma-separated ``key=value`` items with keys ``axes`` /
  ``in`` / ``out`` / ``donates`` (value either ``[a,b,...]`` or ``?``),
  plus the bare tokens ``export`` / ``checkpoint`` marking an export or
  checkpoint scope (the one place PL012 permits host-materializing a
  sharded bank). Spec tokens: an axis name, ``r`` (fully replicated,
  ``P()``), ``?`` (statically undeterminable), ``*`` (variadic tail),
  and ``a+b`` for multi-axis specs like ``P(data, model)``.

Declarations are contracts, not suppressions: PL011 cross-checks every
declaration against the code it annotates, and the generated SHARDING.md
(lint/sharding_contracts.py) is the machine-verified inventory the
unified-mesh refactor starts from.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from photon_ml_tpu.lint.core import (
    FileContext,
    PackageContext,
    attr_root,
    call_name,
)

CANONICAL_AXES = ("data", "model", "entity", "grid")
AXIS_CONSTANTS = {
    "DATA_AXIS": "data",
    "MODEL_AXIS": "model",
    "ENTITY_AXIS": "entity",
    "GRID_AXIS": "grid",
}

# collective -> positional index of the axis-name argument
COLLECTIVES = {
    "psum": 1,
    "pmean": 1,
    "pmax": 1,
    "pmin": 1,
    "all_gather": 1,
    "all_to_all": 1,
    "ppermute": 1,
    "axis_index": 0,
    "psum_scatter": 1,
}
# collectives whose output is complete across the mapped axis — they
# discharge PL013's "replicated out_spec needs a reduction" obligation
REDUCTIONS = {"psum", "pmean", "pmax", "pmin", "all_gather"}

_SHARDING_KW = ("out_shardings", "in_shardings", "donate_argnums",
                "donate_argnames")

_AXIS_PARAM_RE = re.compile(r"(^axis(_name)?$|_axis(_name)?$)")


def is_axis_param_name(name: str) -> bool:
    return bool(_AXIS_PARAM_RE.search(name))


# -- declarations -------------------------------------------------------------


@dataclass
class ShardingDecl:
    line: int
    raw: str
    export: bool = False
    axes: Optional[List[str]] = None
    in_specs: Optional[List[str]] = None
    out_specs: Optional[List[str]] = None
    donates: Optional[List[int]] = None
    has_axes_key: bool = False
    errors: List[str] = field(default_factory=list)


def _split_top_level(raw: str) -> List[str]:
    """Split on commas not nested in brackets."""
    out, depth, cur = [], 0, []
    for ch in raw:
        if ch == "[":
            depth += 1
        elif ch == "]":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return [p.strip() for p in out if p.strip()]


def _parse_list(value: str) -> Optional[List[str]]:
    value = value.strip()
    if value == "?":
        return None
    if value.startswith("[") and value.endswith("]"):
        inner = value[1:-1].strip()
        if not inner:
            return []
        return [t.strip() for t in inner.split(",") if t.strip()]
    return [value]


def parse_sharding_decl(line: int, raw: str) -> ShardingDecl:
    decl = ShardingDecl(line=line, raw=raw)
    for item in _split_top_level(raw):
        if item in ("export", "checkpoint"):
            decl.export = True
            continue
        if "=" not in item:
            decl.errors.append(f"unparseable token {item!r}")
            continue
        key, _, value = item.partition("=")
        key, value = key.strip(), value.strip()
        if key == "axes":
            decl.has_axes_key = True
            decl.axes = _parse_list(value) or []
            if value == "?":
                decl.errors.append("axes may not be '?' — name the axes")
        elif key == "in":
            decl.in_specs = _parse_list(value)
        elif key == "out":
            decl.out_specs = _parse_list(value)
        elif key == "donates":
            toks = _parse_list(value)
            if toks is None:
                decl.donates = None
            else:
                try:
                    decl.donates = sorted(int(t) for t in toks)
                except ValueError:
                    decl.errors.append(f"non-integer donates item in {value!r}")
        else:
            decl.errors.append(f"unknown key {key!r}")
    return decl


# -- spec atoms ---------------------------------------------------------------
#
# A rendered spec is a list of per-argument tokens; each token is a "+"
# join of atoms. Atom forms: a canonical axis name, "r" (replicated),
# "$<symbol>" for an in-scope name the axis resolution could not pin to
# a constant (substituted from the declaration when unambiguous), and
# "?" for anything else.


def substitute(tokens: Optional[List[str]],
               mapping: Dict[str, str]) -> Optional[List[str]]:
    if tokens is None:
        return None
    out = []
    for tok in tokens:
        atoms = []
        for a in tok.split("+"):
            if a.startswith("$"):
                atoms.append(mapping.get(a[1:], "?"))
            else:
                atoms.append(a)
        out.append("+".join(atoms))
    return out


def specs_match(declared: List[str], rendered: List[str]) -> bool:
    """Element-wise compare; '?' (either side) matches anything and a
    trailing '*' in the declaration absorbs the rest."""
    di = 0
    for ri, tok in enumerate(rendered):
        if di >= len(declared):
            return False
        d = declared[di]
        if d == "*":
            return True
        if d != "?" and tok != "?" and d != tok:
            return False
        di += 1
    if di < len(declared):
        return declared[di] == "*" and di == len(declared) - 1
    return True


# -- entries ------------------------------------------------------------------


@dataclass
class SpmdEntry:
    path: str
    qualname: str
    line: int  # declaration attachment line (def or assignment)
    kind: str  # "shard_map" | "jit" | "declared"
    node: ast.AST  # where PL011 reports contract violations
    axes_resolved: Set[str] = field(default_factory=set)
    axis_symbols: Set[str] = field(default_factory=set)
    in_rendered: Optional[List[str]] = None
    out_rendered: Optional[List[str]] = None
    donates: Optional[List[int]] = None
    decl: Optional[ShardingDecl] = None
    mapped_fn: Optional[ast.FunctionDef] = None
    in_spec_exprs: Optional[ast.AST] = None
    out_spec_exprs: Optional[ast.AST] = None

    def symbol_mapping(self) -> Dict[str, str]:
        """Unambiguous symbol -> axis assignment from the declaration:
        when exactly one spec symbol stayed unresolved and the
        declaration names exactly one axis the code did not already
        resolve, they pair up."""
        if self.decl is None or self.decl.axes is None:
            return {}
        leftover = [a for a in self.decl.axes
                    if a not in self.axes_resolved]
        syms = sorted(self.axis_symbols)
        if len(syms) == 1 and len(leftover) == 1:
            return {syms[0]: leftover[0]}
        return {}

    def axes_for_table(self) -> List[str]:
        axes = set(self.axes_resolved)
        mapping = self.symbol_mapping()
        for s in self.axis_symbols:
            axes.add(mapping.get(s, "?"))
        if self.decl is not None and self.decl.axes is not None:
            axes |= {a for a in self.decl.axes if a in CANONICAL_AXES}
        axes.discard("?")
        listed = sorted(axes)
        if not listed and self.axis_symbols:
            listed = ["?"]
        return listed


@dataclass
class ExportScope:
    path: str
    qualname: str
    line: int
    node: ast.AST


# -- per-file model -----------------------------------------------------------


class SpmdFileModel:
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.axis_env: Dict[str, str] = {}
        self.entries: List[SpmdEntry] = []
        self.export_scopes: List[ExportScope] = []
        self.decls: Dict[int, ShardingDecl] = {
            line: parse_sharding_decl(line, raw)
            for line, raw in ctx.sharding_annotations.items()
        }
        self._claimed_decl_lines: Set[int] = set()
        self._claimed_calls: Set[int] = set()
        self.local_defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.local_defs.setdefault(node.name, node)
        self._scan_axis_env()
        self._scan_entries()
        self._attach_orphan_decls()

    # -- axis environment ----------------------------------------------------

    def _scan_axis_env(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name in AXIS_CONSTANTS:
                        self.axis_env[alias.asname or alias.name] = (
                            AXIS_CONSTANTS[alias.name]
                        )
            elif isinstance(node, ast.Assign):
                if (
                    isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)
                    and node.value.value in CANONICAL_AXES
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) and (
                            tgt.id in AXIS_CONSTANTS
                            or tgt.id.endswith("_AXIS")
                        ):
                            self.axis_env[tgt.id] = node.value.value

    def _enclosing_functions(self, node: ast.AST) -> List[ast.AST]:
        out = []
        for anc in self.ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(anc)
        return out

    def resolve_axis(self, expr: ast.AST,
                     node_for_scope: ast.AST,
                     _depth: int = 0) -> Tuple[str, Optional[str]]:
        """-> (kind, value): ("const", axis) | ("literal", s) |
        ("symbol", name) | ("unknown", None)."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return ("literal", expr.value)
        if not isinstance(expr, ast.Name) or _depth > 4:
            return ("unknown", None)
        name = expr.id
        if name in self.axis_env:
            return ("const", self.axis_env[name])
        for fn in self._enclosing_functions(node_for_scope):
            # local assignment chain: ax = axis
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in sub.targets
                ):
                    kind, val = self.resolve_axis(
                        sub.value, sub, _depth + 1
                    )
                    if kind in ("const", "literal", "symbol"):
                        if kind == "literal" and val in CANONICAL_AXES:
                            return ("const", val)
                        if kind == "const":
                            return ("const", val)
                        # fall through to param-default resolution
            # parameter default
            a = fn.args
            params = list(a.posonlyargs) + list(a.args)
            defaults = list(a.defaults)
            if defaults:
                for p, d in zip(params[-len(defaults):], defaults):
                    if p.arg != name:
                        continue
                    kind, val = self.resolve_axis(d, fn, _depth + 1)
                    if kind == "const":
                        return ("const", val)
                    if kind == "literal" and val in CANONICAL_AXES:
                        return ("const", val)
            kw = list(a.kwonlyargs)
            for p, d in zip(kw, a.kw_defaults):
                if d is not None and p.arg == name:
                    kind, val = self.resolve_axis(d, fn, _depth + 1)
                    if kind in ("const",):
                        return ("const", val)
        return ("symbol", name)

    # -- spec rendering ------------------------------------------------------

    def _is_p_call(self, expr: ast.AST) -> bool:
        return isinstance(expr, ast.Call) and call_name(expr) in (
            "P", "PartitionSpec"
        )

    def render_spec(self, expr: ast.AST, entry: "SpmdEntry") -> Optional[str]:
        """One P(...) -> token, collecting resolved axes/symbols into
        the entry; None when the expression is not a literal P call."""
        if not self._is_p_call(expr):
            return None
        atoms: List[str] = []

        def visit(arg):
            if isinstance(arg, ast.Constant) and arg.value is None:
                return
            if isinstance(arg, (ast.Tuple, ast.List)):
                for e in arg.elts:
                    visit(e)
                return
            kind, val = self.resolve_axis(arg, expr)
            if kind == "const":
                atoms.append(val)
                entry.axes_resolved.add(val)
            elif kind == "literal":
                atoms.append(val if val in CANONICAL_AXES else "?")
                if val in CANONICAL_AXES:
                    entry.axes_resolved.add(val)
            elif kind == "symbol":
                atoms.append(f"${val}")
                entry.axis_symbols.add(val)
            else:
                atoms.append("?")

        for arg in expr.args:
            visit(arg)
        return "+".join(atoms) if atoms else "r"

    def render_specs(self, expr: Optional[ast.AST],
                     entry: "SpmdEntry") -> Optional[List[str]]:
        if expr is None:
            return None
        if self._is_p_call(expr):
            tok = self.render_spec(expr, entry)
            return [tok] if tok is not None else None
        if isinstance(expr, (ast.Tuple, ast.List)):
            out = []
            for e in expr.elts:
                tok = self.render_spec(e, entry)
                if tok is None:
                    # still harvest axes from nested P calls for the
                    # axes cross-check, but give up on the arity compare
                    self._harvest_axes(e, entry)
                    return None
                out.append(tok)
            return out
        if isinstance(expr, ast.BinOp):  # computed: (...) + off_spec
            self._harvest_axes(expr, entry)
            return None
        self._harvest_axes(expr, entry)
        return None

    def _harvest_axes(self, expr: ast.AST, entry: "SpmdEntry") -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call) and self._is_p_call(sub):
                self.render_spec(sub, entry)

    # -- donate resolution ---------------------------------------------------

    def resolve_donate(self, expr: ast.AST,
                       scope_node: ast.AST,
                       _depth: int = 0) -> Optional[List[int]]:
        if _depth > 3 or expr is None:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
            return [expr.value]
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: Set[int] = set()
            for e in expr.elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.add(e.value)
                else:
                    return None
            return sorted(out)
        if isinstance(expr, ast.IfExp):
            a = self.resolve_donate(expr.body, scope_node, _depth + 1)
            b = self.resolve_donate(expr.orelse, scope_node, _depth + 1)
            if a is None and b is None:
                return None
            return sorted(set(a or []) | set(b or []))
        if isinstance(expr, ast.Name):
            for fn in self._enclosing_functions(scope_node):
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == expr.id
                        for t in sub.targets
                    ):
                        got = self.resolve_donate(
                            sub.value, sub, _depth + 1
                        )
                        if got is not None:
                            return got
            return None
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name):
            target = self.local_defs.get(expr.func.id)
            if target is None:
                return None
            out: Set[int] = set()
            for sub in ast.walk(target):
                if isinstance(sub, ast.Return) and sub.value is not None:
                    got = self.resolve_donate(sub.value, target, _depth + 1)
                    if got is not None:
                        out.update(got)
            return sorted(out) if out else None
        return None

    # -- entry extraction ----------------------------------------------------

    def _qualname(self, node: ast.AST, leaf: str) -> str:
        parts = []
        for anc in self.ctx.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                parts.append(anc.name)
        parts.reverse()
        parts.append(leaf)
        return ".".join(parts)

    def _decl_near(self, *lines: int) -> Optional[ShardingDecl]:
        """The declaration on (or just above) any of the given lines."""
        candidates: Set[int] = set()
        for ln in lines:
            candidates.update((ln, ln - 1, ln - 2))
        for ln in sorted(candidates, reverse=True):
            decl = self.decls.get(ln)
            if decl is not None and ln not in self._claimed_decl_lines:
                self._claimed_decl_lines.add(ln)
                return decl
        return None

    def _resolves_to_shard_map(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id == "shard_map"
        if isinstance(expr, ast.Attribute):
            return expr.attr == "shard_map"
        return False

    def _resolves_to_jit(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id == "jit"
        if isinstance(expr, ast.Attribute):
            return expr.attr == "jit"
        return False

    def _partial_of(self, call: ast.Call, what) -> bool:
        return (
            isinstance(call, ast.Call)
            and call_name(call) in ("partial", "_partial")
            and bool(call.args)
            and what(call.args[0])
        )

    def _shard_map_kwargs(self, call: ast.Call) -> Dict[str, ast.AST]:
        return {kw.arg: kw.value for kw in call.keywords if kw.arg}

    def _finish_shard_map(self, entry: SpmdEntry,
                          kwargs: Dict[str, ast.AST]) -> None:
        entry.in_spec_exprs = kwargs.get("in_specs")
        entry.out_spec_exprs = kwargs.get("out_specs")
        entry.in_rendered = self.render_specs(entry.in_spec_exprs, entry)
        entry.out_rendered = self.render_specs(entry.out_spec_exprs, entry)
        an = kwargs.get("axis_names")
        if an is not None:
            self._harvest_axis_names(an, entry)

    def _harvest_axis_names(self, expr: ast.AST, entry: SpmdEntry) -> None:
        for sub in ast.walk(expr):
            kind, val = self.resolve_axis(sub, expr)
            if kind == "const":
                entry.axes_resolved.add(val)
            elif kind == "literal" and val in CANONICAL_AXES:
                entry.axes_resolved.add(val)

    def _scan_entries(self) -> None:
        seen_defs: Set[int] = set()
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_def(node, seen_defs)
            elif isinstance(node, ast.Assign):
                self._scan_assign(node)
        # jit-with-sharding-kwargs calls in ANY position (e.g. as a
        # cache-insert argument: _bounded_put(..., jax.jit(_make,
        # out_shardings=...))) — the assignment walk above cannot see
        # these
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if id(node) in self._claimed_calls:
                continue
            if not self._resolves_to_jit(node.func):
                continue
            kw = self._shard_map_kwargs(node)
            if not any(k in kw for k in _SHARDING_KW):
                continue
            self._claimed_calls.add(id(node))
            entry = SpmdEntry(
                path=self.ctx.path,
                qualname=self._qualname(node, "<jit>"),
                line=node.lineno, kind="jit", node=node,
            )
            for key in ("out_shardings", "in_shardings"):
                if key in kw:
                    self._harvest_axes(kw[key], entry)
            if "donate_argnums" in kw:
                entry.donates = self.resolve_donate(
                    kw["donate_argnums"], node
                )
            if node.args and isinstance(node.args[0], ast.Name):
                entry.mapped_fn = self._nearest_def(node, node.args[0].id)
            entry.decl = self._decl_near(node.lineno)
            self.entries.append(entry)
        # export scopes: any def whose declaration says export
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            decl = self._decl_for_def(node)
            if decl is not None and decl.export:
                self.export_scopes.append(ExportScope(
                    path=self.ctx.path,
                    qualname=self._qualname(node, node.name),
                    line=node.lineno, node=node,
                ))

    def _decl_for_def(self, node) -> Optional[ShardingDecl]:
        lines = [node.lineno]
        if node.decorator_list:
            lines.append(node.decorator_list[0].lineno)
        candidates: Set[int] = set()
        for ln in lines:
            candidates.update((ln, ln - 1))
        for ln in sorted(candidates):
            decl = self.decls.get(ln)
            if decl is not None:
                return decl
        return None

    def _scan_def(self, node, seen: Set[int]) -> None:
        if id(node) in seen or not node.decorator_list:
            return
        sm_kwargs: Optional[Dict[str, ast.AST]] = None
        donate_expr: Optional[ast.AST] = None
        jit_kwargs: Dict[str, ast.AST] = {}
        for dec in node.decorator_list:
            if not isinstance(dec, ast.Call):
                continue
            if self._partial_of(dec, self._resolves_to_shard_map):
                kw = self._shard_map_kwargs(dec)
                if "mesh" in kw:
                    sm_kwargs = kw
            elif self._partial_of(dec, self._resolves_to_jit):
                kw = self._shard_map_kwargs(dec)
                if any(k in kw for k in _SHARDING_KW):
                    jit_kwargs = kw
                    donate_expr = kw.get("donate_argnums")
        if sm_kwargs is None and not jit_kwargs:
            return
        seen.add(id(node))
        entry = SpmdEntry(
            path=self.ctx.path,
            qualname=self._qualname(node, node.name),
            line=node.lineno,
            kind="shard_map" if sm_kwargs is not None else "jit",
            node=node,
            mapped_fn=node if sm_kwargs is not None else None,
        )
        if sm_kwargs is not None:
            self._finish_shard_map(entry, sm_kwargs)
        for key in ("out_shardings", "in_shardings"):
            if key in jit_kwargs:
                self._harvest_axes(jit_kwargs[key], entry)
        if donate_expr is not None:
            entry.donates = self.resolve_donate(donate_expr, node)
        entry.decl = self._decl_for_def(node)
        if entry.decl is not None:
            self._claimed_decl_lines.add(entry.decl.line)
        self.entries.append(entry)

    def _scan_assign(self, node: ast.Assign) -> None:
        value = node.value
        if not isinstance(value, ast.Call):
            return
        target = next(
            (t.id for t in node.targets if isinstance(t, ast.Name)), None
        )
        entry: Optional[SpmdEntry] = None
        # fit = partial(shard_map, mesh=..., ...)(fit)
        if isinstance(value.func, ast.Call) and self._partial_of(
            value.func, self._resolves_to_shard_map
        ):
            kw = self._shard_map_kwargs(value.func)
            if "mesh" in kw:
                entry = SpmdEntry(
                    path=self.ctx.path,
                    qualname=self._qualname(
                        node, target or "<shard_map>"
                    ),
                    line=node.lineno, kind="shard_map", node=node,
                )
                self._finish_shard_map(entry, kw)
                if value.args and isinstance(value.args[0], ast.Name):
                    entry.mapped_fn = self._nearest_def(
                        node, value.args[0].id
                    )
        # f = shard_map(g, mesh=..., ...)
        elif self._resolves_to_shard_map(value.func):
            kw = self._shard_map_kwargs(value)
            if "mesh" in kw:
                entry = SpmdEntry(
                    path=self.ctx.path,
                    qualname=self._qualname(
                        node, target or "<shard_map>"
                    ),
                    line=node.lineno, kind="shard_map", node=node,
                )
                self._finish_shard_map(entry, kw)
                if value.args and isinstance(value.args[0], ast.Name):
                    entry.mapped_fn = self._nearest_def(
                        node, value.args[0].id
                    )
        # NAME = jax.jit(f, <sharding-relevant kwargs>) — or any
        # module-level jit assignment (the AOT program families)
        elif self._resolves_to_jit(value.func):
            kw = self._shard_map_kwargs(value)
            module_level = isinstance(self.ctx.parent(node), ast.Module)
            if any(k in kw for k in _SHARDING_KW) or (
                module_level and target is not None
            ):
                entry = SpmdEntry(
                    path=self.ctx.path,
                    qualname=self._qualname(node, target or "<jit>"),
                    line=node.lineno, kind="jit", node=node,
                )
                for key in ("out_shardings", "in_shardings"):
                    if key in kw:
                        self._harvest_axes(kw[key], entry)
                if "donate_argnums" in kw:
                    entry.donates = self.resolve_donate(
                        kw["donate_argnums"], node
                    )
                if value.args and isinstance(value.args[0], ast.Name):
                    entry.mapped_fn = self._nearest_def(
                        node, value.args[0].id
                    )
        if entry is None:
            return
        self._claimed_calls.add(id(value))
        entry.decl = self._decl_near(node.lineno)
        self.entries.append(entry)

    def _nearest_def(self, node: ast.AST,
                     name: str) -> Optional[ast.FunctionDef]:
        for fn in self._enclosing_functions(node):
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)) \
                        and sub.name == name:
                    return sub
        return self.local_defs.get(name)

    def _attach_orphan_decls(self) -> None:
        """A sharding declaration on a def with no detected entry point
        enrolls that def manually (the tiled_sparse batch builders have
        no jit of their own — device_put placement — but still carry a
        sharding contract worth inventorying)."""
        entry_decl_lines = {
            e.decl.line for e in self.entries if e.decl is not None
        }
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            decl = self._decl_for_def(node)
            if decl is None or decl.export:
                continue
            if decl.line in entry_decl_lines:
                continue
            entry = SpmdEntry(
                path=self.ctx.path,
                qualname=self._qualname(node, node.name),
                line=node.lineno, kind="declared", node=node,
                decl=decl, mapped_fn=node,
            )
            entry_decl_lines.add(decl.line)
            self.entries.append(entry)


# -- package index ------------------------------------------------------------


class SpmdIndex:
    def __init__(self, pkg: PackageContext):
        self.models: Dict[str, SpmdFileModel] = {}
        for path, ctx in pkg.contexts.items():
            self.models[path] = SpmdFileModel(ctx)

    def all_entries(self) -> List[SpmdEntry]:
        out: List[SpmdEntry] = []
        for path in sorted(self.models):
            out.extend(self.models[path].entries)
        return out

    def all_export_scopes(self) -> List[ExportScope]:
        out: List[ExportScope] = []
        for path in sorted(self.models):
            out.extend(self.models[path].export_scopes)
        return out


def index(pkg: PackageContext) -> SpmdIndex:
    """The lazily-built, cached SPMD view of one analyzer run."""
    cached = getattr(pkg, "_spmd_index", None)
    if cached is None:
        cached = SpmdIndex(pkg)
        pkg._spmd_index = cached
    return cached


def file_model(ctx: FileContext) -> SpmdFileModel:
    cached = getattr(ctx, "_spmd_model", None)
    if cached is None:
        cached = SpmdFileModel(ctx)
        ctx._spmd_model = cached
    return cached


def in_export_scope(ctx: FileContext, node: ast.AST,
                    model: Optional[SpmdFileModel] = None) -> bool:
    """Is this node inside a function declared '# photon: sharding(export)'
    (checking the whole enclosing-def chain)?"""
    model = model or file_model(ctx)
    export_nodes = {id(s.node) for s in model.export_scopes}
    cur: Optional[ast.AST] = node
    while cur is not None:
        if id(cur) in export_nodes:
            return True
        cur = ctx.parent(cur)
    return False


def collective_axis_arg(call: ast.Call) -> Optional[ast.AST]:
    name = call_name(call)
    pos = COLLECTIVES.get(name)
    if pos is None:
        return None
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    if len(call.args) > pos:
        return call.args[pos]
    return None


def is_collective(call: ast.Call) -> bool:
    """A jax collective by name, with the module sanity-check that the
    callee is an attribute (lax.psum / jax.lax.psum) or a bare name
    imported from jax."""
    name = call_name(call)
    if name not in COLLECTIVES:
        return False
    func = call.func
    if isinstance(func, ast.Attribute):
        root = attr_root(func)
        return root is not None
    return True


__all__ = [
    "AXIS_CONSTANTS",
    "CANONICAL_AXES",
    "COLLECTIVES",
    "REDUCTIONS",
    "ExportScope",
    "ShardingDecl",
    "SpmdEntry",
    "SpmdFileModel",
    "SpmdIndex",
    "collective_axis_arg",
    "file_model",
    "in_export_scope",
    "index",
    "is_axis_param_name",
    "is_collective",
    "parse_sharding_decl",
    "specs_match",
    "substitute",
]
