"""``python -m photon_ml_tpu.lint`` entry point."""

import sys

from photon_ml_tpu.lint.cli import main

sys.exit(main())
