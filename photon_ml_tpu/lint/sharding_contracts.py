"""The sharding-contract registry: every jit/shard_map mesh entry point
in the package, inventoried into a generated SHARDING.md.

The inventory is the SPMD pass's entry-point scan (lint/spmd.py) merged
with the ``# photon: sharding(...)`` declarations PL011 cross-checks:
by the time SHARDING.md generates cleanly, every row has been
machine-verified against the code it describes. The committed file is
drift-gated — ``dev-scripts/lint.sh`` regenerates and diffs it, and
``python -m photon_ml_tpu.lint --check-sharding-md`` exits 1 on any
stale row — so the unified-mesh refactor starts from a complete,
trustworthy map of what shards how (the veScale "sharding is an
explicit, checkable declaration" posture, PAPERS.md).

Rows carry no line numbers on purpose: unrelated edits above an entry
point must not churn the inventory.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from photon_ml_tpu.lint import spmd
from photon_ml_tpu.lint.core import PackageContext, analyze_paths

DEFAULT_SHARDING_MD = "SHARDING.md"

_HEADER = """# SHARDING — machine-verified mesh entry-point inventory

**GENERATED FILE — do not edit.** Regenerate with
`python -m photon_ml_tpu.lint --write-sharding-md` (dev-scripts/lint.sh
diffs this file against a fresh run and fails CI on drift).

Every jit/shard_map mesh entry point in `photon_ml_tpu/`, as extracted
by the photon-lint SPMD pass (PL011-PL014) and cross-checked against
its `# photon: sharding(in=..., out=..., axes=...)` declaration. Spec
tokens: axis names (`data`/`model`/`entity`/`grid`), `r` = fully replicated
(`P()`), `a+b` = multi-axis spec, `?` = statically undeterminable,
`*` = variadic tail. `donates` lists donated argument positions.

## Entry points
"""

_EXPORT_HEADER = """
## Export / checkpoint scopes

Functions declared `# photon: sharding(export)` — the ONLY scopes in
which PL012 permits materializing an entity-/feature-sharded bank off
its shards (model export, checkpoint save/restore, parity oracles).
"""


def _fmt_specs(tokens: Optional[List[str]],
               decl_tokens: Optional[List[str]]) -> str:
    if tokens is not None:
        return ",".join(tokens) if tokens else "-"
    if decl_tokens is not None:
        return ",".join(decl_tokens) if decl_tokens else "-"
    return "?"


def _entry_row(entry: spmd.SpmdEntry) -> Dict[str, str]:
    mapping = entry.symbol_mapping()
    in_r = spmd.substitute(entry.in_rendered, mapping)
    out_r = spmd.substitute(entry.out_rendered, mapping)
    decl = entry.decl
    axes = entry.axes_for_table()
    donates = entry.donates
    if donates is None and decl is not None:
        donates = decl.donates
    return {
        "module": entry.path,
        "entry": entry.qualname,
        "kind": entry.kind,
        "axes": ",".join(axes) if axes else "-",
        "in": _fmt_specs(in_r, decl.in_specs if decl else None),
        "out": _fmt_specs(out_r, decl.out_specs if decl else None),
        "donates": (
            ",".join(str(i) for i in donates) if donates else "-"
        ),
        "declared": "yes" if decl is not None else "NO",
    }


def inventory(pkg: PackageContext) -> List[Dict[str, str]]:
    idx = spmd.index(pkg)
    rows = [
        _entry_row(e) for e in idx.all_entries()
        if "photon_ml_tpu" in e.path.split("/")
    ]
    rows.sort(key=lambda r: (r["module"], r["entry"], r["kind"]))
    return rows


def export_scopes(pkg: PackageContext) -> List[Dict[str, str]]:
    idx = spmd.index(pkg)
    rows = [
        {"module": s.path, "scope": s.qualname}
        for s in idx.all_export_scopes()
        if "photon_ml_tpu" in s.path.split("/")
    ]
    rows.sort(key=lambda r: (r["module"], r["scope"]))
    return rows


def render_markdown(pkg: PackageContext) -> str:
    rows = inventory(pkg)
    lines = [_HEADER]
    lines.append(
        "| Module | Entry point | Kind | Axes | In | Out | Donates |"
    )
    lines.append("|---|---|---|---|---|---|---|")
    for r in rows:
        lines.append(
            f"| {r['module']} | `{r['entry']}` | {r['kind']} | "
            f"{r['axes']} | `{r['in']}` | `{r['out']}` | "
            f"{r['donates']} |"
        )
    lines.append(f"\n{len(rows)} entry point(s).")
    scopes = export_scopes(pkg)
    lines.append(_EXPORT_HEADER)
    lines.append("| Module | Scope |")
    lines.append("|---|---|")
    for s in scopes:
        lines.append(f"| {s['module']} | `{s['scope']}` |")
    lines.append(f"\n{len(scopes)} export/checkpoint scope(s).")
    return "\n".join(lines) + "\n"


def package_context(paths: Sequence[str]) -> Optional[PackageContext]:
    """Analyze ``paths`` and return the run's PackageContext (None when
    nothing parsed)."""
    report = analyze_paths(paths, package_pass=False, spmd_pass=True)
    return report.package


def write_sharding_md(path: str, pkg: PackageContext) -> str:
    content = render_markdown(pkg)
    import os

    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        fh.write(content)
    os.replace(tmp, path)
    return content


def check_sharding_md(path: str, pkg: PackageContext) -> Optional[str]:
    """None when the committed file matches a fresh render; else a
    human-readable drift message."""
    expected = render_markdown(pkg)
    try:
        with open(path, "r", encoding="utf-8") as fh:
            actual = fh.read()
    except OSError as e:
        return f"cannot read {path}: {e}"
    if actual == expected:
        return None
    exp_lines = expected.splitlines()
    act_lines = actual.splitlines()
    for i, (a, b) in enumerate(zip(act_lines, exp_lines), 1):
        if a != b:
            return (
                f"{path} is stale (first drift at line {i}):\n"
                f"  committed: {a}\n"
                f"  expected:  {b}\n"
                "regenerate with: python -m photon_ml_tpu.lint "
                "--write-sharding-md"
            )
    return (
        f"{path} is stale ({len(act_lines)} lines committed, "
        f"{len(exp_lines)} expected) — regenerate with: "
        "python -m photon_ml_tpu.lint --write-sharding-md"
    )
