"""photon-lint: AST-based static checks for the JAX hot-path invariants.

PRs 1-3 established performance invariants that only runtime tests
enforced: every device->host fetch routes through the counted
``parallel/overlap.py`` seam, every spill scratch dir registers for the
atexit sweep, every ``submit_io`` is drained before exit. This package
makes those invariants machine-checked at review time — a
project-specific analyzer over the stdlib ``ast``, no new runtime deps.

Rules (see ``photon_ml_tpu/lint/rules/``):

==========  ======================  ===========================================
id          slug                    protects
==========  ======================  ===========================================
``PL001``   hidden-host-sync        all device->host fetches go through the
                                    counted ``overlap.device_get`` seam
``PL002``   recompile-hazard        no jit-of-lambda / jit-in-loop / unhashable
                                    static_argnums (silent recompilations)
``PL003``   tracer-leak             no tracers stored on ``self``/globals or
                                    Python-branched inside jitted bodies
``PL004``   spill-hygiene           scratch dirs under ``io/`` / GAME streaming
                                    register for the atexit sweep
``PL005``   undrained-io            ``submit_io`` scopes reach a ``drain_io``
``PL006``   reliability-hygiene     artifact writes publish atomically; IO
                                    failures are never silently swallowed
``PL007``   request-path-hygiene    no untimed waits in ``serving/``
``PL008``   unguarded-shared-state  every shared-attr access holds its
                                    declared/inferred guard (whole-package
                                    pass; ``# photon: guarded-by(...)``)
``PL009``   lock-order-inversion    acyclic lock-acquisition order across
                                    modules — NEVER baseline-able
``PL010``   atomicity-hygiene       no stale check-then-act across a lock
                                    release; no callbacks/blocking/foreign
                                    locks inside Condition-backed sections
``PL011``   mesh-axis-discipline    axis names reference the mesh constants;
                                    every jit/shard_map entry point carries a
                                    cross-checked ``# photon: sharding(...)``
                                    contract (the SHARDING.md inventory)
``PL012``   sharded-bank-host-      no host/replicated materialization of an
            gather                  entity-/feature-sharded bank outside a
                                    declared export/checkpoint scope — NEVER
                                    baseline-able
``PL013``   reduction-completeness  shard_map bodies psum what their out_specs
                                    claim replicated, only over sharded axes
``PL014``   donation-hygiene        donated arguments are dead after the
                                    donating call
``PL015``   unordered-iteration-    set/listdir/glob iteration order never
            to-artifact             reaches a serialization or digest sink
                                    without ``sorted()``
``PL016``   ambient-entropy-in-     clocks/pids/uuids/``hash()`` never reach
            artifact                signatures, manifests, cache keys or wire
                                    payloads undeclared
                                    (``# photon: entropy(<reason>)``) — NEVER
                                    baseline-able
``PL017``   float-accumulation-     host-side ``sum()``/``fsum``/``np.sum``
            order                   over unordered collections iterates a
                                    declared canonical order
``PL018``   wire-contract-          every ``MSG_*`` type has encoder, decoder,
            completeness            dispatch and fuzz-corpus entry; every
                                    ``WireError`` kind a frontend mapping —
                                    NEVER baseline-able
==========  ======================  ===========================================

PL008-PL010 are the concurrency pass (two-pass whole-package analysis:
class guard maps, the cross-module lock graph, thread-escape); their
runtime twin is the deterministic interleaving harness in
``photon_ml_tpu/testing/interleave.py``. PL011-PL014 are the SPMD pass
(``lint/spmd.py``): axis-constant resolution, the mesh entry-point
inventory behind the generated ``SHARDING.md``
(``lint/sharding_contracts.py``), sharded-bank taint and per-body
reduction dataflow. PL015-PL018 are the determinism pass
(``lint/determinism.py``): unordered/entropy taint into artifact sinks,
the ``# photon: entropy(<reason>)`` declaration grammar, and the
machine-built wire-message inventory; their runtime twin is the
hash-seed twin-run harness in ``photon_ml_tpu/testing/determinism.py``.
Opt out per-invocation with ``--no-concurrency`` / ``--no-spmd`` /
``--no-determinism``.

Usage::

    python -m photon_ml_tpu.lint photon_ml_tpu bench.py
    python -m photon_ml_tpu.lint --json photon_ml_tpu
    dev-scripts/lint.sh            # photon-lint + ruff (when installed)

Suppress a single line with ``# photon: allow(<rule>)`` (id or slug);
grandfathered sites live in the checked-in ``.photon-lint-baseline.json``
(regenerate with ``--write-baseline``). ``tests/test_lint_clean.py`` runs
the analyzer over the whole package under tier-1, so a new raw readback
fails CI instead of landing silently.
"""

from photon_ml_tpu.lint.core import (
    FileContext,
    PackageContext,
    PackageRule,
    PACKAGE_RULES,
    Report,
    Rule,
    RULES,
    Violation,
    all_rules,
    analyze_paths,
    analyze_source,
    iter_python_files,
    register,
    register_package,
)
from photon_ml_tpu.lint.baseline import (
    BaselineRefused,
    apply_baseline,
    baseline_key,
    load_baseline,
    write_baseline,
)

__all__ = [
    "FileContext",
    "PackageContext",
    "PackageRule",
    "PACKAGE_RULES",
    "Report",
    "Rule",
    "RULES",
    "Violation",
    "all_rules",
    "analyze_paths",
    "analyze_source",
    "iter_python_files",
    "register",
    "register_package",
    "BaselineRefused",
    "apply_baseline",
    "baseline_key",
    "load_baseline",
    "write_baseline",
]
