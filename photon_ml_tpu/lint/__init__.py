"""photon-lint: AST-based static checks for the JAX hot-path invariants.

PRs 1-3 established performance invariants that only runtime tests
enforced: every device->host fetch routes through the counted
``parallel/overlap.py`` seam, every spill scratch dir registers for the
atexit sweep, every ``submit_io`` is drained before exit. This package
makes those invariants machine-checked at review time — a
project-specific analyzer over the stdlib ``ast``, no new runtime deps.

Rules (see ``photon_ml_tpu/lint/rules/``):

==========  ===================  ==============================================
id          slug                 protects
==========  ===================  ==============================================
``PL001``   hidden-host-sync     all device->host fetches go through the
                                 counted ``overlap.device_get`` seam
``PL002``   recompile-hazard     no jit-of-lambda / jit-in-loop / unhashable
                                 static_argnums (silent recompilations)
``PL003``   tracer-leak          no tracers stored on ``self``/globals or
                                 Python-branched inside jitted bodies
``PL004``   spill-hygiene        scratch dirs under ``io/`` / GAME streaming
                                 register for the atexit sweep
``PL005``   undrained-io         ``submit_io`` scopes reach a ``drain_io``
==========  ===================  ==============================================

Usage::

    python -m photon_ml_tpu.lint photon_ml_tpu bench.py
    python -m photon_ml_tpu.lint --json photon_ml_tpu
    dev-scripts/lint.sh            # photon-lint + ruff (when installed)

Suppress a single line with ``# photon: allow(<rule>)`` (id or slug);
grandfathered sites live in the checked-in ``.photon-lint-baseline.json``
(regenerate with ``--write-baseline``). ``tests/test_lint_clean.py`` runs
the analyzer over the whole package under tier-1, so a new raw readback
fails CI instead of landing silently.
"""

from photon_ml_tpu.lint.core import (
    FileContext,
    Report,
    Rule,
    RULES,
    Violation,
    analyze_paths,
    analyze_source,
    iter_python_files,
    register,
)
from photon_ml_tpu.lint.baseline import (
    apply_baseline,
    baseline_key,
    load_baseline,
    write_baseline,
)

__all__ = [
    "FileContext",
    "Report",
    "Rule",
    "RULES",
    "Violation",
    "analyze_paths",
    "analyze_source",
    "iter_python_files",
    "register",
    "apply_baseline",
    "baseline_key",
    "load_baseline",
    "write_baseline",
]
