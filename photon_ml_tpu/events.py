"""Typed training events + emitter.

Reference: photon-ml .../event/Event.scala:27-64 (PhotonSetupEvent,
TrainingStartEvent, TrainingFinishEvent, PhotonOptimizationLogEvent),
EventEmitter.scala:88-130 (registration + synchronized sendEvent),
EventListener.scala; listeners injected by class name via
``--event-listeners`` (Driver.scala:110-119).
"""

from __future__ import annotations

import importlib
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List


@dataclass(frozen=True)
class Event:
    pass


@dataclass(frozen=True)
class PhotonSetupEvent(Event):
    params: Dict[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class TrainingStartEvent(Event):
    job_name: str = ""


@dataclass(frozen=True)
class TrainingFinishEvent(Event):
    job_name: str = ""


@dataclass(frozen=True)
class PhotonOptimizationLogEvent(Event):
    reg_weight: float = 0.0
    iterations: int = 0
    convergence_reason: str = ""
    final_value: float = 0.0
    metrics: Dict[str, float] = field(default_factory=dict)


@dataclass(frozen=True)
class ScheduleCacheEvent(Event):
    """Tile-schedule cache outcome for one training stage: hit/miss/build
    counters plus the host-side build/load/store timers
    (ops/schedule_cache.py). Emitted by the drivers after training so
    listeners can track cold-vs-warm schedule cost per run."""

    stats: Dict[str, float] = field(default_factory=dict)


class EventListener:
    def on_event(self, event: Event) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class EventEmitter:
    """Thread-safe fan-out of events to registered listeners."""

    def __init__(self):
        self._listeners: List[EventListener] = []
        self._lock = threading.Lock()

    def register(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def register_by_name(self, class_path: str) -> None:
        """Instantiate `pkg.module.Class` by name (--event-listeners)."""
        module_name, _, cls_name = class_path.rpartition(".")
        cls = getattr(importlib.import_module(module_name), cls_name)
        self.register(cls())

    def send(self, event: Event) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for listener in listeners:
            listener.on_event(event)

    def close(self) -> None:
        with self._lock:
            listeners = list(self._listeners)
            self._listeners.clear()
        for listener in listeners:
            listener.close()
