"""Compat shim: the typed training events + emitter moved into the
unified telemetry plane as :mod:`photon_ml_tpu.obs.events` (ISSUE 13 —
one structured-event path: ``EventEmitter.send`` now also files every
event into the process flight recorder). Existing emit sites and tests
import from here unchanged."""

from photon_ml_tpu.obs.events import (  # noqa: F401
    Event,
    EventEmitter,
    EventListener,
    PhotonOptimizationLogEvent,
    PhotonSetupEvent,
    ScheduleCacheEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)

__all__ = [
    "Event",
    "PhotonSetupEvent",
    "TrainingStartEvent",
    "TrainingFinishEvent",
    "PhotonOptimizationLogEvent",
    "ScheduleCacheEvent",
    "EventListener",
    "EventEmitter",
]
