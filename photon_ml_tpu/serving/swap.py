"""Zero-copy hot swap: stage a new model generation under live traffic,
flip atomically between micro-batches, roll back on a poisoned artifact.

The decoupled-acting/learning contract (Podracer, PAPERS.md): model
publication must never stall the serving loop. Staging therefore does
ALL the slow work — artifact load (behind the ``serving.model_load``
reliability seam), dense bank assembly, device placement, AOT program
warmup for every ladder shape, and (on the donating path) the refresh
program's own compile — while the previous generation keeps serving.
The flip itself is one reference assignment under the manager lock; the
batcher reads the reference once per dispatch, so a generation change
lands exactly on a micro-batch boundary.

"Zero-copy" is literal on two axes:

- the flip copies nothing — generation N+1 is already device-resident;
- when the new generation's signature matches the old one's (same
  coordinate shapes — the overwhelmingly common retrain case, which the
  entity-axis padding in `model_bank` is designed to preserve), staging
  routes the new values through a DONATING refresh program: XLA reuses
  generation N's buffers for generation N+1's outputs, so steady-state
  device memory holds one bank (both exist only transiently while the
  refresh consumes the old one). The refresh is a bitwise move
  (``select`` on a constant predicate), pinned by the swap parity test.

Entity-set changes are safe under a donating swap: requests carry RAW
entity ids and the batcher resolves them to bank rows per dispatch
(serving/batcher.py), so a generation whose entity set differs inside
the same padded bucket never scores stale rows.

A corrupt artifact (decode failure or an injected ``CORRUPT`` at the
seam) quarantines the model directory to ``*.corrupt`` via the
reliability layer and ROLLS BACK: the previous generation keeps
serving, the failure is accounted, and nothing about the request path
changes.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from functools import partial
from typing import Mapping, Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.obs.flight_recorder import flight_recorder
from photon_ml_tpu.serving.model_bank import (
    DEFAULT_ENTITY_PAD,
    ModelBank,
    build_model_bank,
    place_on_device,
)
from photon_ml_tpu.serving.programs import ServingPrograms

__all__ = ["SwapResult", "ServingModel", "SEAM", "load_model_artifact"]

SEAM = "serving.model_load"


def load_model_artifact(model_dir: str):
    """Read a GAME model directory behind the ``serving.model_load``
    seam: transient IO errors retry on the per-seam budget; a corrupt
    artifact quarantines to ``*.corrupt`` and raises (callers with a
    live previous generation catch and roll back instead)."""
    from photon_ml_tpu.reliability import InjectedCorruption, io_call
    from photon_ml_tpu.reliability.retry import quarantine_artifact

    try:
        return io_call(SEAM, _load_model, model_dir, detail=model_dir)
    except (InjectedCorruption, ValueError) as e:
        quarantine_artifact(model_dir, SEAM)
        raise RuntimeError(
            f"model artifact at {model_dir} is corrupt (quarantined): {e}"
        ) from e


# photon: sharding(axes=[], donates=[0])
@partial(jax.jit, donate_argnums=(0,))
def _donating_refresh(old_arrays, new_arrays):
    """Write generation N+1's values into buffers XLA may alias from
    generation N's donated ones. ``where`` on a constant-true predicate
    is a select — the output carries ``new``'s exact bits (a plain
    ``new + 0.0`` would flip -0.0 to +0.0), while consuming ``old`` so
    its buffers are donatable."""
    return jax.tree_util.tree_map(
        lambda o, n: jnp.where(jnp.bool_(True), n, o),
        old_arrays,
        new_arrays,
    )


_REFRESH_LOCK = threading.Lock()
_REFRESH_CACHE: dict = {}


def _refresh_executable(arrays):
    """AOT ``lower().compile()`` of the donating refresh for these
    array shapes, cached by tree/shape/dtype signature. Staging calls
    this BEFORE taking ``dispatch_lock``, so the first donating swap
    pays its compile off the request path and the flip itself stays an
    all-cache-hit device-to-device select."""
    leaves, treedef = jax.tree_util.tree_flatten(arrays)
    key = (
        treedef,
        tuple((tuple(a.shape), jnp.dtype(a.dtype).str) for a in leaves),
    )
    with _REFRESH_LOCK:
        exe = _REFRESH_CACHE.get(key)
    if exe is None:
        structs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), arrays
        )
        exe = _donating_refresh.lower(structs, structs).compile()
        with _REFRESH_LOCK:
            _REFRESH_CACHE[key] = exe
    return exe


@dataclass
class SwapResult:
    ok: bool
    generation: int
    donated: bool = False
    recompiled_programs: int = 0
    rolled_back: bool = False
    quarantined: Optional[str] = None
    error: str = ""


class ServingModel:
    """Generation manager: owns the current ModelBank, the program
    cache, and the stage/flip/rollback protocol."""

    def __init__(
        self,
        bank: ModelBank,
        programs: Optional[ServingPrograms] = None,
        *,
        partial: bool = False,
        entity_shard=None,
    ):
        from photon_ml_tpu import ownership

        # partial=True is SHARD-SERVER mode: the AOT ladder holds the
        # scatter/gather program family (fe + per-coordinate terms)
        # instead of full margins, and entity_shard=(s, n) makes every
        # staged generation load the same 1/n slice of the entity axis
        # this server owns (the shared ownership rule) — a swap can
        # never change which rows this host answers for.
        self.partial = bool(partial)
        self.entity_shard = ownership.validate_entity_shard(entity_shard)
        self._lock = threading.Lock()
        # Serializes whole stage/flip protocols. Swaps arrive from more
        # than one thread (registry watcher promote, operator rollback
        # on a connection thread, driver --swap-after-requests): two
        # concurrent _flips would both read the same `prev`, both mint
        # generation prev+1, and on the donated path BOTH would hand
        # prev's buffers to the refresh program — a use-after-donate.
        # Staging is slow on purpose (artifact load, program warmup);
        # holding this lock across it only serializes swaps, never the
        # request path (dispatch takes dispatch_lock, not this).
        self._stage_lock = threading.Lock()
        self._bank = bank
        self.programs = programs or ServingPrograms()
        self.programs.ensure_compiled(bank, partial=self.partial)
        self.swap_history = []
        # a bank staged by prepare_swap, waiting for commit_prepared
        # (the router-coordinated two-step flip); read/written only
        # under _stage_lock
        self._prepared: Optional[ModelBank] = None
        # Mutual exclusion between a DONATING flip and an in-flight
        # dispatch: donation invalidates generation N's device buffers,
        # so the refresh must not run while a dispatch is executing
        # against them. MicroBatcher picks this lock up automatically
        # from a bound `ServingModel.current` bank_ref and holds it for
        # the duration of each dispatch; the donated flip takes it for
        # the (sub-ms, all-cache-hit) refresh — which is exactly the
        # "flipped atomically between requests" contract.
        self.dispatch_lock = threading.Lock()

    # the batcher's bank_ref
    def current(self) -> ModelBank:
        with self._lock:
            return self._bank

    @property
    def generation(self) -> int:
        return self.current().generation

    def ready(self) -> bool:
        """Readiness: the current bank is live (not retired) and every
        ladder rung holds a precompiled executable for its spec — a
        ready service can answer ANY admissible batch shape without a
        hot-path compile. Distinct from liveness (the dispatcher
        heartbeat, owned by the batcher): a service can be alive but
        not yet ready (mid-staging) and must not take traffic."""
        bank = self.current()
        if bank.retired:
            return False
        return all(
            self.programs.executable(bank.spec, B, partial=self.partial)
            is not None
            for B in self.programs.ladder
        )

    def quarantine_re(self, re_type: str) -> None:
        """Operator/fault-path entry: mark one RE coordinate of the
        CURRENT generation unusable. Requests touching it score FE-only
        (degraded), everything else is unaffected — the graceful-
        degradation contract, scoped to this generation (the next swap
        installs a clean bank)."""
        self.current().quarantine_re(re_type)

    @classmethod
    def load(
        cls,
        model_dir: str,
        index_maps: Mapping[str, object],
        shard_widths: Mapping[str, int],
        *,
        ladder=None,
        entity_pad_to: int = DEFAULT_ENTITY_PAD,
        native_index_threshold: Optional[int] = None,
        model_id: str = "",
        partial: bool = False,
        entity_shard=None,
    ) -> "ServingModel":
        """Initial load: the artifact read runs behind the
        ``serving.model_load`` seam (transient IO errors retry on the
        per-seam budget); a corrupt artifact quarantines and raises —
        with no previous generation there is nothing to roll back to."""
        loaded = load_model_artifact(model_dir)
        bank = build_model_bank(
            loaded,
            index_maps,
            shard_widths,
            generation=1,
            entity_pad_to=entity_pad_to,
            native_index_threshold=native_index_threshold,
            model_id=model_id,
            entity_shard=entity_shard,
        )
        programs = (
            ServingPrograms(ladder) if ladder is not None else None
        )
        return cls(
            bank, programs, partial=partial, entity_shard=entity_shard
        )

    def stage_and_swap(
        self,
        model_dir: str,
        *,
        entity_pad_to: int = DEFAULT_ENTITY_PAD,
        native_index_threshold: Optional[int] = None,
        model_id: str = "",
    ) -> SwapResult:
        """Load generation N+1, stage it on device, warm its programs,
        flip. Never raises on a bad artifact: quarantines + rolls back,
        returning the failure in the SwapResult."""
        from photon_ml_tpu.reliability import (
            InjectedCorruption,
            SeamFailure,
            io_call,
        )
        from photon_ml_tpu.reliability.retry import quarantine_artifact

        # one swap protocol at a time: `prev` read, staging and the
        # flip happen under _stage_lock so racing swap requests (the
        # watcher's promote vs an operator rollback) serialize instead
        # of both staging against the same predecessor
        with self._stage_lock:
            prev = self.current()
            try:
                loaded = io_call(
                    SEAM, _load_model, model_dir, detail=model_dir
                )
            except (InjectedCorruption, ValueError) as e:
                q = quarantine_artifact(model_dir, SEAM)
                result = SwapResult(
                    ok=False,
                    generation=prev.generation,
                    rolled_back=True,
                    quarantined=q,
                    error=str(e),
                )
                self.swap_history.append(result)
                flight_recorder().record(
                    "swap.abort",
                    error=result.error,
                    rolled_back=result.rolled_back,
                    quarantined=result.quarantined,
                )
                return result
            except SeamFailure as e:
                result = SwapResult(
                    ok=False,
                    generation=prev.generation,
                    rolled_back=True,
                    error=str(e),
                )
                self.swap_history.append(result)
                flight_recorder().record(
                    "swap.abort",
                    error=result.error,
                    rolled_back=result.rolled_back,
                    quarantined=result.quarantined,
                )
                return result

            staged = build_model_bank(
                loaded,
                index_maps=prev.index_maps,
                shard_widths=prev.shard_widths,
                generation=prev.generation + 1,
                entity_pad_to=entity_pad_to,
                native_index_threshold=native_index_threshold,
                device=False,  # host arrays: placement happens below
                model_id=model_id,
                entity_shard=self.entity_shard,
            )
            return self._flip(staged)

    def swap_to_bank(self, staged: ModelBank) -> SwapResult:
        """Flip to an already-built bank (in-memory publication path —
        e.g. a co-located trainer handing over arrays directly)."""
        with self._stage_lock:
            prev = self.current()
            staged.generation = prev.generation + 1
            return self._flip(staged)

    # -- two-step flip (router-coordinated swaps) ---------------------------

    def prepare_swap(
        self,
        model_dir: str,
        *,
        entity_pad_to: int = DEFAULT_ENTITY_PAD,
        native_index_threshold: Optional[int] = None,
        model_id: str = "",
    ) -> SwapResult:
        """Step 1 of the router-coordinated two-step flip: load + build
        the next generation's bank and warm its programs, but DO NOT
        serve it. The routing tier stages on every shard-server first
        and only commits once ALL of them staged OK — so a fleet can
        never serve a mixed-generation gather because one shard's
        artifact was corrupt. A failed stage quarantines/rolls back
        exactly like :meth:`stage_and_swap`; a successful one parks the
        bank for :meth:`commit_prepared` (re-preparing replaces it)."""
        from photon_ml_tpu.reliability import (
            InjectedCorruption,
            SeamFailure,
            io_call,
        )
        from photon_ml_tpu.reliability.retry import quarantine_artifact

        with self._stage_lock:
            prev = self.current()
            try:
                loaded = io_call(
                    SEAM, _load_model, model_dir, detail=model_dir
                )
            except (InjectedCorruption, ValueError) as e:
                q = quarantine_artifact(model_dir, SEAM)
                result = SwapResult(
                    ok=False,
                    generation=prev.generation,
                    rolled_back=True,
                    quarantined=q,
                    error=str(e),
                )
                self.swap_history.append(result)
                flight_recorder().record(
                    "swap.abort",
                    error=result.error,
                    rolled_back=result.rolled_back,
                    quarantined=result.quarantined,
                )
                return result
            except SeamFailure as e:
                result = SwapResult(
                    ok=False,
                    generation=prev.generation,
                    rolled_back=True,
                    error=str(e),
                )
                self.swap_history.append(result)
                flight_recorder().record(
                    "swap.abort",
                    error=result.error,
                    rolled_back=result.rolled_back,
                    quarantined=result.quarantined,
                )
                return result
            staged = build_model_bank(
                loaded,
                index_maps=prev.index_maps,
                shard_widths=prev.shard_widths,
                generation=prev.generation + 1,
                entity_pad_to=entity_pad_to,
                native_index_threshold=native_index_threshold,
                device=False,
                model_id=model_id,
                entity_shard=self.entity_shard,
            )
            return self._park_prepared(staged)

    def prepare_swap_bank(self, staged: ModelBank) -> SwapResult:
        """Step 1 over an already-built host bank (in-memory publication
        / synthetic fleets)."""
        with self._stage_lock:
            return self._park_prepared(staged)

    def _park_prepared(self, staged: ModelBank) -> SwapResult:  # photon: guarded-by(_stage_lock)
        # ALL the slow work happens now, while the previous generation
        # keeps serving: program warmup for the staged spec, and (on
        # the donating path) the refresh executable's own compile. The
        # later commit is the same sub-ms flip stage_and_swap performs.
        prev = self.current()
        staged.generation = prev.generation + 1
        # device placement happens NOW too (idempotent for _flip's own
        # pass): commit must be the sub-ms flip, not a host->device copy
        staged.arrays = place_on_device(staged.arrays)
        recompiled = self.programs.ensure_compiled(
            staged, partial=self.partial
        )
        if staged.spec == prev.spec:
            _refresh_executable(staged.arrays)
        self._prepared = staged
        flight_recorder().record(
            "swap.stage", generation=staged.generation,
            donated=staged.spec == prev.spec,
        )
        return SwapResult(
            ok=True,
            generation=staged.generation,
            donated=staged.spec == prev.spec,
            recompiled_programs=recompiled,
        )

    def commit_prepared(self) -> SwapResult:
        """Step 2: flip to the bank :meth:`prepare_swap` parked. With
        nothing prepared (or after :meth:`abort_prepared`) this is a
        named failure, never a silent no-op — the router treats it as
        that shard refusing the flip."""
        with self._stage_lock:
            staged = self._prepared
            self._prepared = None
            prev = self.current()
            if staged is None:
                result = SwapResult(
                    ok=False,
                    generation=prev.generation,
                    error="no prepared generation to commit",
                )
                self.swap_history.append(result)
                flight_recorder().record(
                    "swap.abort",
                    error=result.error,
                    rolled_back=result.rolled_back,
                    quarantined=result.quarantined,
                )
                return result
            # re-number against the CURRENT generation: another swap
            # may have landed between prepare and commit
            staged.generation = prev.generation + 1
            return self._flip(staged)

    def abort_prepared(self) -> bool:
        """Drop a parked generation (router abort after a peer shard
        failed its stage). Returns whether anything was parked."""
        with self._stage_lock:
            had = self._prepared is not None
            self._prepared = None
        if had:
            flight_recorder().record("swap.abort", reason="router abort")
        return had

    def _flip(self, staged: ModelBank) -> SwapResult:  # photon: guarded-by(_stage_lock)
        prev = self.current()
        donated = staged.spec == prev.spec
        if donated:
            # same shapes: refresh in place — the old generation's
            # buffers are donated to the new one's outputs. ALL slow
            # work happens before the lock: program warmup (all cache
            # hits when the spec is warm), host->device placement of the
            # new values, and the refresh program's own compile
            # (_refresh_executable, cached across swaps). Only the
            # refresh call + reference flip run under dispatch_lock —
            # exclusive with dispatch, because a batch mid-execution
            # must not have its bank donated out from under it.
            recompiled = self.programs.ensure_compiled(
                staged, partial=self.partial
            )
            staged.arrays = place_on_device(staged.arrays)
            refresh = _refresh_executable(staged.arrays)
            with self.dispatch_lock:
                staged.arrays = refresh(prev.arrays, staged.arrays)
                with self._lock:
                    self._bank = staged
                    prev.retired = True
        else:
            # changed shapes: stage fresh buffers (both generations
            # coexist briefly); prev stays valid, no exclusion needed.
            # Every ladder shape compiles BEFORE the flip: a swap can
            # slow staging, never the first post-swap request.
            staged.arrays = place_on_device(staged.arrays)
            recompiled = self.programs.ensure_compiled(
                staged, partial=self.partial
            )
            with self._lock:
                self._bank = staged
                prev.retired = True
        result = SwapResult(
            ok=True,
            generation=staged.generation,
            donated=donated,
            recompiled_programs=recompiled,
        )
        self.swap_history.append(result)
        flight_recorder().record(
            "swap.commit", generation=staged.generation, donated=donated,
        )
        return result


def _load_model(model_dir: str):
    from photon_ml_tpu.game.model_io import load_game_model

    return load_game_model(model_dir)
