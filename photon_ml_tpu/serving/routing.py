"""Scatter/gather routing tier: planet-scale serving over entity-sharded
shard-servers.

The Podracer split (PAPERS.md) applied to GAME serving: THIN routers in
front of N device-resident scorers, where shard-server ``s`` holds
exactly the ``1/N`` slice of every random-effect bank that the shared
ownership rule (:mod:`photon_ml_tpu.ownership` — the same ``e % N`` the
pod trainer places banks with) assigns to it. The router holds no
coefficients at all: just the model's entity-id -> code indexes (the
O(1) :class:`~.model_bank.EntityRowIndex` machinery, mmap-backed above
100k ids) and persistent connections to the fleet. Per request it:

1. resolves each raw entity id to its code and its code to its OWNING
   shard (``ownership.owner_of``) — the scatter set is the owners, not
   the fleet, so per-request work does not grow with N;
2. scatters one partial-score sub-request per owning shard (plus one
   designated shard for the fixed-effect half — every shard holds the
   full FE banks, so any healthy shard can provide it, bitwise);
3. gathers the per-coordinate terms and re-sums them HOST-SIDE in
   float32, in the bank spec's exact accumulation order, finishing
   with the request's offset — each step an exactly-rounded IEEE add,
   which is what makes the routed margin **bitwise-equal** to the
   single-server serving path and the batch scorer (the DrJAX
   map/reduce framing: shard-local map, order-pinned reduce).

**Degradation is per-shard, never an outage.** Each shard has a health
window + circuit breaker; a dead, shedding or deadline-blown shard
yields FE-only terms for *its* entities only, flagged ``degraded`` —
exactly the unknown-entity zero the single-server path adds — while
every other shard's terms stay exact. Sub-requests run under the
request's own deadline budget with a hedged-or-shed policy: a slow
shard is hedged once on a fresh connection inside the remaining
budget, then shed (degraded) — the p99 does not ride the slowest
shard. Only a fleet with NO healthy shard refuses outright
(:class:`~.admission.NoShardAvailable` — without FE there is nothing
left to degrade to).

**Hot-entity cache.** Head-skewed (zipf) traffic re-scores the same
few entities with the same features; the router absorbs it with a
bounded LRU over ``(generation, slot, blake2b(entity, features))`` ->
term. Keys carry the routing generation, so a cached gen-N partial can
never serve under gen-N+1 by construction, and the whole map is purged
atomically at swap-commit. Only deterministic paths populate it
(non-degraded responses at the current generation), so a cache hit is
bitwise the cold path — pinned by tests.

**Two-step generation flip.** Shard generations must advance in
lockstep (a margin summed from gen-N and gen-N+1 terms matches neither
model), so the router coordinates swaps: phase 1 stages the new
generation on EVERY shard (slow work under live traffic; any failure
aborts the others and nobody flips), phase 2 commits shard by shard
(each a sub-ms flip), then the router bumps its own generation and
purges the cache under one lock. In-flight gathers that straddle the
commit wave detect mixed generations and re-scatter once against the
settled fleet.
"""

from __future__ import annotations

import hashlib
import json
import socket
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout
from dataclasses import dataclass
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu import ownership
from photon_ml_tpu.obs.flight_recorder import flight_recorder
from photon_ml_tpu.obs.trace import (
    PARENT_KEY,
    TRACE_KEY,
    start_span,
    wire_context,
)
from photon_ml_tpu.serving import wire as wirefmt
from photon_ml_tpu.serving.admission import NoShardAvailable, ScoreOutcome
from photon_ml_tpu.serving.model_bank import EntityRowIndex

__all__ = [
    "RoutedScore",
    "RoutingPolicy",
    "ShardHealth",
    "HotEntityCache",
    "TcpShardTransport",
    "RouterMetrics",
    "ShardRouter",
]

# Poll beat for every blocking wait on the router's paths (PL007
# request-path hygiene: no untimed waits anywhere).
POLL_S = 0.25
# Default sub-request budget when the request carries no deadline.
DEFAULT_SUBREQUEST_TIMEOUT_S = 2.0
# Control-plane ops (topology, stage/commit) may do artifact IO.
CONTROL_TIMEOUT_S = 120.0
# The fe slot's cache key name (no spec coordinate is ever named this:
# coordinate names come from artifacts, this is not a legal one).
FE_SLOT = "__fe__"


class RoutedScore(ScoreOutcome):
    """A routed margin: still a float (the bitwise parity tests compare
    it raw), still carrying ``degraded``/``generation``, plus the
    routing annotations: how many shards the request fanned out to
    (0 = served entirely from the hot-entity cache), whether every slot
    came from cache, and which shards degraded to FE-only."""

    __slots__ = ("fanout", "cache_hit", "degraded_shards", "fe_shard")

    def __new__(
        cls,
        value: float,
        *,
        degraded: bool = False,
        generation: int = 0,
        fanout: int = 0,
        cache_hit: bool = False,
        degraded_shards: Tuple[int, ...] = (),
        fe_shard: Optional[int] = None,
    ) -> "RoutedScore":
        self = super().__new__(
            cls, value, degraded=degraded, generation=generation
        )
        self.fanout = int(fanout)
        self.cache_hit = bool(cache_hit)
        self.degraded_shards = tuple(degraded_shards)
        # which shard provided the fixed-effect half (None = the hot
        # cache did): the fleet-conservation attribution key — every
        # wire-served request is attributed to exactly ONE shard
        self.fe_shard = None if fe_shard is None else int(fe_shard)
        return self


@dataclass(frozen=True)
class RoutingPolicy:
    """The hedged-or-shed knobs.

    ``hedge_frac`` of the remaining budget is given to the first
    attempt; if it times out and ``hedge`` is on, ONE hedge goes out on
    a fresh connection for the remainder — tail latency from a slow
    connection costs one retry, never the whole budget twice. Shards
    whose circuit is open (``fail_threshold`` consecutive failures) are
    skipped outright for ``cooldown_s``, then probed half-open.
    """

    hedge: bool = True
    hedge_frac: float = 0.5
    subrequest_timeout_s: float = DEFAULT_SUBREQUEST_TIMEOUT_S
    fail_threshold: int = 3
    cooldown_s: float = 2.0
    health_window: int = 64


class ShardHealth:
    """Per-shard health: a sliding outcome window for observability and
    a consecutive-failure circuit breaker for routing decisions.

    ``allow()`` is consulted before every sub-request: CLOSED (healthy)
    admits; OPEN (tripped) refuses until ``cooldown_s`` elapsed, then
    admits probes (half-open) — a recovered shard heals itself on the
    first success, a still-dead one re-opens on the probe's failure.
    """

    def __init__(
        self, shard_index: int, policy: RoutingPolicy, *, recorder=None
    ):
        self.shard_index = int(shard_index)
        self._policy = policy
        self._lock = threading.Lock()
        self._window: List[int] = []
        self._consecutive_failures = 0
        self._open_until = 0.0
        self._failures_total = 0
        self._successes_total = 0
        self._flight = (
            recorder if recorder is not None else flight_recorder()
        )

    def note(self, ok: bool) -> None:
        transition = None
        with self._lock:
            was_open = (
                self._consecutive_failures >= self._policy.fail_threshold
            )
            self._window.append(0 if ok else 1)
            if len(self._window) > self._policy.health_window:
                self._window.pop(0)
            if ok:
                self._consecutive_failures = 0
                self._open_until = 0.0
                self._successes_total += 1
                if was_open:
                    transition = "close"
            else:
                self._consecutive_failures += 1
                self._failures_total += 1
                if self._consecutive_failures >= self._policy.fail_threshold:
                    self._open_until = (
                        time.monotonic() + self._policy.cooldown_s
                    )
                    if not was_open:
                        transition = "open"
        if transition is not None:
            # breaker transitions are flight-recorder events (recorded
            # OUTSIDE this health window's lock — the recorder has its
            # own); per-call outcomes stay counters, not events
            self._flight.record(
                f"circuit.{transition}", shard=self.shard_index
            )

    def allow(self) -> bool:
        with self._lock:
            if self._consecutive_failures < self._policy.fail_threshold:
                return True
            # open: admit again once the cooldown passed (half-open
            # probe; a failure re-arms the cooldown via note())
            return time.monotonic() >= self._open_until

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            n = len(self._window)
            return {
                "shard": self.shard_index,
                "healthy": (
                    self._consecutive_failures < self._policy.fail_threshold
                    or time.monotonic() >= self._open_until
                ),
                "consecutive_failures": self._consecutive_failures,
                "window_unhealthy_rate": (
                    round(sum(self._window) / n, 4) if n else 0.0
                ),
                "successes": self._successes_total,
                "failures": self._failures_total,
            }


class HotEntityCache:
    """Bounded LRU over ``(generation, slot, digest)`` -> float32 term.

    ``slot`` is a spec coordinate name (or :data:`FE_SLOT`), ``digest``
    a blake2b over the entity id + the exact feature payload the term
    depends on — so a hit is the deterministic replay of the cold
    path's float, bit for bit. Generation lives IN the key: a stale
    generation's entry can never answer a lookup at the live one, and
    :meth:`purge_other_generations` drops the dead weight atomically at
    swap-commit. ``max_entries <= 0`` disables caching entirely."""

    def __init__(self, max_entries: int = 4096):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._map: Dict[tuple, float] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.purged = 0

    @property
    def enabled(self) -> bool:
        return self.max_entries > 0

    def get(self, key: tuple) -> Optional[float]:
        if not self.enabled:
            return None
        with self._lock:
            if key in self._map:
                v = self._map.pop(key)
                self._map[key] = v  # recency touch
                self.hits += 1
                return v
            self.misses += 1
            return None

    def put(self, key: tuple, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._map.pop(key, None)
            while len(self._map) >= self.max_entries:
                self._map.pop(next(iter(self._map)))
                self.evictions += 1
            self._map[key] = float(value)

    def purge_other_generations(self, generation: int) -> int:
        """Drop every entry not keyed to ``generation`` — ONE atomic
        sweep under the lock, called at swap-commit so no reader can
        observe a mix of old and new entries."""
        with self._lock:
            dead = [k for k in self._map if k[0] != generation]
            for k in dead:
                del self._map[k]
            self.purged += len(dead)
            return len(dead)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._map),
                "max_entries": self.max_entries,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "purged": self.purged,
            }


class TransportError(RuntimeError):
    """A sub-request could not complete at the transport level
    (connect/send/receive failure or timeout). The router converts it
    into per-shard degradation, never a request failure."""


class TcpShardTransport:
    """One persistent connection to a shard-server, safe for concurrent
    callers: requests are multiplexed by uid — senders serialize on a
    write lock, a reader thread demuxes responses into per-uid futures.
    A connection-level failure fails every pending future (the router
    then degrades/hedges); the transport is single-use after that (the
    router opens a fresh one).

    ``wire`` picks the protocol for the WHOLE connection: ``"json"``
    is the JSON-lines plane; ``"binary"`` speaks photon-wire frames
    (the shard frontend sniffs our first byte) — score sub-requests
    and partial responses ride raw float buffers, control objects ride
    MSG_JSON frames, and encodes reuse one per-transport buffer.
    """

    def __init__(
        self,
        host: str,
        port: int,
        *,
        connect_timeout_s: float = 5.0,
        wire: str = "json",
        max_frame_bytes: Optional[int] = None,
    ):
        self.host = host
        self.port = int(port)
        self.wire = str(wire)
        if self.wire not in wirefmt.WIRE_PROTOCOLS:
            raise ValueError(
                f"unknown wire protocol {wire!r} "
                f"(know {wirefmt.WIRE_PROTOCOLS})"
            )
        self.max_frame_bytes = wirefmt.resolve_max_frame_bytes(
            max_frame_bytes
        )
        self._sock = socket.create_connection(
            (host, int(port)), timeout=connect_timeout_s
        )
        self._sock.settimeout(POLL_S)
        self._send_lock = threading.Lock()
        # reused per-connection encode buffer; mutated ONLY under
        # _send_lock (the same lock that orders the sendalls)
        self._encode_buf = bytearray()
        self._lock = threading.Lock()  # guards _pending
        self._pending: Dict[str, Future] = {}
        self.unmatched_responses = 0
        self._closed = threading.Event()
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f"photon-router-read-{host}:{port}",
            daemon=True,
        )
        self._reader.start()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def send_request(self, obj: Mapping) -> Future:
        """Ship one request (a JSON line or a binary frame, per the
        connection's protocol); the returned future resolves with the
        response object for ``obj['uid']`` (callers wait with their own
        timeout — PL007)."""
        uid = obj["uid"]
        fut: Future = Future()
        with self._lock:
            if self._closed.is_set():
                raise TransportError(
                    f"connection to {self.host}:{self.port} is closed"
                )
            self._pending[uid] = fut
        try:
            if self.wire == "binary":
                with self._send_lock:
                    buf = self._encode_buf
                    del buf[:]
                    if "op" in obj:
                        # control objects ride MSG_JSON frames — same
                        # framing, no hot-path codec needed
                        wirefmt.append_json(buf, obj)
                    else:
                        wirefmt.append_score_request(buf, obj)
                    self._sock.sendall(buf)
            else:
                data = (json.dumps(obj) + "\n").encode("utf-8")
                with self._send_lock:
                    self._sock.sendall(data)
        except OSError as e:
            with self._lock:
                self._pending.pop(uid, None)
            self._fail_all(e)
            raise TransportError(
                f"send to {self.host}:{self.port} failed: {e}"
            ) from e
        return fut

    def abandon(self, uid: str) -> None:
        """Forget a pending uid (hedged-away / timed-out attempt); its
        late response, if any, is counted unmatched and dropped."""
        with self._lock:
            self._pending.pop(uid, None)

    def request(self, obj: Mapping, timeout_s: float):
        """Send + wait, bounded. Timeout abandons the uid (a late
        response is counted unmatched and dropped)."""
        fut = self.send_request(obj)
        try:
            return fut.result(timeout=max(timeout_s, 0.001))
        except (TimeoutError, _FutureTimeout):
            self.abandon(obj["uid"])
            raise TransportError(
                f"no response from {self.host}:{self.port} within "
                f"{timeout_s * 1e3:.0f}ms"
            ) from None

    def _read_loop(self) -> None:
        if self.wire == "binary":
            self._read_frames()
            return
        buf = b""
        while not self._closed.is_set():
            nl = buf.find(b"\n")
            if nl < 0:
                try:
                    chunk = self._sock.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError as e:
                    self._fail_all(e)
                    return
                if not chunk:
                    self._fail_all(ConnectionError("EOF from shard"))
                    return
                buf += chunk
                continue
            line, buf = buf[:nl], buf[nl + 1:]
            if not line.strip():
                continue
            try:
                resp = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                self.unmatched_responses += 1
                continue
            self._dispatch_response(resp)

    def _read_frames(self) -> None:
        decoder = wirefmt.FrameDecoder(self.max_frame_bytes)
        while not self._closed.is_set():
            try:
                chunk = self._sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError as e:
                self._fail_all(e)
                return
            if not chunk:
                self._fail_all(ConnectionError("EOF from shard"))
                return
            try:
                frames = decoder.feed(chunk)
            except wirefmt.WireError as e:
                # framing lost on a multiplexed connection: nothing
                # downstream is decodable — fail every pending future
                # (the router degrades/hedges per shard, as for EOF)
                self._fail_all(e)
                return
            for mtype, payload in frames:
                # response-side dispatch: only response frame types may
                # resolve pending futures. Without this allowlist a
                # request-type frame (MSG_SCORE_REQUEST) carrying a uid
                # would decode fine and complete a caller's future with
                # a request echo — protocol confusion, not an error.
                if mtype not in (
                    wirefmt.MSG_JSON,
                    wirefmt.MSG_SCORE_RESPONSE,
                    wirefmt.MSG_PARTIAL_RESPONSE,
                    wirefmt.MSG_TRACE_RESPONSE,
                ):
                    self.unmatched_responses += 1
                    continue
                try:
                    resp = wirefmt.decode_message(mtype, payload)
                except wirefmt.WireError:
                    self.unmatched_responses += 1
                    continue
                self._dispatch_response(resp)

    def _dispatch_response(self, resp: Mapping) -> None:
        uid = resp.get("uid")
        with self._lock:
            fut = self._pending.pop(uid, None) if uid else None
        if fut is None:
            # a response for an abandoned/unknown uid (e.g. a
            # hedged-away attempt, or a shard-side READ_FAULT whose
            # uid was lost): counted, dropped — the owning attempt
            # recovers through its own timeout
            self.unmatched_responses += 1
            return
        if not fut.done():
            fut.set_result(resp)

    def _fail_all(self, exc: BaseException) -> None:
        self._closed.set()
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for fut in pending:
            if not fut.done():
                fut.set_exception(
                    TransportError(f"connection failed: {exc}")
                )
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self) -> None:
        self._fail_all(ConnectionError("transport closed"))
        self._reader.join(timeout=2 * POLL_S + 1.0)


class RouterMetrics:
    """Router-side accounting: request outcomes, fan-out, cache and
    health counters, fan-out latency percentiles. Host arithmetic only
    (the router has no device)."""

    def __init__(self, *, max_latency_samples: int = 1 << 18):
        self._lock = threading.Lock()
        self._max_samples = int(max_latency_samples)
        self._lat: List[float] = []
        self._stride = 1
        self._seen = 0
        self._requests = 0
        self._ok = 0
        self._degraded = 0
        self._failed = 0
        self._cache_full_hits = 0
        self._fanout_counts: Dict[int, int] = {}
        self._subrequests = 0
        self._sub_failures: Dict[int, int] = {}
        self._hedges = 0
        self._generation_retries = 0
        self._first_t: Optional[float] = None
        self._last_t: Optional[float] = None
        # live registry mirrors (SLO-engine inputs), bound once by
        # bind_registry before traffic; single-writer plain publishes
        self._reg_total = None  # photon: guarded-by(atomic)
        self._reg_bad = None  # photon: guarded-by(atomic)
        self._reg_latency = None  # photon: guarded-by(atomic)

    def bind_registry(self, registry, *, prefix: str = "router") -> None:
        """Mirror request outcomes into live registry instruments —
        ``<prefix>_requests_total`` / ``<prefix>_bad_total`` counters
        and the ``<prefix>_latency_seconds`` histogram — so SLO specs
        (obs/slo.py) evaluate over the routed plane. Bind BEFORE
        traffic: the mirrors are plain single-writer references read
        bare on the record path."""
        self._reg_total = registry.counter(
            f"{prefix}_requests_total", "routed requests completed"
        )
        self._reg_bad = registry.counter(
            f"{prefix}_bad_total",
            "routed requests that burned error budget "
            "(failed or degraded)",
        )
        self._reg_latency = registry.histogram(
            f"{prefix}_latency_seconds", "routed request latency"
        )

    def record(
        self,
        *,
        ok: bool,
        degraded: bool,
        fanout: int,
        cache_full_hit: bool,
        latency_s: float,
    ) -> None:
        now = time.perf_counter()
        # registry mirrors first, OUTSIDE our lock (each instrument has
        # its own; nesting ours around theirs would add a lock edge the
        # record path does not need)
        total = self._reg_total
        if total is not None:
            total.inc()
            if not ok:
                self._reg_bad.inc(reason="failed")
            elif degraded:
                self._reg_bad.inc(reason="degraded")
            self._reg_latency.observe(latency_s)
        with self._lock:
            self._requests += 1
            self._ok += int(ok and not degraded)
            self._degraded += int(ok and degraded)
            self._failed += int(not ok)
            self._cache_full_hits += int(cache_full_hit)
            self._fanout_counts[fanout] = (
                self._fanout_counts.get(fanout, 0) + 1
            )
            if self._first_t is None:
                self._first_t = now - latency_s
            self._last_t = now
            self._seen += 1
            if (self._seen - 1) % self._stride == 0:
                self._lat.append(latency_s)
                if len(self._lat) >= self._max_samples:
                    self._lat = self._lat[::2]
                    self._stride *= 2

    def record_subrequest(self, shard: int, *, ok: bool) -> None:
        with self._lock:
            self._subrequests += 1
            if not ok:
                self._sub_failures[shard] = (
                    self._sub_failures.get(shard, 0) + 1
                )

    def record_hedge(self) -> None:
        with self._lock:
            self._hedges += 1

    def record_generation_retry(self) -> None:
        with self._lock:
            self._generation_retries += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            lat = np.asarray(self._lat, np.float64)
            elapsed = (
                (self._last_t - self._first_t)
                if self._first_t is not None and self._last_t is not None
                else 0.0
            )
            out: Dict[str, object] = {
                "requests": self._requests,
                "ok": self._ok,
                "degraded": self._degraded,
                "failed": self._failed,
                "qps": (
                    round(self._requests / elapsed, 3)
                    if elapsed > 0 else None
                ),
                "cache_full_hits": self._cache_full_hits,
                "fanout_counts": {
                    str(k): v
                    for k, v in sorted(self._fanout_counts.items())
                },
                "fanout_mean": (
                    round(
                        sum(k * v for k, v in self._fanout_counts.items())
                        / self._requests,
                        4,
                    )
                    if self._requests else None
                ),
                "subrequests": self._subrequests,
                "subrequest_failures": {
                    str(k): v
                    for k, v in sorted(self._sub_failures.items())
                },
                "hedges": self._hedges,
                "generation_retries": self._generation_retries,
            }
            if lat.size:
                out.update({
                    "latency_p50_ms": round(
                        float(np.percentile(lat, 50)) * 1e3, 6
                    ),
                    "latency_p99_ms": round(
                        float(np.percentile(lat, 99)) * 1e3, 6
                    ),
                    "latency_max_ms": round(float(lat.max()) * 1e3, 6),
                })
            return out


def _digest(*parts: object) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    for p in parts:
        h.update(
            p if isinstance(p, bytes)
            else json.dumps(p, sort_keys=False).encode("utf-8")
        )
        h.update(b"\x00")
    return h.digest()


class ShardRouter:
    """The scatter/gather tier over one shard-server fleet.

    ``transport_factory(shard_index)`` opens a connection to shard
    ``i`` (defaults to :class:`TcpShardTransport` over ``addresses``);
    tests inject in-process fakes, which also makes the whole
    fan-out/cache/swap plane schedulable under the interleaving
    harness. ``entity_ids`` maps each random-effect id type to the
    model's FULL sorted entity-id list — the router's only model state:
    an id's position is its code, its code's owner is the shared rule.

    ``score_record`` is thread-safe (open-loop drivers call it from
    many submitter threads); swaps serialize on their own lock.
    """

    def __init__(
        self,
        addresses: Sequence[Tuple[str, int]] = (),
        *,
        entity_ids: Mapping[str, Sequence[str]],
        shard_configs=None,
        transport_factory: Optional[Callable[[int], object]] = None,
        num_shards: Optional[int] = None,
        policy: Optional[RoutingPolicy] = None,
        cache_entries: int = 4096,
        metrics: Optional[RouterMetrics] = None,
        native_index_threshold: Optional[int] = None,
        recorder=None,
        wire: str = "json",
    ):
        self.wire = str(wire)
        if self.wire not in ("json", "binary", "auto"):
            raise ValueError(
                f"unknown wire mode {wire!r} (json | binary | auto)"
            )
        # the NEGOTIATED data-plane protocol: starts json, settled at
        # connect() from the fleet's topology advertisements.
        # single-writer atomic publish — connect() is the only writer
        # (one plain assignment, before it marks the router connected)
        self._data_wire = "json"  # photon: guarded-by(atomic)
        if transport_factory is None:
            if not addresses:
                raise ValueError(
                    "ShardRouter needs addresses or a transport_factory"
                )
            addrs = [(h, int(p)) for h, p in addresses]

            def transport_factory(i, _addrs=addrs):
                # data plane: reads the negotiated protocol at BUILD
                # time, so transports (re)opened after connect() speak
                # whatever the fleet agreed on
                return TcpShardTransport(*_addrs[i], wire=self._data_wire)

            def control_factory(i, _addrs=addrs):
                # control plane: always fresh JSON connections — swap
                # staging and topology discovery predate (and outlive)
                # any data-plane negotiation
                return TcpShardTransport(*_addrs[i])

            self.num_shards = len(addrs)
        else:
            control_factory = transport_factory
            self.num_shards = (
                int(num_shards)
                if num_shards is not None
                else (len(addresses) if addresses else None)
            )
        self._transport_factory = transport_factory
        self._control_factory = control_factory
        self.policy = policy or RoutingPolicy()
        self.metrics = metrics or RouterMetrics()
        # the router's conservation ledger (obs/flight_recorder.py):
        # every admitted request reaches exactly one ATTRIBUTED
        # terminal — shard:<i> (wire-served, keyed by the FE provider),
        # cache (zero fan-out), degraded (FE-only), no_shard/error —
        # which is what fleet_check_conservation balances against the
        # shards' own books. Defaults to the process recorder;
        # in-process fleets pass the router its own.
        self._flight = (
            recorder if recorder is not None else flight_recorder()
        )
        self.cache = HotEntityCache(cache_entries)
        self._indexes: Dict[str, EntityRowIndex] = {}
        for id_type, ids in entity_ids.items():
            ids = list(ids)
            if ids != sorted(ids):
                raise ValueError(
                    f"entity ids for {id_type!r} must be the model's "
                    "SORTED id list (position == entity code)"
                )
            self._indexes[id_type] = EntityRowIndex(
                ids, native_threshold=native_index_threshold
            )
        # per-shard feature-bag map for cache digests (None disables
        # per-entry digests in favor of whole-record ones)
        self._shard_bags: Optional[Dict[str, List[str]]] = (
            {
                cfg.shard_id: list(cfg.feature_bags)
                for cfg in shard_configs
            }
            if shard_configs is not None
            else None
        )
        # connection state, lazily (re)built per shard under _conn_lock
        self._conn_lock = threading.Lock()
        self._transports: Dict[int, object] = {}
        self._uid_lock = threading.Lock()
        self._uid_seq = 0
        # routing-generation state + the swap protocol serializer
        self._gen_lock = threading.Lock()
        self._generation = 0
        self._swap_serial = threading.Lock()
        self.health: List[ShardHealth] = []
        self._entries: Tuple = ()
        self._id_types: Tuple[str, ...] = ()
        self._connected = False

    # -- wiring --------------------------------------------------------------

    def connect(self) -> Dict[str, object]:
        """Fetch + cross-check every shard's topology: indexes must
        match positions, counts must agree (and equal the fleet size),
        spec entries and generations must be identical — a fleet that
        disagrees on any of these would route coefficients to the
        wrong host, so it is refused outright."""
        topos = []
        n = self.num_shards
        if n is None:
            raise ValueError("fleet size unknown: pass addresses")
        for i in range(n):
            # topology is fetched over the control plane (fresh JSON
            # connections): the data plane's protocol is not yet known
            # — it is negotiated from these very advertisements
            t = self._control_factory(i)
            try:
                resp = t.request(
                    {"op": "topology", "uid": self._next_uid()},
                    CONTROL_TIMEOUT_S,
                )
            finally:
                if hasattr(t, "close"):
                    t.close()
            if resp.get("status") != "ok":
                raise ValueError(f"shard {i} topology refused: {resp}")
            topos.append(resp)
        for i, topo in enumerate(topos):
            if int(topo["shard_index"]) != i:
                raise ValueError(
                    f"shard at position {i} reports index "
                    f"{topo['shard_index']} — the fleet ordering and the "
                    "ownership rule disagree"
                )
            if int(topo["shard_count"]) != n:
                raise ValueError(
                    f"shard {i} reports {topo['shard_count']} shards, "
                    f"router has {n}"
                )
            if topo.get("rule") != ownership.OWNERSHIP_RULE:
                raise ValueError(
                    f"shard {i} uses ownership rule {topo.get('rule')!r}, "
                    f"router uses {ownership.OWNERSHIP_RULE!r}"
                )
        first = topos[0]
        for i, topo in enumerate(topos[1:], start=1):
            if topo["entries"] != first["entries"]:
                raise ValueError(
                    f"shard {i} spec entries differ from shard 0: "
                    f"{topo['entries']} vs {first['entries']}"
                )
            if int(topo["generation"]) != int(first["generation"]):
                raise ValueError(
                    f"fleet generations disagree: shard {i} at "
                    f"{topo['generation']}, shard 0 at "
                    f"{first['generation']}"
                )
        self._entries = tuple(
            (e[0], e[1], tuple(e[2]), e[3]) for e in first["entries"]
        )
        self._id_types = tuple(
            sorted({t for e in self._entries for t in e[2]})
        )
        missing = [t for t in self._id_types if t not in self._indexes]
        if missing:
            raise ValueError(
                f"router has no entity-id index for id type(s) {missing}"
            )
        # -- wire negotiation: the data plane goes binary only when the
        # WHOLE fleet advertises it. A router pinned to binary facing a
        # JSON-only shard is refused outright — a wire-protocol
        # mismatch is a fleet-layout mismatch, the same class of error
        # as a misordered shard.
        json_only = [
            i for i, topo in enumerate(topos)
            if "binary" not in (
                (topo.get("wire") or {}).get("protocols") or ("json",)
            )
        ]
        if self.wire == "binary" and json_only:
            raise ValueError(
                "wire-protocol mismatch: router requires the binary "
                f"data plane but shard(s) {json_only} advertise JSON "
                "only"
            )
        negotiated = (
            "binary"
            if self.wire in ("binary", "auto") and not json_only
            else "json"
        )
        if negotiated != self._data_wire:
            self._data_wire = negotiated
            # drop any pre-negotiation data transports; the next
            # sub-request rebuilds them on the negotiated protocol
            with self._conn_lock:
                stale = list(self._transports.values())
                self._transports.clear()
            for t in stale:
                if hasattr(t, "close"):
                    t.close()
        self.health = [
            ShardHealth(i, self.policy, recorder=self._flight)
            for i in range(n)
        ]
        with self._gen_lock:
            self._generation = int(first["generation"])
        self._connected = True
        return {
            "shards": n,
            "generation": int(first["generation"]),
            "entries": [list(e) for e in self._entries],
            "wire": negotiated,
        }

    @property
    def generation(self) -> int:
        with self._gen_lock:
            return self._generation

    def _next_uid(self) -> str:
        with self._uid_lock:
            self._uid_seq += 1
            return f"sub-{self._uid_seq}"

    def _publish_transport(self, shard: int, fresh):  # photon: guarded-by(_conn_lock)
        """Install ``fresh`` unless a racing builder already published
        a live transport (the decision re-checks under the lock —
        never trusts the caller's pre-build peek). Returns
        (transport_to_drop, transport_to_use)."""
        cur = self._transports.get(shard)
        if cur is not None and not getattr(cur, "closed", False):
            return fresh, cur
        self._transports[shard] = fresh
        return None, fresh

    def _transport(self, shard: int):
        with self._conn_lock:
            t = self._transports.get(shard)
            if t is not None and not getattr(t, "closed", False):
                return t
        # build OUTSIDE the lock (a TCP connect can block for seconds;
        # holding _conn_lock would stall every other shard's senders),
        # then publish — a racing builder's duplicate is closed
        fresh = self._transport_factory(shard)
        with self._conn_lock:
            drop, keep = self._publish_transport(shard, fresh)
        if drop is not None and hasattr(drop, "close"):
            drop.close()
        return keep

    def _drop_transport(self, shard: int, t) -> None:
        with self._conn_lock:
            if self._transports.get(shard) is t:
                self._transports.pop(shard, None)
        if hasattr(t, "close"):
            t.close()

    def close(self) -> None:
        with self._conn_lock:
            transports = list(self._transports.values())
            self._transports.clear()
        for t in transports:
            if hasattr(t, "close"):
                t.close()

    # -- the scatter/gather request path ------------------------------------

    def _codes_of(self, record: Mapping) -> Dict[str, Tuple[Optional[str], int]]:
        """id type -> (raw id or None, code or -1): the same id
        resolution the single-server request assembly performs, plus
        the router's code lookup (position in the model's sorted id
        universe; -1 = unknown -> the zero term, never a sub-request)."""
        out: Dict[str, Tuple[Optional[str], int]] = {}
        meta = record.get("metadataMap") or {}
        for t in self._id_types:
            v = record.get(t)
            if v is None:
                v = meta.get(t)
            if v is None:
                out[t] = (None, -1)
            else:
                v = str(v)
                out[t] = (v, self._indexes[t].row_of(v))
        return out

    def _entry_cache_key(
        self, generation: int, entry, codes, record: Mapping
    ) -> Optional[tuple]:
        """Cache key for one term slot, or None when the slot is not
        cacheable (no feature-bag map, or an mf pair with a missing
        id). The digest covers the entity id(s) AND the exact feature
        payload the term depends on, so equal keys imply bitwise-equal
        terms."""
        kind, name, id_types, feature_shard = entry
        ids = [codes[t][0] for t in id_types]
        if any(i is None for i in ids):
            return None
        if kind == "re":
            if self._shard_bags is None:
                return None
            bags = self._shard_bags.get(feature_shard)
            if bags is None:
                return None
            payload = [record.get(b) or [] for b in bags]
            return (
                generation, name, _digest(ids, payload)
            )
        # mf: the term is a latent dot product — it depends only on the
        # two entity ids
        return (generation, name, _digest(ids))

    def _fe_cache_key(
        self, generation: int, record: Mapping
    ) -> Optional[tuple]:
        if self._shard_bags is None:
            return None
        payload = [
            [record.get(b) or [] for b in bags]
            for _sid, bags in sorted(self._shard_bags.items())
        ]
        return (generation, FE_SLOT, _digest(payload))

    def _scatter(
        self,
        record: Mapping,
        shards: Sequence[int],
        budget_s: float,
        trace=None,
    ) -> Dict[int, Optional[Mapping]]:
        """Fan one partial-score sub-request out to ``shards`` and
        gather, bounded by ``budget_s`` overall. ALL first attempts go
        out before any wait (the fleet computes concurrently; the
        gather's wall time is the slowest shard, not the sum), then the
        hedged-or-shed policy runs per shard: no answer by the hedge
        point -> one fresh-connection hedge inside the remaining
        budget -> shed (None, degraded downstream). Every outcome is
        noted in its shard's health window."""
        t0 = time.monotonic()
        hedge_at = t0 + (
            budget_s * self.policy.hedge_frac
            if self.policy.hedge
            else budget_s
        )
        deadline = t0 + budget_s
        # phase 1: fire every first attempt
        pending: Dict[int, tuple] = {}  # shard -> (transport, obj, fut, span)
        out: Dict[int, Optional[Mapping]] = {}
        for s in shards:
            if not self.health[s].allow():
                out[s] = None
                continue
            obj = dict(record)
            obj["uid"] = self._next_uid()
            obj["deadline_ms"] = budget_s * 1e3
            # sub-request span, nested under the router span; the wire
            # object carries its context so the shard frontend's span
            # nests under THIS one (dict(record) already relayed any
            # caller context; an active trace overrides it)
            sub = start_span(
                "router.subrequest",
                trace_id=getattr(trace, "trace_id", None),
                parent_id=getattr(trace, "span_id", None),
                shard=s,
            )
            if sub.trace_id is not None:
                obj[TRACE_KEY] = sub.trace_id
                obj[PARENT_KEY] = sub.span_id
            try:
                t = self._transport(s)
                pending[s] = (t, obj, t.send_request(obj), sub)
            except (TransportError, OSError):
                pending[s] = (None, obj, None, sub)
        # phase 2: gather; concurrent attempts overlap, so the per-shard
        # waits share the same absolute deadlines
        for s, (t, obj, fut, sub) in pending.items():
            resp = None
            if fut is not None:
                try:
                    resp = fut.result(
                        timeout=max(hedge_at - time.monotonic(), 0.001)
                    )
                except (TimeoutError, _FutureTimeout):
                    if hasattr(t, "abandon"):
                        t.abandon(obj["uid"])
                except (TransportError, OSError):
                    pass  # connection-level failure: hedge below
            if t is not None and getattr(t, "closed", False):
                self._drop_transport(s, t)
            if resp is None and self.policy.hedge:
                remaining = deadline - time.monotonic()
                if remaining > 0:
                    self.metrics.record_hedge()
                    resp = self._hedge_once(s, obj, remaining)
            ok = (
                resp is not None
                and resp.get("status") == "ok"
                and "fe" in resp
            )
            sub.end(ok=ok)
            out[s] = resp if ok else None
            self.health[s].note(ok)
            self.metrics.record_subrequest(s, ok=ok)
        return out

    def _hedge_once(
        self, shard: int, obj: Mapping, budget_s: float
    ) -> Optional[Mapping]:
        """One hedge on a FRESH connection (the persistent one may be
        the problem); a fresh uid so the abandoned first attempt's late
        response can never be mistaken for this one's."""
        try:
            hedge = self._transport_factory(shard)
        except (TransportError, OSError):
            return None
        try:
            retry = dict(obj)
            retry["uid"] = self._next_uid()
            return hedge.request(retry, budget_s)
        except (TransportError, OSError):
            return None
        finally:
            if hasattr(hedge, "close"):
                hedge.close()

    def score_record(
        self,
        record: Mapping,
        *,
        deadline_ms: Optional[float] = None,
    ) -> RoutedScore:
        """Route one GameExample-shaped record through the fleet into
        one final margin. See the module docstring for the algebra; the
        short version: scatter to owners (+ one FE provider), gather
        terms, re-sum in spec order in float32, cache the hot slots."""
        if not self._connected:
            raise RuntimeError("router not connected (call connect())")
        t_start = time.perf_counter()
        if deadline_ms is None:
            deadline_ms = record.get("deadline_ms")
        budget_s = (
            float(deadline_ms) / 1e3
            if deadline_ms is not None
            else self.policy.subrequest_timeout_s
        )
        codes = self._codes_of(record)
        # conservation ledger: admitted HERE, exactly one attributed
        # terminal below — the router side of the fleet-wide invariant
        self._flight.note_admitted()
        # the root of the routed request's trace: one trace id per
        # request, minted here (or joined from the caller's wire
        # context); every sub-request and every shard-side span nests
        # under it — "one connected trace per routed request"
        wire_t, wire_p = wire_context(record)
        sp = start_span(
            "router.request", trace_id=wire_t, parent_id=wire_p,
            uid=str(record.get("uid") or ""),
        )
        try:
            outcome = self._score_once(
                record, codes, budget_s, use_cache=True, trace=sp
            )
            if outcome is None:
                # generation moved mid-gather (a commit wave passed):
                # one clean re-scatter against the settled fleet, cache
                # cold
                self.metrics.record_generation_retry()
                outcome = self._score_once(
                    record, codes, budget_s, use_cache=False, trace=sp
                )
            if outcome is None:
                # still unsettled after one retry: fleet is mid-flip
                # AND disagreeing; refuse rather than emit a mixed
                # margin
                raise NoShardAvailable(
                    "shard generations disagreed across two gather "
                    "attempts"
                )
        except NoShardAvailable:
            sp.end(status="refused")
            self._flight.note_terminal("no_shard", attribution="no_shard")
            self.metrics.record(
                ok=False,
                degraded=False,
                fanout=0,
                cache_full_hit=False,
                latency_s=time.perf_counter() - t_start,
            )
            raise
        except Exception:
            # anything else still reaches a named terminal — an
            # admitted request with no terminal is exactly the hole
            # fleet conservation exists to expose
            sp.end(status="error")
            self._flight.note_terminal("error", attribution="error")
            raise
        sp.end(
            status="ok",
            fanout=outcome.fanout,
            degraded=outcome.degraded,
            cache_hit=outcome.cache_hit,
            generation=outcome.generation,
        )
        # attribution: degraded outcomes are router-local (FE-only for
        # at least one slot — no single shard "served" the request);
        # zero-fan-out requests were served by the hot cache; the rest
        # key off the shard that provided the FE half. "mixed" (FE from
        # cache, terms from the wire) stays a router-local bucket so
        # the shard join's >= direction is never overstated.
        if outcome.degraded:
            attribution = "degraded"
        elif outcome.fanout == 0:
            attribution = "cache"
        elif outcome.fe_shard is not None:
            attribution = f"shard:{outcome.fe_shard}"
        else:
            attribution = "mixed"
        self._flight.note_terminal(
            "ok", generation=outcome.generation, attribution=attribution
        )
        self.metrics.record(
            ok=True,
            degraded=outcome.degraded,
            fanout=outcome.fanout,
            cache_full_hit=outcome.cache_hit,
            latency_s=time.perf_counter() - t_start,
        )
        return outcome

    def _score_once(
        self, record, codes, budget_s: float, *, use_cache: bool,
        trace=None,
    ) -> Optional[RoutedScore]:
        generation = self.generation
        cache_on = use_cache and self.cache.enabled
        # -- plan: which slots come from cache, which shard owns the
        # rest ------------------------------------------------------------
        fe_key = self._fe_cache_key(generation, record) if cache_on else None
        fe_value = self.cache.get(fe_key) if fe_key is not None else None
        slot_values: Dict[str, float] = {}
        slot_keys: Dict[str, tuple] = {}
        need: Dict[int, List[object]] = {}  # shard -> [entry, ...]
        fe_entries = []  # entries any shard can answer (mf)
        for entry in self._entries:
            kind, name, id_types, _shard = entry
            entry_codes = [codes[t][1] for t in id_types]
            if any(c < 0 for c in entry_codes):
                # unknown/absent entity: the exact 0.0 the single-server
                # program adds — no sub-request, no cache entry
                slot_values[name] = 0.0
                continue
            key = (
                self._entry_cache_key(generation, entry, codes, record)
                if cache_on else None
            )
            if key is not None:
                hit = self.cache.get(key)
                if hit is not None:
                    slot_values[name] = hit
                    continue
                slot_keys[name] = key
            if kind == "re":
                owner = ownership.owner_of(entry_codes[0], self.num_shards)
                need.setdefault(owner, []).append(entry)
            else:
                fe_entries.append(entry)
        need_fe = fe_value is None or fe_entries
        fanout_shards = sorted(need)
        if need_fe and not fanout_shards:
            # nothing entity-owned to fetch, but the FE half (and any
            # mf terms) still needs A shard: pick a healthy one,
            # spreading deadline-less idle traffic by uid hash
            fe_shard = self._pick_fe_shard(record)
            if fe_shard is None:
                raise NoShardAvailable(
                    "no healthy shard-server can provide the "
                    "fixed-effect score"
                )
            fanout_shards = [fe_shard]
        # -- scatter/gather -----------------------------------------------
        responses = (
            self._scatter(record, fanout_shards, budget_s, trace=trace)
            if fanout_shards else {}
        )
        live = {
            s: r for s, r in responses.items() if r is not None
        }
        if need_fe and not live and fanout_shards:
            # every owner (or the chosen FE provider) failed; the FE
            # half is non-negotiable — walk the remaining healthy fleet
            for s in self._fallback_order(record):
                if s in responses:
                    continue
                extra = self._scatter(record, [s], budget_s, trace=trace)
                if extra[s] is not None:
                    responses.update(extra)
                    live = {s: extra[s]}
                    break
            else:
                raise NoShardAvailable(
                    "no healthy shard-server answered for the "
                    "fixed-effect score"
                )
        # -- generation consistency ---------------------------------------
        gens = {int(r["generation"]) for r in live.values()}
        if len(gens) > 1:
            return None  # mixed gather: caller re-scatters once
        gen = gens.pop() if gens else generation
        if cache_on and gen != generation:
            # the fleet flipped under us; every cached slot we planned
            # with belongs to the old generation — redo cold
            return None
        # -- assemble -------------------------------------------------------
        degraded_shards = []
        degraded = False
        fe_from_wire = None
        fe_shard: Optional[int] = None
        for s, r in responses.items():
            if r is None:
                degraded_shards.append(s)
                continue
            if bool(r.get("degraded")):
                degraded = True
            if fe_from_wire is None:
                fe_from_wire = np.float32(r["fe"])
                fe_shard = s
            terms = r.get("terms") or {}
            for entry in need.get(s, ()):
                name = entry[1]
                if name in terms:
                    slot_values[name] = float(np.float32(terms[name]))
                else:
                    slot_values[name] = 0.0
                    degraded = True
            if s in live and fe_entries:
                for entry in fe_entries:
                    name = entry[1]
                    if name in terms:
                        slot_values.setdefault(
                            name, float(np.float32(terms[name]))
                        )
        for s in degraded_shards:
            # a dead shard's entities degrade to the FE-only zero — for
            # ITS entities only; everything else in this request is
            # exact
            degraded = True
            for entry in need.get(s, ()):
                slot_values[entry[1]] = 0.0
        for entry in fe_entries:
            if entry[1] not in slot_values:
                slot_values[entry[1]] = 0.0
                degraded = True
        if fe_value is None:
            if fe_from_wire is None:
                raise NoShardAvailable(
                    "no healthy shard-server answered for the "
                    "fixed-effect score"
                )
            fe = fe_from_wire
        else:
            fe = np.float32(fe_value)
            fe_shard = None  # the cache provided the FE half
        # -- recompose: the full program's accumulation order, f32 ---------
        total = np.float32(fe)
        for entry in self._entries:
            total = np.float32(
                total + np.float32(slot_values[entry[1]])
            )
        off = record.get("offset")
        total = np.float32(
            total + np.float32(0.0 if off is None else float(off))
        )
        # -- populate the cache (deterministic, current-gen, non-degraded
        # slots only) ------------------------------------------------------
        if cache_on and gen == generation and not degraded:
            if fe_key is not None and fe_value is None:
                self.cache.put(fe_key, float(fe))
            for name, key in slot_keys.items():
                if name in slot_values:
                    self.cache.put(key, slot_values[name])
        return RoutedScore(
            float(total),
            degraded=degraded,
            generation=gen,
            fanout=len(fanout_shards),
            cache_hit=not fanout_shards,
            degraded_shards=tuple(sorted(degraded_shards)),
            fe_shard=fe_shard,
        )

    def _pick_fe_shard(self, record: Mapping) -> Optional[int]:
        for s in self._fallback_order(record):
            return s
        return None

    def _fallback_order(self, record: Mapping):
        """Healthy shards, starting at a uid-hash offset so FE-only
        traffic spreads over the fleet instead of hammering shard 0."""
        uid = str(record.get("uid") or "")
        start = (
            int.from_bytes(_digest(uid)[:4], "big") % self.num_shards
        )
        for k in range(self.num_shards):
            s = (start + k) % self.num_shards
            if self.health[s].allow():
                yield s

    # -- the router-coordinated two-step flip --------------------------------

    def coordinate_swap(self, model_dir) -> Dict[str, object]:
        """Flip the WHOLE fleet to a new model generation, two-step:

        1. ``stage_swap`` on every shard (each loads + warms its own
           1/N slice under live traffic). ANY failure aborts the
           already-staged shards — nobody flips, the old generation
           keeps serving everywhere.
        2. ``commit_swap`` on every shard (each a sub-ms flip), then —
           under one lock — bump the routing generation and purge every
           other generation's cache entries. In-flight gathers that
           straddle the wave re-scatter once via the mixed-generation
           check.

        ``model_dir`` is one artifact path for the whole fleet (every
        shard loads its own entity slice of it) or a per-shard list.
        """
        dirs = (
            list(model_dir)
            if isinstance(model_dir, (list, tuple))
            else [model_dir] * self.num_shards
        )
        if len(dirs) != self.num_shards:
            raise ValueError(
                f"{len(dirs)} model dirs for {self.num_shards} shards"
            )
        with self._swap_serial:
            staged: List[int] = []
            for s in range(self.num_shards):
                resp = self._control(
                    s, {"op": "stage_swap", "model_dir": dirs[s]}
                )
                if resp is None or not resp.get("ok"):
                    for p in staged:
                        self._control(p, {"op": "abort_swap"})
                    self._flight.record(
                        "swap.fleet_abort", phase="stage", failed_shard=s,
                    )
                    return {
                        "ok": False,
                        "phase": "stage",
                        "failed_shard": s,
                        "error": (
                            resp.get("error", "stage refused")
                            if resp is not None
                            else "shard unreachable"
                        ),
                        "generation": self.generation,
                    }
                staged.append(s)
            committed: List[int] = []
            new_gens = set()
            for s in range(self.num_shards):
                resp = self._control(s, {"op": "commit_swap"})
                if resp is None or not resp.get("ok"):
                    # a commit failure mid-wave leaves a mixed fleet —
                    # surfaced loudly; the gather-side consistency check
                    # keeps responses correct (never mixed) meanwhile
                    return {
                        "ok": False,
                        "phase": "commit",
                        "failed_shard": s,
                        "committed": committed,
                        "error": (
                            resp.get("error", "commit refused")
                            if resp is not None
                            else "shard unreachable"
                        ),
                        "generation": self.generation,
                    }
                committed.append(s)
                new_gens.add(int(resp["generation"]))
            if len(new_gens) != 1:
                return {
                    "ok": False,
                    "phase": "commit",
                    "error": f"fleet generations diverged: {new_gens}",
                    "generation": self.generation,
                }
            new_gen = new_gens.pop()
            with self._gen_lock:
                self._generation = new_gen
                purged = self.cache.purge_other_generations(new_gen)
            self._flight.record(
                "swap.fleet_commit", generation=new_gen,
                shards=self.num_shards, cache_purged=purged,
            )
            return {
                "ok": True,
                "generation": new_gen,
                "cache_purged": purged,
            }

    def _control(self, shard: int, obj: Dict) -> Optional[Mapping]:
        """One control op on a FRESH connection: staging a generation
        can take seconds, and running it on the multiplexed data
        connection would stall every in-flight score sub-request behind
        the shard frontend's per-connection reader. Control stays JSON
        regardless of the negotiated data plane — status/swap tooling
        must work against ANY fleet member, negotiated or not."""
        obj = dict(obj)
        obj["uid"] = self._next_uid()
        try:
            t = self._control_factory(shard)
        except (TransportError, OSError):
            return None
        try:
            resp = t.request(obj, CONTROL_TIMEOUT_S)
        except (TransportError, OSError):
            return None
        finally:
            if hasattr(t, "close"):
                t.close()
        if resp.get("status") not in ("ok", "error"):
            return None
        return resp

    # -- observability -------------------------------------------------------

    def status(self) -> Dict[str, object]:
        return {
            "shards": self.num_shards,
            "generation": self.generation,
            "rule": ownership.OWNERSHIP_RULE,
            "wire": {
                "requested": self.wire,
                "negotiated": self._data_wire,
            },
            "health": [h.snapshot() for h in self.health],
            "cache": self.cache.snapshot(),
            "router": self.metrics.snapshot(),
        }
