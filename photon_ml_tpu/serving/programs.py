"""AOT fixed-shape scoring programs over a model bank.

Per-request latency on XLA is only predictable when nothing in the
request path can trigger a compile (the pjit/TPUv4 discipline: a small
closed set of shapes, all lowered ahead of time). The request path here
sees exactly ``len(ladder)`` program shapes per model signature — one
padded batch shape per ladder rung — and every one of them is
``lower().compile()``d at bank-load/swap-stage time, BEFORE the shape
can appear on the hot path. After warmup the dispatch loop only ever
calls precompiled executables; the zero-recompile contract is pinned by
``tests/test_serving.py`` with jax's lowering counter.

The executable cache is keyed like the tile-schedule cache: by content
signature — ``(bank spec, padded batch shape)`` — not by bank object
identity, so a hot-swapped generation with unchanged shapes reuses every
program, and a re-load of the same model costs zero compiles.

The scoring function replays the batch scorer's per-coordinate algebra
(`game.model_io.LoadedGameModel.score`) term for term — same gathers,
same per-row reductions, same accumulation order — which is what makes
serving scores bitwise-equal to the batch driver's.
"""

from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp

from photon_ml_tpu.serving.model_bank import ModelBank

__all__ = [
    "RequestBatch",
    "ServingPrograms",
    "DEFAULT_LADDER",
    "select_shape",
    "term_entries",
]

# Padded micro-batch shapes, smallest to largest. 1 serves the idle
# closed loop with no pad waste; 256 is the saturating-load coalescing
# cap (past ~256 rows the per-dispatch fixed cost is already amortized
# to noise and bigger shapes only add tail latency).
DEFAULT_LADDER = (1, 8, 64, 256)


def select_shape(n: int, ladder: Sequence[int]) -> int:
    """Smallest ladder shape that fits ``n`` rows (callers cap takes at
    ``max(ladder)``, so there is always one)."""
    for b in ladder:
        if n <= b:
            return b
    raise ValueError(f"batch of {n} exceeds the ladder {tuple(ladder)}")


class RequestBatch(NamedTuple):
    """One padded micro-batch: per-shard features, per-id-type entity
    codes, offsets. Padded rows carry zero features, code -1 and offset
    0 — they score finite garbage that the demux discards."""

    indices: Dict[str, jnp.ndarray]  # shard -> int32 [B, k]
    values: Dict[str, jnp.ndarray]  # shard -> float32 [B, k]
    codes: Dict[str, jnp.ndarray]  # re/mf id type -> int32 [B]
    offsets: jnp.ndarray  # float32 [B]


def _score_spec(spec, arrays, batch: RequestBatch):
    """Margins + offsets for one padded batch. ``spec`` is static (the
    bank signature); the loop unrolls at trace time into the exact
    coordinate-order sum the batch scorer computes eagerly."""
    total = jnp.zeros(batch.offsets.shape, jnp.float32)
    for entry in spec:
        kind, name = entry[0], entry[1]
        if kind == "fe":
            shard_id = entry[2]
            w = arrays[name]
            total = total + jnp.sum(
                batch.values[shard_id]
                * jnp.take(w, batch.indices[shard_id], axis=0),
                axis=-1,
            )
        elif kind == "re":
            re_type, shard_id = entry[2], entry[3]
            bank = arrays[name]
            codes = batch.codes[re_type]
            valid = codes >= 0
            w_rows = jnp.take(bank, jnp.maximum(codes, 0), axis=0)
            score = jnp.sum(
                batch.values[shard_id]
                * jnp.take_along_axis(
                    w_rows, batch.indices[shard_id], axis=1
                ),
                axis=-1,
            )
            total = total + jnp.where(valid, score, 0.0)
        else:  # mf
            row_t, col_t = entry[2], entry[3]
            R, C = arrays[name]
            rows = batch.codes[row_t]
            cols = batch.codes[col_t]
            valid = (rows >= 0) & (cols >= 0)
            r = jnp.take(R, jnp.maximum(rows, 0), axis=0)
            c = jnp.take(C, jnp.maximum(cols, 0), axis=0)
            total = total + jnp.where(valid, jnp.sum(r * c, axis=-1), 0.0)
    return total + batch.offsets


def term_entries(spec):
    """The ordered (kind, name, id_types, feature_shard) of every
    per-entity spec entry — the coordinate slots a
    :class:`~.admission.PartialScore` carries and the routing tier
    re-sums. MF entries list both id types and no feature shard (their
    term is a latent dot product). Order IS the contract: the router
    adds terms in exactly this sequence, which is the full program's
    accumulation order."""
    out = []
    for entry in spec:
        if entry[0] == "re":
            out.append(("re", entry[1], (entry[2],), entry[3]))
        elif entry[0] == "mf":
            out.append(("mf", entry[1], (entry[2], entry[3]), None))
    return tuple(out)


def _score_spec_partial(spec, arrays, batch: RequestBatch):
    """The scatter/gather decomposition of :func:`_score_spec`: the
    fixed-effect accumulation (identical chain of f32 adds as the full
    program's FE prefix — every shard holds the full FE banks) and one
    column per re/mf entry with that coordinate's term (0.0 where the
    entity code is -1, exactly the zero the full program adds). The
    router recomposes ``((fe + t_1) + t_2)… + offset`` host-side in
    float32 — each step exactly-rounded IEEE, so the routed margin is
    bitwise the single-server one. Offsets are NOT added here: the
    router owns them (it has the request; sub-requests may fan out to
    several shards and the offset must be applied exactly once)."""
    fe = jnp.zeros(batch.offsets.shape, jnp.float32)
    terms = []
    for entry in spec:
        kind, name = entry[0], entry[1]
        if kind == "fe":
            shard_id = entry[2]
            w = arrays[name]
            fe = fe + jnp.sum(
                batch.values[shard_id]
                * jnp.take(w, batch.indices[shard_id], axis=0),
                axis=-1,
            )
        elif kind == "re":
            re_type, shard_id = entry[2], entry[3]
            bank = arrays[name]
            codes = batch.codes[re_type]
            valid = codes >= 0
            w_rows = jnp.take(bank, jnp.maximum(codes, 0), axis=0)
            score = jnp.sum(
                batch.values[shard_id]
                * jnp.take_along_axis(
                    w_rows, batch.indices[shard_id], axis=1
                ),
                axis=-1,
            )
            terms.append(jnp.where(valid, score, 0.0))
        else:  # mf
            row_t, col_t = entry[2], entry[3]
            R, C = arrays[name]
            rows = batch.codes[row_t]
            cols = batch.codes[col_t]
            valid = (rows >= 0) & (cols >= 0)
            r = jnp.take(R, jnp.maximum(rows, 0), axis=0)
            c = jnp.take(C, jnp.maximum(cols, 0), axis=0)
            terms.append(jnp.where(valid, jnp.sum(r * c, axis=-1), 0.0))
    stacked = (
        jnp.stack(terms, axis=1)
        if terms
        else jnp.zeros(batch.offsets.shape + (0,), jnp.float32)
    )
    return fe, stacked


# photon: sharding(axes=[])
_score_jit = jax.jit(_score_spec, static_argnums=(0,))
# photon: sharding(axes=[])
_score_partial_jit = jax.jit(_score_spec_partial, static_argnums=(0,))


def _batch_structs(spec, B: int) -> RequestBatch:
    """ShapeDtypeStructs of a padded batch at ladder shape ``B`` (the
    lowering inputs; shard widths/id types come from the spec)."""
    f32, i32 = jnp.float32, jnp.int32
    indices: Dict[str, jax.ShapeDtypeStruct] = {}
    values: Dict[str, jax.ShapeDtypeStruct] = {}
    codes: Dict[str, jax.ShapeDtypeStruct] = {}
    for entry in spec:
        kind = entry[0]
        if kind == "fe":
            shard_id, _d, k = entry[2], entry[3], entry[4]
            indices[shard_id] = jax.ShapeDtypeStruct((B, k), i32)
            values[shard_id] = jax.ShapeDtypeStruct((B, k), f32)
        elif kind == "re":
            re_type, shard_id, k = entry[2], entry[3], entry[6]
            indices[shard_id] = jax.ShapeDtypeStruct((B, k), i32)
            values[shard_id] = jax.ShapeDtypeStruct((B, k), f32)
            codes[re_type] = jax.ShapeDtypeStruct((B,), i32)
        else:
            for t in (entry[2], entry[3]):
                codes[t] = jax.ShapeDtypeStruct((B,), i32)
    return RequestBatch(
        indices=indices,
        values=values,
        codes=codes,
        offsets=jax.ShapeDtypeStruct((B,), f32),
    )


def _array_structs(arrays):
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), arrays
    )


class ServingPrograms:
    """The per-shape executable cache. ``ensure_compiled`` is the warmup
    seam (bank load + swap staging); ``score`` is the hot path and — by
    contract — never lowers anything the cache does not already hold
    unless an unwarmed shape arrives (counted, and zero after warmup)."""

    def __init__(self, ladder: Sequence[int] = DEFAULT_LADDER, max_entries: int = 64):
        if not ladder or list(ladder) != sorted(set(int(b) for b in ladder)):
            raise ValueError(
                f"ladder must be strictly increasing and non-empty: {ladder}"
            )
        self.ladder: Tuple[int, ...] = tuple(int(b) for b in ladder)
        self._max_entries = max_entries
        self._lock = threading.Lock()
        # insertion-ordered dict used as an LRU: every hit re-inserts at
        # the end, so eviction (front pop) drops the coldest entry and
        # spec churn can never push out the live bank's ladder rungs
        self._cache: Dict[tuple, object] = {}
        # single-flight guard: key -> Event held by the thread compiling
        # it, so racing callers wait instead of compiling redundantly
        self._inflight: Dict[tuple, threading.Event] = {}
        self.compile_count = 0
        self.cold_dispatch_compiles = 0

    def _lru_get(self, key):  # photon: guarded-by(_lock)
        """Cache lookup + recency touch. Caller holds ``self._lock``
        (declared on the def line; the analyzer checks call sites)."""
        exe = self._cache.get(key)
        if exe is not None:
            self._cache[key] = self._cache.pop(key)
        return exe

    def _get_or_compile(self, spec, arrays, B: int, *,
                        partial: bool = False):
        """Returns ``(executable, freshly_compiled)``. Exactly one
        thread lowers a given (spec, B, mode); losers of the race wait
        on the winner's event and take the cached result. If the
        winner's compile raises, waiters retry (and may compile
        themselves). ``partial`` selects the scatter/gather program
        (fe + per-coordinate terms) over the full-margin one — the two
        families share the LRU, keyed apart."""
        key = (spec, B, bool(partial))
        while True:
            with self._lock:
                exe = self._lru_get(key)
                if exe is not None:
                    return exe, False
                ev = self._inflight.get(key)
                if ev is None:
                    ev = threading.Event()
                    self._inflight[key] = ev
                    break
            # timed wait (request-path hygiene, PL007): re-check the
            # cache each beat instead of parking unbounded on the
            # winner's event
            while not ev.wait(timeout=0.1):
                continue
        try:
            jitted = _score_partial_jit if partial else _score_jit
            exe = jitted.lower(
                spec, _array_structs(arrays), _batch_structs(spec, B)
            ).compile()
            with self._lock:
                while len(self._cache) >= self._max_entries:
                    self._cache.pop(next(iter(self._cache)))
                self._cache[key] = exe
                self.compile_count += 1
            return exe, True
        finally:
            with self._lock:
                self._inflight.pop(key, None)
            ev.set()

    def ensure_compiled(self, bank: ModelBank, *,
                        partial: bool = False) -> int:
        """AOT-compile every ladder shape for this bank's signature;
        returns how many programs were newly compiled (0 when the spec
        was already warm — the swap-without-recompile case).
        ``partial`` warms the shard-server program family instead of
        the full-margin one."""
        fresh = 0
        for B in self.ladder:
            _, new = self._get_or_compile(
                bank.spec, bank.arrays, B, partial=partial
            )
            fresh += int(new)
        return fresh

    def executable(self, spec, B: int, *, partial: bool = False):
        with self._lock:
            return self._lru_get((spec, B, bool(partial)))

    def score(self, bank: ModelBank, batch: RequestBatch) -> jnp.ndarray:
        """Device scores for one padded batch (no readback here — the
        batcher owns the single counted device_get per dispatch)."""
        B = batch.offsets.shape[0]
        exe = self.executable(bank.spec, B)
        if exe is None:
            # an unwarmed shape reached the hot path: compile it now and
            # count the miss — the bench/test gates pin this at zero
            # after warmup
            with self._lock:
                self.cold_dispatch_compiles += 1
            exe, _ = self._get_or_compile(bank.spec, bank.arrays, B)
        return exe(bank.arrays, batch)

    def score_partial(self, bank: ModelBank, batch: RequestBatch):
        """Device (fe[B], terms[B, R]) for one padded batch — the
        shard-server half of a routed score. Same zero-recompile
        contract as :meth:`score` (shard servers warm this family at
        load/swap-stage time); no readback here either."""
        B = batch.offsets.shape[0]
        exe = self.executable(bank.spec, B, partial=True)
        if exe is None:
            with self._lock:
                self.cold_dispatch_compiles += 1
            exe, _ = self._get_or_compile(
                bank.spec, bank.arrays, B, partial=True
            )
        return exe(bank.arrays, batch)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "compiled_programs": len(self._cache),
                "compile_count": self.compile_count,
                "cold_dispatch_compiles": self.cold_dispatch_compiles,
            }
