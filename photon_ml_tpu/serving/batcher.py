"""Micro-batching request loop: admit, coalesce, pad, dispatch, demux.

The Podracer idiom (PAPERS.md): the serving loop is its own component —
it never blocks on training, model publication, or artifact IO. Here it
also never blocks on the DEVICE more than one dispatch at a time:
concurrent submitters enqueue; a single dispatcher thread drains the
queue into the smallest padded ladder shape that fits, runs ONE
precompiled device program, performs exactly ONE counted readback
(``overlap.device_get``) and resolves each request's future with its own
row.

Batching is continuous by default (``max_wait_s = 0``): whatever
accumulated while the previous dispatch executed forms the next batch,
so an idle service answers a lone request at shape 1 with zero imposed
wait, and a saturated service coalesces to the ladder cap without any
timer tuning. ``max_wait_s > 0`` forces coalescing for bursty open-loop
sources.

Overload discipline (the Orca-style continuous-batching contract,
PAPERS.md): admission happens at ``submit`` — a request whose
client-propagated ``deadline_ms`` is already beaten by the predicted
queue wait is SHED immediately (:class:`~.admission.RequestShed`), and
a full queue blocks a submitter only for the request's own remaining
budget, never indefinitely. Requests whose deadline expires while
queued are dropped *before* dispatch (the device never scores dead
work). Every accepted request reaches exactly one terminal state:
a :class:`~.admission.ScoreOutcome`, or one of the named
``ServingError`` failures — including :class:`~.admission.DrainTimeout`
for requests still pending when a bounded ``drain`` runs out of budget.

Graceful degradation: when a random-effect bank is quarantined (or its
row resolution fails mid-swap), the batch scores FE-ONLY for the
affected rows — bitwise what the batch scorer produces for an unknown
entity — and the outcome carries ``degraded=True`` instead of an error.

Request assembly lives here too: :func:`requests_from_dataset` turns a
``GameDataset`` into per-row requests (the file-replay path — identical
padding/width to the batch scorer, which is what the bitwise parity bar
needs), and :func:`request_from_record` maps one raw record dict
through prebuilt index maps (the stdin/front-end path).
"""

from __future__ import annotations

import contextlib
import threading
import time
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional

import numpy as np

from photon_ml_tpu.obs.flight_recorder import flight_recorder
from photon_ml_tpu.obs.trace import (
    PARENT_KEY,
    TRACE_KEY,
    record_span,
    tracing_enabled,
)
from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.serving.admission import (
    AdmissionController,
    BatcherClosed,
    DeadlineExceeded,
    DrainTimeout,
    PartialScore,
    RequestShed,
    ScoreOutcome,
)
from photon_ml_tpu.serving.model_bank import ModelBank
from photon_ml_tpu.serving.programs import (
    RequestBatch,
    ServingPrograms,
    select_shape,
    term_entries,
)

__all__ = [
    "ScoreRequest",
    "MicroBatcher",
    "DrainReport",
    "request_from_record",
    "requests_from_dataset",
]

_NO_LOCK = contextlib.nullcontext()

# How long a submitter without a deadline may block on a full queue
# before it is shed: backpressure stays bounded even for clients that
# declared no latency budget of their own.
DEFAULT_SUBMIT_WAIT_S = 30.0
# score()'s result bound when the request carries no deadline — a
# request path where ANY wait is unbounded is exactly what PL007
# (request-path-hygiene) exists to reject.
DEFAULT_RESULT_TIMEOUT_S = 600.0
# Slack past the deadline for score()'s result wait: the dispatch a
# request was admitted into may still be executing when its queue
# deadline passes.
RESULT_DEADLINE_SLACK_S = 30.0
# Idle dispatcher wake-up period: each pass refreshes the liveness
# heartbeat, so "dispatcher alive" is a recent timestamp, not a guess.
HEARTBEAT_INTERVAL_S = 0.25
# Consecutive row-resolution failures on one RE type before the bank
# quarantines that coordinate (every later request scores FE-only
# without paying the failing lookup again).
RE_QUARANTINE_AFTER = 3


def _resolve(fut: Future, *, result=None, error: Optional[BaseException] = None) -> bool:
    """Resolve a future exactly once; racing resolvers (dispatcher vs
    drain-timeout) both go through here, so a lost race is a no-op, not
    a crash."""
    if fut.done():
        return False
    try:
        if error is not None:
            fut.set_exception(error)
        else:
            fut.set_result(result)
        return True
    except InvalidStateError:
        return False


@dataclass
class ScoreRequest:
    """One scoring request: per-shard padded (indices, values) rows at
    the bank's shard widths, plus RAW entity ids. Entity ids resolve to
    bank rows at DISPATCH time, against the bank the batch actually
    runs on — never at build time. A request is therefore valid across
    hot swaps: a generation flip that keeps device shapes but changes
    the entity set (the exact case entity padding preserves) re-resolves
    every queued and replayed request against the new generation's rows
    instead of scoring stale ones."""

    uid: str
    indices: Dict[str, np.ndarray]  # shard -> int32 [k]
    values: Dict[str, np.ndarray]  # shard -> float32 [k]
    entity_ids: Dict[str, Optional[str]]  # id type -> raw id (None = absent)
    offset: float = 0.0
    # client-propagated latency budget in milliseconds from enqueue;
    # None = no deadline (bounded only by the batcher's own submit cap)
    deadline_ms: Optional[float] = None
    # passthrough columns for the scores artifact (batch-scorer record
    # layout); never touch the device
    label: Optional[float] = None
    weight: float = 1.0
    metadata: Optional[Dict[str, str]] = None
    # end-to-end tracing (obs/trace.py): the wire-carried trace id and
    # the parent span the dispatch-window span nests under. Host-only
    # annotations — they never touch the device path.
    trace_id: Optional[str] = None
    parent_span: Optional[str] = None
    _enqueue_t: float = field(default=0.0, repr=False)

    def expired(self, now: float) -> bool:
        return (
            self.deadline_ms is not None
            and (now - self._enqueue_t) * 1e3 > self.deadline_ms
        )


@dataclass
class DrainReport:
    """What a bounded drain did: how many requests were pending when it
    started, how many completed inside the budget, how many were failed
    with :class:`DrainTimeout`, and whether the dispatcher exited."""

    pending_at_start: int = 0
    completed: int = 0
    failed: int = 0
    duration_s: float = 0.0
    timed_out: bool = False

    def to_dict(self) -> Dict[str, object]:
        return {
            "pending_at_start": self.pending_at_start,
            "completed": self.completed,
            "failed": self.failed,
            "duration_s": round(self.duration_s, 6),
            "timed_out": self.timed_out,
        }


def request_from_record(
    record: Mapping,
    bank: ModelBank,
    shard_configs,
    *,
    has_response: bool = True,
) -> ScoreRequest:
    """One raw GameExample-shaped dict -> ScoreRequest through the
    bank's index maps (the stdin/JSON and network front-end path; the
    Avro replay path goes through :func:`requests_from_dataset`)."""
    from photon_ml_tpu.game.data import record_response
    from photon_ml_tpu.utils.index_map import feature_key, intercept_key

    indices: Dict[str, np.ndarray] = {}
    values: Dict[str, np.ndarray] = {}
    for cfg in shard_configs:
        imap = bank.index_maps[cfg.shard_id]
        k = bank.shard_widths[cfg.shard_id]
        ix = np.zeros((k,), np.int32)
        vs = np.zeros((k,), np.float32)
        pos = 0
        for bag in cfg.feature_bags:
            for f in record.get(bag) or []:
                j = imap.get_index(feature_key(f["name"], f["term"]))
                if j < 0:
                    continue  # unknown feature: dropped, like the builder
                if pos >= k:
                    raise ValueError(
                        f"request {record.get('uid')!r} exceeds shard "
                        f"{cfg.shard_id!r} width {k}"
                    )
                ix[pos] = j
                vs[pos] = float(f["value"])
                pos += 1
        if cfg.add_intercept:
            j = imap.get_index(intercept_key())
            if j >= 0:
                if pos >= k:
                    raise ValueError(
                        f"request {record.get('uid')!r} exceeds shard "
                        f"{cfg.shard_id!r} width {k}"
                    )
                ix[pos] = j
                vs[pos] = 1.0
                pos += 1
        indices[cfg.shard_id] = ix
        values[cfg.shard_id] = vs
    # raw ids only — the dispatcher resolves them against whichever
    # bank generation the batch runs on. A record missing an id type
    # scores FE-only (unknown-entity semantics), and its key is OMITTED
    # from metadata (never the literal "None"), matching the dataset
    # path's records.
    entity_ids: Dict[str, Optional[str]] = {}
    for t in bank.re_types:
        v = record.get(t)
        if v is None:
            v = (record.get("metadataMap") or {}).get(t)
        entity_ids[t] = None if v is None else str(v)
    off = record.get("offset")
    wgt = record.get("weight")
    uid = record.get("uid")
    deadline = record.get("deadline_ms")
    trace_id = record.get(TRACE_KEY)
    parent_span = record.get(PARENT_KEY)
    meta = {t: e for t, e in entity_ids.items() if e is not None}
    return ScoreRequest(
        uid="" if uid is None else str(uid),
        indices=indices,
        values=values,
        entity_ids=entity_ids,
        offset=0.0 if off is None else float(off),
        deadline_ms=None if deadline is None else float(deadline),
        label=(
            record_response(record, True) if has_response else None
        ),
        weight=1.0 if wgt is None else float(wgt),
        metadata=meta or None,
        trace_id=None if trace_id is None else str(trace_id),
        parent_span=None if parent_span is None else str(parent_span),
    )


def requests_from_dataset(ds, bank: ModelBank) -> List[ScoreRequest]:
    """Per-row requests from a GameDataset built with the bank's index
    maps — row slices are views. Requests carry the RAW entity id
    strings (the dataset's codes index the dataset's own entity table,
    not the model's); the dispatcher resolves id -> bank row against
    whichever generation each batch runs on, so a replayed trace stays
    correct across hot swaps whose entity sets differ. ``bank`` pins the
    per-shard widths the AOT program shapes were compiled for."""
    for sid, k in bank.shard_widths.items():
        sd = ds.shards.get(sid)
        if sd is None or sd.indices.shape[1] != k:
            got = None if sd is None else sd.indices.shape[1]
            raise ValueError(
                f"dataset shard {sid!r} width {got!r} != bank request "
                f"width {k} (the trace must be built at the bank's "
                "padded layout)"
            )
    out: List[ScoreRequest] = []
    id_types = sorted(ds.entity_indexes)
    for i in range(ds.num_real_rows):
        entity_ids = {
            t: (
                ds.entity_indexes[t].ids[int(ds.entity_codes[t][i])]
                if int(ds.entity_codes[t][i]) >= 0
                else None
            )
            for t in id_types
        }
        meta = {t: e for t, e in entity_ids.items() if e is not None}
        out.append(
            ScoreRequest(
                uid=ds.uids[i],
                indices={
                    sid: sd.indices[i] for sid, sd in ds.shards.items()
                },
                values={
                    sid: sd.values[i] for sid, sd in ds.shards.items()
                },
                entity_ids=entity_ids,
                offset=float(ds.offsets[i]),
                label=float(ds.labels[i]),
                weight=float(ds.weights[i]),
                metadata=meta or None,
            )
        )
    return out


class MicroBatcher:
    """Bounded-queue micro-batcher over a live bank reference.

    ``bank_ref`` is a zero-arg callable returning the CURRENT ModelBank
    — the hot-swap seam: the dispatcher reads it once per dispatch, so a
    generation flip lands exactly on a batch boundary, never inside one.
    """

    def __init__(
        self,
        bank_ref: Callable[[], ModelBank],
        programs: ServingPrograms,
        metrics=None,
        *,
        max_wait_s: float = 0.0,
        max_queue: int = 4096,
        swap_lock: Optional[threading.Lock] = None,
        admission: Optional[AdmissionController] = None,
        default_deadline_ms: Optional[float] = None,
        max_submit_wait_s: float = DEFAULT_SUBMIT_WAIT_S,
        partial: Optional[bool] = None,
        recorder=None,
    ):
        self._bank_ref = bank_ref
        self._programs = programs
        self._metrics = metrics
        # conservation ledger target: the process flight recorder by
        # default; in-process fleets (tests, bench rigs) pass each
        # member its OWN recorder so the per-member books stay separate
        self._flight = recorder if recorder is not None else flight_recorder()
        # exclusion against a DONATING hot swap (see ServingModel.
        # dispatch_lock): inferred from a bound ServingModel.current
        # bank_ref so the safe wiring is the default wiring
        owner = getattr(bank_ref, "__self__", None)
        self._swap_lock = (
            swap_lock
            if swap_lock is not None
            else getattr(owner, "dispatch_lock", None)
        )
        # shard-server mode: dispatch the scatter/gather partial
        # program (fe + per-coordinate terms) and resolve futures with
        # PartialScore instead of ScoreOutcome. Like the swap lock, the
        # mode is inferred from a bound ServingModel so the safe wiring
        # is the default wiring.
        self._partial = (
            bool(partial)
            if partial is not None
            else bool(getattr(owner, "partial", False))
        )
        self._max_wait_s = float(max_wait_s)
        self._max_queue = int(max_queue)
        self._admission = admission or AdmissionController()
        self._default_deadline_ms = (
            None if default_deadline_ms is None else float(default_deadline_ms)
        )
        self._max_submit_wait_s = float(max_submit_wait_s)
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._space = threading.Condition(self._lock)
        self._queue: List = []  # (ScoreRequest, Future)
        self._inflight: List = []  # the take a dispatch is executing
        self._closed = False
        self._draining = False
        # dispatcher-thread-local scratch (never read off-thread)
        self._re_fail_counts: Dict[str, int] = {}
        # single-writer atomic publish: only the dispatcher stamps it
        # (plain float assignment), liveness probes read it bare — a
        # heartbeat behind a lock would measure the lock, not the loop
        self._last_heartbeat = time.perf_counter()  # photon: guarded-by(atomic)
        self._worker = threading.Thread(
            target=self._dispatch_loop,
            name="photon-serving-dispatch",
            daemon=True,
        )
        self._worker.start()

    # -- liveness ------------------------------------------------------------

    def alive(self) -> bool:
        """Dispatcher liveness: the worker thread exists and is running
        (it exits only after close/drain)."""
        return self._worker.is_alive()

    def heartbeat_age_s(self) -> float:
        """Seconds since the dispatcher last crossed its loop — it beats
        at least every ``HEARTBEAT_INTERVAL_S`` even when idle, so a
        large age means a wedged (not merely idle) dispatcher."""
        return time.perf_counter() - self._last_heartbeat

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue) + len(self._inflight)

    # -- submit side ---------------------------------------------------------

    def submit(self, request: ScoreRequest) -> Future:
        """Admit one request, or refuse it NOW with a named error.

        Admission control (all under the queue lock, all O(1)):

        - closed/draining batcher -> :class:`BatcherClosed`;
        - predicted queue wait past the request's ``deadline_ms`` ->
          :class:`RequestShed` immediately (no queue slot consumed);
        - full queue -> block at most the request's remaining deadline
          (or ``max_submit_wait_s`` for deadline-less requests), then
          :class:`RequestShed`. The indefinite block is gone: every
          submit returns or raises in bounded time.
        """
        fut: Future = Future()
        now = time.perf_counter()
        request._enqueue_t = now
        if request.deadline_ms is None:
            request.deadline_ms = self._default_deadline_ms
        wait_budget_s = (
            request.deadline_ms / 1e3
            if request.deadline_ms is not None
            else self._max_submit_wait_s
        )
        limit = now + wait_budget_s
        # shed accounting happens AFTER the lock is released (PL010
        # atomicity-hygiene): the metrics object has its own lock, and
        # a foreign critical section inside the Condition-backed queue
        # lock stalls the dispatcher and every parked submitter.
        # predicted_wait_s is lock-free by design (single-writer EWMA).
        try:
            with self._lock:
                if self._closed or self._draining:
                    raise BatcherClosed("batcher is closed")
                if request.deadline_ms is not None:
                    predicted = self._admission.predicted_wait_s(
                        len(self._queue)
                    )
                    if predicted * 1e3 > request.deadline_ms:
                        raise RequestShed(
                            f"predicted queue wait {predicted * 1e3:.1f}"
                            f"ms exceeds deadline "
                            f"{request.deadline_ms:.1f}ms "
                            f"(queue depth {len(self._queue)})",
                            reason="predicted_wait",
                        )
                while len(self._queue) >= self._max_queue:
                    if self._closed or self._draining:
                        raise BatcherClosed("batcher is closed")
                    remaining = limit - time.perf_counter()
                    if remaining <= 0:
                        raise RequestShed(
                            f"queue full ({self._max_queue}) past the "
                            "request's wait budget "
                            f"{wait_budget_s * 1e3:.1f}ms",
                            reason="queue_full",
                        )
                    self._space.wait(timeout=remaining)
                if self._closed or self._draining:
                    raise BatcherClosed("batcher is closed")
                self._queue.append((request, fut))
                self._nonempty.notify()
        except RequestShed as e:
            if self._metrics is not None:
                self._metrics.record_shed(e.reason)
            # structured overload event + (refused-before-admission, so
            # it enters neither side of the conservation ledger)
            self._flight.record("request.shed", reason=e.reason)
            raise
        # conservation ledger (obs/flight_recorder.py): one admitted
        # mark per queued request; every resolution site below marks
        # the matching terminal — check_conservation() is the
        # every-request-reaches-a-named-outcome invariant. Fed OUTSIDE
        # the queue lock, like the shed accounting above.
        self._flight.note_admitted()
        return fut

    def score(
        self, request: ScoreRequest, timeout: Optional[float] = None
    ) -> ScoreOutcome:
        """Closed-loop convenience: submit and wait — bounded. The wait
        is the request's own deadline plus dispatch slack (the batch it
        was admitted into still has to execute), or the module default
        for deadline-less requests."""
        fut = self.submit(request)
        if timeout is None:
            timeout = (
                request.deadline_ms / 1e3 + RESULT_DEADLINE_SLACK_S
                if request.deadline_ms is not None
                else DEFAULT_RESULT_TIMEOUT_S
            )
        return fut.result(timeout=timeout)

    def close(self) -> None:
        """Serve everything queued, then stop the dispatcher. Idempotent.
        Submitters blocked on a full queue are woken (both conditions
        notified) and raise instead of hanging."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._nonempty.notify_all()
            self._space.notify_all()
        self._worker.join()

    def drain(self, timeout_s: float) -> DrainReport:
        """Bounded shutdown: stop admitting, serve what is already
        queued for up to ``timeout_s``, then fail every still-pending
        future with :class:`DrainTimeout` — one terminal outcome per
        request, zero hung futures, whatever state the device is in.
        """
        t0 = time.perf_counter()
        deadline = t0 + max(float(timeout_s), 0.0)
        leftovers: Optional[List] = None  # None = was already closed
        pending_at_start = 0
        with self._lock:
            if not self._closed:
                self._draining = True
                pending_at_start = len(self._queue) + len(self._inflight)
                # wake blocked submitters (they raise BatcherClosed)
                # and an idle dispatcher
                self._nonempty.notify_all()
                self._space.notify_all()
                while self._queue or self._inflight:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    # _space is notified after every take AND after
                    # every dispatch completion, so this wakes as work
                    # finishes
                    self._space.wait(timeout=min(remaining, 0.05))
                leftovers = list(self._queue) + list(self._inflight)
                self._queue.clear()
                self._closed = True
                self._nonempty.notify_all()
                self._space.notify_all()
        if leftovers is None:
            # already closed: accounting outside the queue lock (PL010
            # atomicity-hygiene — record_drain takes the metrics lock)
            report = DrainReport(duration_s=time.perf_counter() - t0)
            if self._metrics is not None:
                self._metrics.record_drain(report)
            return report
        failed = 0
        for _req, fut in leftovers:
            if _resolve(fut, error=DrainTimeout(
                "request still pending when the drain budget "
                f"({timeout_s:.3f}s) ran out"
            )):
                failed += 1
        if failed:
            self._flight.note_terminal("drain_timeout", n=failed)
        join_budget = max(deadline - time.perf_counter(), 0.0) + 1.0
        self._worker.join(timeout=join_budget)
        report = DrainReport(
            pending_at_start=pending_at_start,
            completed=pending_at_start - failed,
            failed=failed,
            duration_s=time.perf_counter() - t0,
            timed_out=bool(failed) or self._worker.is_alive(),
        )
        if self._metrics is not None:
            self._metrics.record_drain(report)
        return report

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- dispatch side -------------------------------------------------------

    def _take(self) -> List:
        """Block until work exists (waking periodically to beat the
        liveness heartbeat), optionally linger ``max_wait_s`` for
        coalescing, then claim up to ``max(ladder)`` requests."""
        cap = self._programs.ladder[-1]
        with self._lock:
            while not self._queue and not self._closed:
                self._nonempty.wait(timeout=HEARTBEAT_INTERVAL_S)
                self._last_heartbeat = time.perf_counter()
            if not self._queue:
                return []  # closed and drained
            if self._max_wait_s > 0.0 and not self._draining:
                deadline = self._queue[0][0]._enqueue_t + self._max_wait_s
                while (
                    len(self._queue) < cap
                    and not self._closed
                    and (remaining := deadline - time.perf_counter()) > 0
                ):
                    self._nonempty.wait(timeout=remaining)
                    self._last_heartbeat = time.perf_counter()
            take = self._queue[:cap]
            del self._queue[:cap]
            self._inflight = list(take)
            self._space.notify_all()
            return take

    def _finish_take(self) -> None:
        with self._lock:
            self._inflight = []
            # drain() parks on _space waiting for inflight to clear
            self._space.notify_all()

    def _expire_dead(self, take: List) -> List:
        """Drop requests whose deadline passed while they queued —
        BEFORE assembly, so the device never scores dead work. Each
        dropped future fails with the named DeadlineExceeded outcome."""
        now = time.perf_counter()
        live: List = []
        expired = 0
        for req, fut in take:
            if req.expired(now):
                waited_ms = (now - req._enqueue_t) * 1e3
                if _resolve(fut, error=DeadlineExceeded(
                    f"deadline {req.deadline_ms:.1f}ms exceeded after "
                    f"{waited_ms:.1f}ms in queue"
                )):
                    expired += 1
            else:
                live.append((req, fut))
        if expired:
            if self._metrics is not None:
                self._metrics.record_deadline_expired(expired)
            fr = self._flight
            fr.record("request.deadline", expired=expired)
            fr.note_terminal("deadline_exceeded", n=expired)
        return live

    def _dispatch_loop(self) -> None:
        while True:
            self._last_heartbeat = time.perf_counter()
            take = self._take()
            if not take:
                return
            try:
                take = self._expire_dead(take)
                if take:
                    self._dispatch(take)
            except BaseException as e:  # resolve, never wedge submitters
                errored = 0
                for _req, fut in take:
                    errored += int(_resolve(fut, error=e))
                if errored:
                    self._flight.note_terminal(
                        "dispatch_error", n=errored
                    )
            finally:
                self._finish_take()

    def _assemble(self, requests: List[ScoreRequest], bank: ModelBank,
                  B: int):
        n = len(requests)
        indices: Dict[str, np.ndarray] = {}
        values: Dict[str, np.ndarray] = {}
        # only the shards the SPEC scores: requests may carry more
        # (an FE-only model under a multi-shard request config), but
        # the compiled program's pytree holds exactly the spec's shards
        for sid in bank.used_shards:
            k = bank.shard_widths[sid]
            ix = np.zeros((B, k), np.int32)
            vs = np.zeros((B, k), np.float32)
            for i, r in enumerate(requests):
                ix[i] = r.indices[sid]
                vs[i] = r.values[sid]
            indices[sid] = ix
            values[sid] = vs
        # resolve raw entity ids against the bank THIS batch dispatches
        # on (one vectorized rows_of per id type): requests pre-built or
        # queued before a hot swap score the new generation's rows, not
        # stale build-time ones. A quarantined RE bank — or a lookup
        # that fails outright (e.g. the native index store dying
        # mid-swap) — degrades those rows to FE-only (code -1, the
        # batch scorer's unknown-entity semantics) instead of failing
        # the whole batch.
        degraded = np.zeros((B,), bool)
        codes: Dict[str, np.ndarray] = {}
        for t in bank.re_types:
            c = np.full((B,), -1, np.int32)
            present: List[int] = []
            ids: List[str] = []
            for i, r in enumerate(requests):
                e = r.entity_ids.get(t) if r.entity_ids else None
                if e is not None:
                    present.append(i)
                    ids.append(e)
            if present:
                rows_at = np.asarray(present)
                if t in bank.quarantined_re_types:
                    degraded[rows_at] = True
                else:
                    try:
                        c[rows_at] = bank.entity_rows[t].rows_of(ids)
                        self._re_fail_counts.pop(t, None)
                    except Exception as e:
                        degraded[rows_at] = True
                        fails = self._re_fail_counts.get(t, 0) + 1
                        self._re_fail_counts[t] = fails
                        if fails >= RE_QUARANTINE_AFTER:
                            bank.quarantine_re(t)
                            if self._metrics is not None:
                                self._metrics.record_re_quarantine(t)
                        if self._metrics is not None:
                            self._metrics.record_re_resolution_failure(t)
            codes[t] = c
        offsets = np.zeros((B,), np.float32)
        offsets[:n] = [r.offset for r in requests]
        batch = RequestBatch(
            indices=indices, values=values, codes=codes, offsets=offsets
        )
        return batch, degraded

    def _dispatch(self, take: List) -> None:
        from photon_ml_tpu.reliability import io_call

        t0 = time.perf_counter()
        requests = [r for r, _ in take]
        # the whole device section (bank read -> assemble -> execute ->
        # readback) is exclusive with a donating hot swap, so a flip
        # lands BETWEEN batches and can never invalidate the buffers of
        # one in flight; uncontended, the lock costs nanoseconds
        lock = self._swap_lock if self._swap_lock is not None else _NO_LOCK

        def _run():
            with lock:
                bank = self._bank_ref()
                B = select_shape(len(requests), self._programs.ladder)
                batch, degraded = self._assemble(requests, bank, B)
                if self._partial:
                    # fe + terms fetched as ONE batched transfer — the
                    # readback budget is unchanged in shard mode
                    scores_dev = self._programs.score_partial(bank, batch)
                else:
                    scores_dev = self._programs.score(bank, batch)
                # the ONE counted device->host transfer for this batch
                scores = overlap.device_get(scores_dev)
            return bank, B, degraded, scores

        # the serving.dispatch reliability seam: dispatch is idempotent
        # (pure compute + readback), so a planned transient fault is
        # retried bitwise; an exhausted budget fails the batch's futures
        # with a SeamFailure NAMING the seam — one terminal outcome each
        bank, B, degraded, scores = io_call(
            "serving.dispatch", _run,
            detail=f"{len(requests)} request(s)",
        )
        t1 = time.perf_counter()
        self._admission.note_dispatch(rows=len(requests), busy_s=t1 - t0)
        n_degraded = 0
        n_ok = 0
        if self._partial:
            fe, terms = scores
            names = tuple(e[1] for e in term_entries(bank.spec))
            n_terms = len(names)
        traced = []
        collect_traces = tracing_enabled()
        for i, (req, fut) in enumerate(take):
            deg = bool(degraded[i])
            n_degraded += int(deg)
            if collect_traces and req.trace_id is not None:
                # per-request trace contexts ride the DISPATCH span as
                # one attr; the serving.score leaves are synthesized at
                # export (trace.expand_spans) — the hot path pays one
                # tuple per traced request, not one span
                traced.append((req.trace_id, req.parent_span, deg))
            if self._partial:
                # vector form: the f32 term row rides the outcome as-is
                # (no per-float dict build); the JSON wire materializes
                # float(np.float32) lazily — the exact f64 of the f32
                # bits — and the binary wire ships the raw bits
                n_ok += int(_resolve(fut, result=PartialScore.from_vector(
                    float(fe[i]),
                    names,
                    terms[i, :n_terms],
                    offset=req.offset,
                    degraded=deg,
                    generation=bank.generation,
                )))
            else:
                n_ok += int(_resolve(fut, result=ScoreOutcome(
                    float(scores[i]), degraded=deg,
                    generation=bank.generation,
                )))
        if n_ok:
            self._flight.note_terminal(
                "ok", generation=bank.generation, n=n_ok
            )
        # stamped AFTER the device section from timestamps already in
        # hand — record_span is a no-op branch when tracing is off and
        # a lock-free ring append when on, so the locked device section
        # above acquires nothing new.
        record_span(
            "serving.dispatch", t0, t1,
            shape=B, occupancy=len(requests), generation=bank.generation,
            partial=self._partial,
            **({"traces": traced} if traced else {}),
        )
        if self._metrics is not None:
            if n_degraded:
                self._metrics.record_degraded(n_degraded)
            self._metrics.record_dispatch(
                shape=B,
                occupancy=len(requests),
                queue_wait_s=t0 - min(r._enqueue_t for r in requests),
                device_s=t1 - t0,
                generation=bank.generation,
            )
            done = time.perf_counter()
            for req in requests:
                self._metrics.record_latency(done - req._enqueue_t)
