"""Micro-batching request loop: coalesce, pad, dispatch, demux.

The Podracer idiom (PAPERS.md): the serving loop is its own component —
it never blocks on training, model publication, or artifact IO. Here it
also never blocks on the DEVICE more than one dispatch at a time:
concurrent submitters enqueue; a single dispatcher thread drains the
queue into the smallest padded ladder shape that fits, runs ONE
precompiled device program, performs exactly ONE counted readback
(``overlap.device_get``) and resolves each request's future with its own
row.

Batching is continuous by default (``max_wait_s = 0``): whatever
accumulated while the previous dispatch executed forms the next batch,
so an idle service answers a lone request at shape 1 with zero imposed
wait, and a saturated service coalesces to the ladder cap without any
timer tuning. ``max_wait_s > 0`` forces coalescing for bursty open-loop
sources.

Request assembly lives here too: :func:`requests_from_dataset` turns a
``GameDataset`` into per-row requests (the file-replay path — identical
padding/width to the batch scorer, which is what the bitwise parity bar
needs), and :func:`request_from_record` maps one raw record dict
through prebuilt index maps (the stdin path).
"""

from __future__ import annotations

import contextlib
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional

import numpy as np

from photon_ml_tpu.parallel import overlap
from photon_ml_tpu.serving.model_bank import ModelBank
from photon_ml_tpu.serving.programs import (
    RequestBatch,
    ServingPrograms,
    select_shape,
)

__all__ = [
    "ScoreRequest",
    "MicroBatcher",
    "request_from_record",
    "requests_from_dataset",
]

_NO_LOCK = contextlib.nullcontext()


@dataclass
class ScoreRequest:
    """One scoring request: per-shard padded (indices, values) rows at
    the bank's shard widths, plus RAW entity ids. Entity ids resolve to
    bank rows at DISPATCH time, against the bank the batch actually
    runs on — never at build time. A request is therefore valid across
    hot swaps: a generation flip that keeps device shapes but changes
    the entity set (the exact case entity padding preserves) re-resolves
    every queued and replayed request against the new generation's rows
    instead of scoring stale ones."""

    uid: str
    indices: Dict[str, np.ndarray]  # shard -> int32 [k]
    values: Dict[str, np.ndarray]  # shard -> float32 [k]
    entity_ids: Dict[str, Optional[str]]  # id type -> raw id (None = absent)
    offset: float = 0.0
    # passthrough columns for the scores artifact (batch-scorer record
    # layout); never touch the device
    label: Optional[float] = None
    weight: float = 1.0
    metadata: Optional[Dict[str, str]] = None
    _enqueue_t: float = field(default=0.0, repr=False)


def request_from_record(
    record: Mapping,
    bank: ModelBank,
    shard_configs,
    *,
    has_response: bool = True,
) -> ScoreRequest:
    """One raw GameExample-shaped dict -> ScoreRequest through the
    bank's index maps (the stdin/JSON path; the Avro replay path goes
    through :func:`requests_from_dataset` instead)."""
    from photon_ml_tpu.game.data import record_response
    from photon_ml_tpu.utils.index_map import feature_key, intercept_key

    indices: Dict[str, np.ndarray] = {}
    values: Dict[str, np.ndarray] = {}
    for cfg in shard_configs:
        imap = bank.index_maps[cfg.shard_id]
        k = bank.shard_widths[cfg.shard_id]
        ix = np.zeros((k,), np.int32)
        vs = np.zeros((k,), np.float32)
        pos = 0
        for bag in cfg.feature_bags:
            for f in record.get(bag) or []:
                j = imap.get_index(feature_key(f["name"], f["term"]))
                if j < 0:
                    continue  # unknown feature: dropped, like the builder
                if pos >= k:
                    raise ValueError(
                        f"request {record.get('uid')!r} exceeds shard "
                        f"{cfg.shard_id!r} width {k}"
                    )
                ix[pos] = j
                vs[pos] = float(f["value"])
                pos += 1
        if cfg.add_intercept:
            j = imap.get_index(intercept_key())
            if j >= 0:
                if pos >= k:
                    raise ValueError(
                        f"request {record.get('uid')!r} exceeds shard "
                        f"{cfg.shard_id!r} width {k}"
                    )
                ix[pos] = j
                vs[pos] = 1.0
                pos += 1
        indices[cfg.shard_id] = ix
        values[cfg.shard_id] = vs
    # raw ids only — the dispatcher resolves them against whichever
    # bank generation the batch runs on. A record missing an id type
    # scores FE-only (unknown-entity semantics), and its key is OMITTED
    # from metadata (never the literal "None"), matching the dataset
    # path's records.
    entity_ids: Dict[str, Optional[str]] = {}
    for t in bank.re_types:
        v = record.get(t)
        if v is None:
            v = (record.get("metadataMap") or {}).get(t)
        entity_ids[t] = None if v is None else str(v)
    off = record.get("offset")
    wgt = record.get("weight")
    uid = record.get("uid")
    meta = {t: e for t, e in entity_ids.items() if e is not None}
    return ScoreRequest(
        uid="" if uid is None else str(uid),
        indices=indices,
        values=values,
        entity_ids=entity_ids,
        offset=0.0 if off is None else float(off),
        label=(
            record_response(record, True) if has_response else None
        ),
        weight=1.0 if wgt is None else float(wgt),
        metadata=meta or None,
    )


def requests_from_dataset(ds, bank: ModelBank) -> List[ScoreRequest]:
    """Per-row requests from a GameDataset built with the bank's index
    maps — row slices are views. Requests carry the RAW entity id
    strings (the dataset's codes index the dataset's own entity table,
    not the model's); the dispatcher resolves id -> bank row against
    whichever generation each batch runs on, so a replayed trace stays
    correct across hot swaps whose entity sets differ. ``bank`` pins the
    per-shard widths the AOT program shapes were compiled for."""
    for sid, k in bank.shard_widths.items():
        sd = ds.shards.get(sid)
        if sd is None or sd.indices.shape[1] != k:
            got = None if sd is None else sd.indices.shape[1]
            raise ValueError(
                f"dataset shard {sid!r} width {got!r} != bank request "
                f"width {k} (the trace must be built at the bank's "
                "padded layout)"
            )
    out: List[ScoreRequest] = []
    id_types = sorted(ds.entity_indexes)
    for i in range(ds.num_real_rows):
        entity_ids = {
            t: (
                ds.entity_indexes[t].ids[int(ds.entity_codes[t][i])]
                if int(ds.entity_codes[t][i]) >= 0
                else None
            )
            for t in id_types
        }
        meta = {t: e for t, e in entity_ids.items() if e is not None}
        out.append(
            ScoreRequest(
                uid=ds.uids[i],
                indices={
                    sid: sd.indices[i] for sid, sd in ds.shards.items()
                },
                values={
                    sid: sd.values[i] for sid, sd in ds.shards.items()
                },
                entity_ids=entity_ids,
                offset=float(ds.offsets[i]),
                label=float(ds.labels[i]),
                weight=float(ds.weights[i]),
                metadata=meta or None,
            )
        )
    return out


class MicroBatcher:
    """Bounded-queue micro-batcher over a live bank reference.

    ``bank_ref`` is a zero-arg callable returning the CURRENT ModelBank
    — the hot-swap seam: the dispatcher reads it once per dispatch, so a
    generation flip lands exactly on a batch boundary, never inside one.
    """

    def __init__(
        self,
        bank_ref: Callable[[], ModelBank],
        programs: ServingPrograms,
        metrics=None,
        *,
        max_wait_s: float = 0.0,
        max_queue: int = 4096,
        swap_lock: Optional[threading.Lock] = None,
    ):
        self._bank_ref = bank_ref
        self._programs = programs
        self._metrics = metrics
        # exclusion against a DONATING hot swap (see ServingModel.
        # dispatch_lock): inferred from a bound ServingModel.current
        # bank_ref so the safe wiring is the default wiring
        owner = getattr(bank_ref, "__self__", None)
        self._swap_lock = (
            swap_lock
            if swap_lock is not None
            else getattr(owner, "dispatch_lock", None)
        )
        self._max_wait_s = float(max_wait_s)
        self._max_queue = int(max_queue)
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._space = threading.Condition(self._lock)
        self._queue: List = []  # (ScoreRequest, Future)
        self._closed = False
        self._worker = threading.Thread(
            target=self._dispatch_loop,
            name="photon-serving-dispatch",
            daemon=True,
        )
        self._worker.start()

    # -- submit side ---------------------------------------------------------

    def submit(self, request: ScoreRequest) -> Future:
        """Enqueue one request; blocks only when the bounded queue is
        full (backpressure, not unbounded memory)."""
        fut: Future = Future()
        request._enqueue_t = time.perf_counter()
        with self._lock:
            while len(self._queue) >= self._max_queue and not self._closed:
                self._space.wait()
            if self._closed:
                raise RuntimeError("batcher is closed")
            self._queue.append((request, fut))
            self._nonempty.notify()
        return fut

    def score(self, request: ScoreRequest) -> float:
        """Closed-loop convenience: submit and wait."""
        return self.submit(request).result()

    def close(self) -> None:
        """Drain the queue, stop the dispatcher. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._nonempty.notify_all()
            self._space.notify_all()
        self._worker.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- dispatch side -------------------------------------------------------

    def _take(self) -> List:
        """Block until work exists, optionally linger ``max_wait_s`` for
        coalescing, then claim up to ``max(ladder)`` requests."""
        cap = self._programs.ladder[-1]
        with self._lock:
            while not self._queue and not self._closed:
                self._nonempty.wait()
            if not self._queue:
                return []  # closed and drained
            if self._max_wait_s > 0.0:
                deadline = self._queue[0][0]._enqueue_t + self._max_wait_s
                while (
                    len(self._queue) < cap
                    and not self._closed
                    and (remaining := deadline - time.perf_counter()) > 0
                ):
                    self._nonempty.wait(timeout=remaining)
            take = self._queue[:cap]
            del self._queue[:cap]
            self._space.notify_all()
            return take

    def _dispatch_loop(self) -> None:
        while True:
            take = self._take()
            if not take:
                return
            try:
                self._dispatch(take)
            except BaseException as e:  # resolve, never wedge submitters
                for _req, fut in take:
                    if not fut.done():
                        fut.set_exception(e)

    def _assemble(self, requests: List[ScoreRequest], bank: ModelBank,
                  B: int) -> RequestBatch:
        n = len(requests)
        indices: Dict[str, np.ndarray] = {}
        values: Dict[str, np.ndarray] = {}
        for sid, k in bank.shard_widths.items():
            ix = np.zeros((B, k), np.int32)
            vs = np.zeros((B, k), np.float32)
            for i, r in enumerate(requests):
                ix[i] = r.indices[sid]
                vs[i] = r.values[sid]
            indices[sid] = ix
            values[sid] = vs
        # resolve raw entity ids against the bank THIS batch dispatches
        # on (one vectorized rows_of per id type): requests pre-built or
        # queued before a hot swap score the new generation's rows, not
        # stale build-time ones
        codes: Dict[str, np.ndarray] = {}
        for t in bank.re_types:
            c = np.full((B,), -1, np.int32)
            present: List[int] = []
            ids: List[str] = []
            for i, r in enumerate(requests):
                e = r.entity_ids.get(t) if r.entity_ids else None
                if e is not None:
                    present.append(i)
                    ids.append(e)
            if present:
                c[np.asarray(present)] = bank.entity_rows[t].rows_of(ids)
            codes[t] = c
        offsets = np.zeros((B,), np.float32)
        offsets[:n] = [r.offset for r in requests]
        return RequestBatch(
            indices=indices, values=values, codes=codes, offsets=offsets
        )

    def _dispatch(self, take: List) -> None:
        t0 = time.perf_counter()
        requests = [r for r, _ in take]
        # the whole device section (bank read -> assemble -> execute ->
        # readback) is exclusive with a donating hot swap, so a flip
        # lands BETWEEN batches and can never invalidate the buffers of
        # one in flight; uncontended, the lock costs nanoseconds
        lock = self._swap_lock if self._swap_lock is not None else _NO_LOCK
        with lock:
            bank = self._bank_ref()
            B = select_shape(len(requests), self._programs.ladder)
            batch = self._assemble(requests, bank, B)
            scores_dev = self._programs.score(bank, batch)
            # the ONE counted device->host transfer for this whole batch
            scores = overlap.device_get(scores_dev)
        t1 = time.perf_counter()
        for i, (req, fut) in enumerate(take):
            if not fut.done():
                fut.set_result(float(scores[i]))
        if self._metrics is not None:
            self._metrics.record_dispatch(
                shape=B,
                occupancy=len(requests),
                queue_wait_s=t0 - min(r._enqueue_t for r in requests),
                device_s=t1 - t0,
                generation=bank.generation,
            )
            done = time.perf_counter()
            for req in requests:
                self._metrics.record_latency(done - req._enqueue_t)
