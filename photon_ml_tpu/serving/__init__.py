"""Online scoring service: the low-latency request path over
device-resident GAME model banks.

Seven pieces, composed by ``cli/serving_driver.py``:

- :mod:`photon_ml_tpu.serving.model_bank` — fixed/random-effect
  coefficients as padded device arrays + O(1) host entity->row index;
- :mod:`photon_ml_tpu.serving.programs` — the AOT fixed-shape program
  ladder (every batch shape compiled before it can reach the hot path);
- :mod:`photon_ml_tpu.serving.admission` — deadlines, the load-shed
  predictor, and the named terminal outcomes every request resolves to;
- :mod:`photon_ml_tpu.serving.batcher` — micro-batching dispatch loop,
  exactly one counted readback per dispatched batch, deadline drops
  before dispatch, FE-only graceful degradation, bounded drain;
- :mod:`photon_ml_tpu.serving.frontend` — the TCP JSON-lines accept
  loop (bounded reads, per-connection writers, readiness/liveness,
  SIGTERM drain);
- :mod:`photon_ml_tpu.serving.swap` — zero-copy hot swap of model
  generations with quarantine + rollback on poisoned artifacts;
- :mod:`photon_ml_tpu.serving.metrics` — p50/p99 latency, QPS,
  occupancy, shed/deadline/degraded/drain accounting for metrics.json;
- :mod:`photon_ml_tpu.serving.shard_server` — the same stack serving
  ONE entity shard in partial-score mode, plus the router's control
  plane (topology discovery, two-step generation flip);
- :mod:`photon_ml_tpu.serving.routing` — the scatter/gather tier in
  front of a shard-server fleet: ownership-ruled fan-out, bitwise
  f32 recomposition, per-shard degradation, the hot-entity cache.
"""

from photon_ml_tpu.serving.admission import (  # noqa: F401
    AdmissionController,
    BatcherClosed,
    DeadlineExceeded,
    DrainTimeout,
    NoShardAvailable,
    PartialScore,
    RequestShed,
    ScoreOutcome,
    ServingError,
)
from photon_ml_tpu.serving.batcher import (  # noqa: F401
    DrainReport,
    MicroBatcher,
    ScoreRequest,
    request_from_record,
    requests_from_dataset,
)
from photon_ml_tpu.serving.frontend import ServingFrontend  # noqa: F401
from photon_ml_tpu.serving.metrics import ServingMetrics  # noqa: F401
from photon_ml_tpu.serving.model_bank import (  # noqa: F401
    DEFAULT_ENTITY_PAD,
    EntityRowIndex,
    ModelBank,
    bank_from_arrays,
    build_model_bank,
)
from photon_ml_tpu.serving.programs import (  # noqa: F401
    DEFAULT_LADDER,
    RequestBatch,
    ServingPrograms,
    select_shape,
)
from photon_ml_tpu.serving.routing import (  # noqa: F401
    HotEntityCache,
    RoutedScore,
    RouterMetrics,
    RoutingPolicy,
    ShardHealth,
    ShardRouter,
    TcpShardTransport,
)
from photon_ml_tpu.serving.shard_server import (  # noqa: F401
    ShardServer,
    make_shard_ops,
    shard_topology,
)
from photon_ml_tpu.serving.swap import (  # noqa: F401
    ServingModel,
    SwapResult,
    load_model_artifact,
)
