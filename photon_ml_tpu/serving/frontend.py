"""Network front-end: a TCP JSON-lines accept loop in front of
``MicroBatcher.submit``.

The wire protocol is one JSON object per ``\\n``-terminated line, both
directions — the same GameExample-shaped records the stdin replay path
consumes (plus an optional ``deadline_ms``), so a request that works
through ``--request-paths -`` works verbatim over a socket. Responses
carry exactly one terminal ``status`` per request line:

- ``{"uid":…, "status":"ok", "score":…, "generation":…, "degraded":…}``
- ``{"uid":…, "status":"shed", "error":"SHED", "message":…}``
- ``{"uid":…, "status":"deadline_exceeded", "error":"DEADLINE_EXCEEDED",…}``
- ``{"uid":…, "status":"error", "error":<NAME>, "message":…}`` — named
  errors (``BAD_REQUEST``, ``READ_FAULT``, ``DRAIN_TIMEOUT``,
  ``DISPATCH_FAILED``, ``CLOSED``, ``INTERNAL``), never a crash and
  never silence.

The same port also speaks photon-wire's length-prefixed binary framing
(:mod:`photon_ml_tpu.serving.wire`): the reader sniffs each
connection's FIRST byte — the frame magic selects binary for that
connection, anything else stays JSON-lines — so old clients and binary
routers coexist on one accept loop. Binary responses reuse the same
terminal-status dicts, hot paths (scores, partials, trace drains) ride
raw little-endian float buffers, and both protocols share one framing
cap (``max_frame_bytes``).

Control lines ``{"op": "status"|"ready"|"live"}`` answer the lifecycle
questions without touching the device: **readiness** (bank loaded +
ladder warm — ``ServingModel.ready()``) says "this replica may take
traffic"; **liveness** (the dispatcher heartbeat — beating even when
idle) says "this replica is not wedged". A load balancer drains on
not-ready and restarts on not-live; conflating them turns every staging
pause into a restart. ``{"op": "quarantine_re", "re_type": …}`` is the
operator's graceful-degradation lever: the named random-effect bank of
the CURRENT generation stops being consulted and affected requests
score FE-only with ``degraded: true`` until the next swap.

Robustness invariants (the "serving under fire" contract):

- **Bounded reads.** Per-connection reads are buffered with a hard
  ``max_line_bytes`` cap — an unframed flood gets a named error and the
  connection closed, never unbounded host memory.
- **Per-connection writer threads.** Responses are demuxed onto a
  bounded per-connection queue drained by a writer thread with a send
  timeout: a slow (or stalled) client backs up only its OWN queue; when
  that overflows the connection is dropped and counted
  (``frontend.connections_dropped_slow``) — the dispatcher and every
  other client are unaffected.
- **Fault seam.** Every received line crosses the
  ``serving.frontend.read`` reliability seam: a planned fault surfaces
  as a ``READ_FAULT`` error response on that connection, bit-for-bit
  reproducible from the fault plan, with the service still up.
- **Drain protocol.** ``stop_accepting()`` (SIGTERM) closes the
  listener and refuses new score lines with ``CLOSED``; the driver then
  drains the batcher within its budget (leftovers fail with
  ``DRAIN_TIMEOUT``) and ``close()`` flushes every writer queue and
  joins every connection thread — zero hung futures, zero leaked
  connections (``open_connections()`` is asserted by the chaos arm).
"""

from __future__ import annotations

import json
import os
import queue
import socket
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.obs.flight_recorder import flight_recorder
from photon_ml_tpu.obs.trace import TRACE_KEY, start_span, wire_context
from photon_ml_tpu.serving.admission import (
    DeadlineExceeded,
    DrainTimeout,
    PartialScore,
    RequestShed,
    ServingError,
)
from photon_ml_tpu.serving.batcher import MicroBatcher, request_from_record
from photon_ml_tpu.serving import wire

__all__ = ["ServingFrontend", "READ_SEAM"]

READ_SEAM = "serving.frontend.read"

# Framing cap: a line that exceeds this without a newline is not a
# request, it is a flood — named error, connection closed. The SAME cap
# refuses a binary frame length (wire.py); both resolve through
# wire.resolve_max_frame_bytes (explicit > PHOTON_MAX_FRAME_BYTES > 1 MiB).
DEFAULT_MAX_LINE_BYTES = wire.DEFAULT_MAX_FRAME_BYTES
# Bounded per-connection response queue (slow-client protection).
DEFAULT_WRITER_QUEUE = 1024
# Socket poll period: every blocking socket wait wakes at this beat to
# observe shutdown — no untimed waits anywhere on the request path.
POLL_S = 0.25
# A client that cannot absorb one response within this budget is
# stalled; its connection is dropped rather than wedging the writer.
DEFAULT_SEND_TIMEOUT_S = 5.0

_STATUS_OPS = ("status", "ready", "readiness", "live", "liveness", "health")


def _error_response(uid, code: str, message: str) -> Dict[str, object]:
    return {
        "uid": uid,
        "status": "error",
        "error": code,
        "message": message,
    }


def _outcome_response(uid, outcome, *, binary: bool = False) -> Dict[str, object]:
    if isinstance(outcome, PartialScore):
        # shard-server mode: the scatter/gather half-score. Floats ride
        # JSON as shortest-round-trip doubles holding exact f32 values,
        # so the router's recomposition is bitwise. On a binary
        # connection the PartialScore itself rides to the writer thread
        # (_wire_partial) and its f32 term VECTOR is encoded in one
        # buffer copy — no per-term dict is ever built.
        if binary:
            return {
                "uid": uid,
                "status": "ok",
                "partial": True,
                "generation": outcome.generation,
                "degraded": outcome.degraded,
                "_wire_partial": outcome,
            }
        return {
            "uid": uid,
            "status": "ok",
            "partial": True,
            "fe": outcome.fe,
            "terms": dict(outcome.terms),
            "generation": outcome.generation,
            "degraded": outcome.degraded,
        }
    return {
        "uid": uid,
        "status": "ok",
        "score": float(outcome),
        "generation": getattr(outcome, "generation", 0),
        "degraded": bool(getattr(outcome, "degraded", False)),
    }


def _failure_response(uid, exc: BaseException) -> Dict[str, object]:
    from photon_ml_tpu.reliability import SeamFailure

    if isinstance(exc, RequestShed):
        return {
            "uid": uid, "status": "shed", "error": exc.code,
            "message": str(exc),
        }
    if isinstance(exc, DeadlineExceeded):
        return {
            "uid": uid, "status": "deadline_exceeded", "error": exc.code,
            "message": str(exc),
        }
    if isinstance(exc, DrainTimeout):
        return _error_response(uid, exc.code, str(exc))
    if isinstance(exc, ServingError):
        return _error_response(uid, exc.code, str(exc))
    if isinstance(exc, SeamFailure):
        return _error_response(uid, "DISPATCH_FAILED", str(exc))
    if isinstance(exc, TimeoutError):
        return _error_response(uid, "TIMEOUT", str(exc))
    return _error_response(uid, "INTERNAL", str(exc))


class _Connection:
    """One accepted socket: a reader thread (bounded framing ->
    request handling) and a writer thread (bounded queue -> coalesced
    sendall with a send timeout). Either side failing closes both.

    The reader sniffs the connection's FIRST byte: the wire magic
    selects binary framing for the whole connection; anything else is
    the JSON-lines protocol, unchanged."""

    def __init__(self, frontend: "ServingFrontend", sock: socket.socket,
                 peer: str):
        self.fe = frontend
        self.sock = sock
        self.peer = peer
        # single-writer atomic publish: the reader thread flips this to
        # "binary" ONCE at first-byte sniff, before any request (and so
        # any response the writer could encode) exists on the connection
        self.proto = "json"  # photon: guarded-by(atomic)
        self.outq: "queue.Queue" = queue.Queue(
            maxsize=frontend.writer_queue_max
        )
        self.closing = threading.Event()
        self.pending = 0
        self._pending_lock = threading.Lock()
        sock.settimeout(POLL_S)
        self.reader = threading.Thread(
            target=self._read_loop, name=f"photon-fe-read-{peer}",
            daemon=True,
        )
        self.writer = threading.Thread(
            target=self._write_loop, name=f"photon-fe-write-{peer}",
            daemon=True,
        )
        self.reader.start()
        self.writer.start()

    # -- response side -------------------------------------------------------

    def send(self, response: Dict[str, object]) -> None:
        """Enqueue one response; a full queue means THIS client is not
        keeping up — drop the connection (counted), never block the
        caller (which may be the dispatcher's done-callback)."""
        try:
            self.outq.put_nowait(response)
        except queue.Full:
            self.fe._note("connections_dropped_slow")
            self.closing.set()

    def _note_pending(self, delta: int) -> None:
        with self._pending_lock:
            self.pending += delta

    def _write_loop(self) -> None:
        out = bytearray()  # reused encode buffer: grows once, kept hot
        while True:
            try:
                resp = self.outq.get(timeout=POLL_S)
            except queue.Empty:
                if self.closing.is_set():
                    with self._pending_lock:
                        drained = self.pending == 0
                    if drained and self.outq.empty():
                        break
                continue
            # coalesce the backlog: every response already queued rides
            # the SAME sendall — one syscall per burst, not per response
            batch = [resp]
            while True:
                try:
                    batch.append(self.outq.get_nowait())
                except queue.Empty:
                    break
            del out[:]
            if self.proto == "binary":
                for r in batch:
                    wire.append_response(out, r)
            else:
                for r in batch:
                    out += json.dumps(r).encode("utf-8")
                    out += b"\n"
            try:
                self.sock.settimeout(DEFAULT_SEND_TIMEOUT_S)
                self.sock.sendall(out)
                self.sock.settimeout(POLL_S)
            except OSError:
                self.fe._note("connections_dropped_slow")
                self.closing.set()
                break
            if len(batch) > 1:
                self.fe._note("coalesced_responses", len(batch) - 1)
            if self.fe.metrics is not None:
                for r in batch:
                    self.fe.metrics.record_response(str(r.get("status")))
        self._shutdown_socket()

    # -- request side --------------------------------------------------------

    def _read_loop(self) -> None:
        # first-byte protocol sniff: a binary client's very first byte
        # is the frame magic — not a legal first byte of any JSON-lines
        # request — so one byte decides the connection's protocol and
        # JSON clients keep working unchanged on the same port
        buf = b""
        while not self.closing.is_set() and not buf:
            try:
                buf = self.sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError:
                self.closing.set()
                return
            if not buf:
                self.closing.set()
                return  # EOF before any byte
        if buf and buf[0] == wire.MAGIC:
            self.proto = "binary"
            self._read_frames(buf)
        else:
            self._read_lines(buf)
        self.closing.set()

    def _read_lines(self, buf: bytes) -> None:
        from photon_ml_tpu.reliability import (
            InjectedCorruption,
            InjectedFault,
            inject,
        )

        while not self.closing.is_set():
            nl = buf.find(b"\n")
            if nl < 0:
                if len(buf) > self.fe.max_frame_bytes:
                    # unframed flood: named error, then close — framing
                    # cannot be recovered past the cap
                    self.fe._note("oversized")
                    self.send(_error_response(
                        None, "BAD_REQUEST",
                        f"line exceeds {self.fe.max_frame_bytes} bytes",
                    ))
                    break
                try:
                    chunk = self.sock.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    break
                if not chunk:
                    break  # EOF
                buf += chunk
                continue
            line, buf = buf[:nl], buf[nl + 1:]
            if not line.strip():
                continue
            self.fe._note("lines")
            try:
                # the reliability seam: one crossing per received line,
                # so "fail the 3rd read with EIO" replays exactly
                inject(READ_SEAM, detail=self.peer)
            except (InjectedFault, InjectedCorruption, OSError) as e:
                self.fe._note("read_faults")
                self.send(_error_response(None, "READ_FAULT", str(e)))
                continue
            self._handle_line(line)

    def _read_frames(self, buf: bytes) -> None:
        from photon_ml_tpu.reliability import (
            InjectedCorruption,
            InjectedFault,
            inject,
        )

        decoder = wire.FrameDecoder(self.fe.max_frame_bytes)
        while not self.closing.is_set():
            try:
                frames = decoder.feed(buf)
            except wire.WireError as e:
                # framing provably lost (bad magic/version) or a giant
                # announced length: the binary twin of the oversized
                # line — named refusal, then close (framing cannot be
                # recovered; a lying length never buffers its payload)
                self.fe._note(
                    "oversized" if e.kind == "oversized" else "malformed"
                )
                self.send(_error_response(None, "BAD_REQUEST", str(e)))
                break
            for mtype, payload in frames:
                self.fe._note("lines")
                try:
                    # same seam, same cadence: one crossing per frame
                    inject(READ_SEAM, detail=self.peer)
                except (InjectedFault, InjectedCorruption, OSError) as e:
                    self.fe._note("read_faults")
                    self.send(_error_response(None, "READ_FAULT", str(e)))
                    continue
                self._handle_frame(mtype, payload)
            try:
                buf = self.sock.recv(1 << 16)
            except socket.timeout:
                buf = b""
                continue
            except OSError:
                break
            if not buf:
                break  # EOF (a mid-frame disconnect just drops the tail)

    def _handle_frame(self, mtype: int, payload: bytes) -> None:
        try:
            if mtype == wire.MSG_SCORE_REQUEST:
                obj = wire.decode_score_request(payload)
            elif mtype == wire.MSG_JSON:
                obj = wire.decode_message(mtype, payload)
            else:
                raise wire.WireError(
                    f"unexpected message type 0x{mtype:02x} on the "
                    "request side"
                )
        except wire.WireError as e:
            self.fe._note("malformed")
            self.send(_error_response(None, "BAD_REQUEST", str(e)))
            return
        self._handle_obj(obj)

    def _handle_line(self, line: bytes) -> None:
        try:
            obj = json.loads(line.decode("utf-8"))
            if not isinstance(obj, dict):
                raise ValueError("request must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            self.fe._note("malformed")
            self.send(_error_response(None, "BAD_REQUEST", str(e)))
            return
        self._handle_obj(obj)

    def _handle_obj(self, obj: Dict) -> None:
        op = obj.get("op")
        if op is not None:
            self.fe._note("control")
            if str(op) in _STATUS_OPS:
                self.send(self.fe.status_response(str(op)))
            elif str(op) == "metrics":
                # live wire exposition of the process metrics plane:
                # the registry's merged snapshot (instruments + every
                # subsystem view), or Prometheus-style text with
                # {"format": "prometheus"} — without a registry wired
                # the op still answers from the serving accumulator
                self.send(self.fe.metrics_response(obj))
            elif str(op) == "trace":
                # incremental span drain for the fleet collector:
                # cursor/seq-keyed so polls never duplicate or drop
                # spans, plus the (wall, perf) epoch + epoch-mapped
                # "now" for NTP-style clock-skew estimation
                self.send(self.fe.trace_response(obj))
            elif str(op) == "flight":
                rec = flight_recorder()
                self.send({
                    "uid": obj.get("uid"),
                    "status": "ok",
                    "op": op,
                    "flight": rec.snapshot(),
                    "conservation": rec.check_conservation(),
                })
            elif str(op) == "dump_flight":
                if not self.fe.flight_dump_path:
                    self.send(_error_response(
                        obj.get("uid"), "BAD_REQUEST",
                        "no flight dump path configured (--obs-dir)",
                    ))
                    return
                path = flight_recorder().dump(
                    self.fe.flight_dump_path, reason="operator op"
                )
                self.send({
                    "uid": obj.get("uid"),
                    "status": "ok" if path else "error",
                    "op": op,
                    "path": path,
                })
            elif str(op) == "quarantine_re":
                # operator lever for graceful degradation: mark one RE
                # coordinate of the CURRENT generation unusable —
                # affected requests score FE-only with degraded=True
                # until the next swap installs a clean bank
                re_type = str(obj.get("re_type"))
                try:
                    self.fe.serving_model.quarantine_re(re_type)
                except ValueError as e:
                    self.send(_error_response(
                        obj.get("uid"), "BAD_REQUEST", str(e)
                    ))
                    return
                self.send({
                    "status": "ok",
                    "op": op,
                    "re_type": re_type,
                    "generation": self.fe.serving_model.generation,
                })
            elif str(op) == "rollback":
                # operator lever: flip back to the parent generation
                # (registry watcher required — a replay-mode service
                # has no lineage to roll along)
                if self.fe.rollback_handler is None:
                    self.send(_error_response(
                        obj.get("uid"), "BAD_REQUEST",
                        "no registry watcher attached: rollback needs "
                        "generation lineage",
                    ))
                    return
                ok = bool(self.fe.rollback_handler())
                self.send({
                    "status": "ok" if ok else "error",
                    "op": op,
                    "rolled_back": ok,
                    "generation": self.fe.serving_model.generation,
                })
            elif str(op) in self.fe.extra_ops:
                # extension ops (shard topology / two-step swap):
                # handler failures become named responses, never a
                # dropped line or a dead connection
                try:
                    resp = self.fe.extra_ops[str(op)](obj)
                except Exception as e:
                    resp = _error_response(obj.get("uid"), "INTERNAL",
                                           str(e))
                self.send(resp)
            else:
                self.send(_error_response(
                    obj.get("uid"), "BAD_REQUEST", f"unknown op {op!r}"
                ))
            return
        self.fe._handle_score(self, obj)

    # -- teardown ------------------------------------------------------------

    def _shutdown_socket(self) -> None:
        try:
            self.sock.close()
        except OSError:
            self.fe._note("socket_close_errors")
        self.fe._forget(self)

    def join(self, timeout_s: float) -> None:
        self.closing.set()
        self.reader.join(timeout=timeout_s)
        self.writer.join(timeout=timeout_s)


class ServingFrontend:
    """The accept loop + connection registry in front of one
    :class:`MicroBatcher`. See the module docstring for the protocol
    and the robustness contract."""

    def __init__(
        self,
        batcher: MicroBatcher,
        serving_model,
        shard_configs,
        *,
        metrics=None,
        host: str = "127.0.0.1",
        port: int = 0,
        has_response: bool = True,
        max_line_bytes: Optional[int] = None,
        max_frame_bytes: Optional[int] = None,
        writer_queue_max: int = DEFAULT_WRITER_QUEUE,
        on_completion: Optional[Callable[[int], None]] = None,
        on_outcome: Optional[Callable[[bool, bool, bool], None]] = None,
        lineage_provider: Optional[Callable[[], Dict]] = None,
        rollback_handler: Optional[Callable[[], bool]] = None,
        extra_ops: Optional[Dict[str, Callable[[Dict], Dict]]] = None,
        status_extra: Optional[Callable[[], Dict]] = None,
        metrics_registry=None,
        flight_dump_path: Optional[str] = None,
    ):
        self.batcher = batcher
        self.serving_model = serving_model
        self.shard_configs = shard_configs
        self.metrics = metrics
        # live telemetry exposition (obs/): {"op": "metrics"} serves
        # the process registry's merged snapshot (JSON or Prometheus
        # text); {"op": "flight"} serves the flight-recorder ring +
        # conservation verdict; {"op": "dump_flight"} persists it to
        # the operator-configured path (never a wire-supplied one)
        self.metrics_registry = metrics_registry
        self.flight_dump_path = flight_dump_path
        self.host = host
        self.has_response = bool(has_response)
        # ONE framing cap for both protocols (JSON line length == binary
        # frame length): explicit arg > PHOTON_MAX_FRAME_BYTES env >
        # 1 MiB. max_line_bytes is the legacy spelling of the same knob.
        self.max_frame_bytes = wire.resolve_max_frame_bytes(
            max_frame_bytes if max_frame_bytes is not None
            else max_line_bytes
        )
        self.max_line_bytes = self.max_frame_bytes
        self.writer_queue_max = int(writer_queue_max)
        self.on_completion = on_completion
        # continuous-retraining hooks (registry.watcher): per-outcome
        # health feed (ok, degraded, failed), generation-lineage block
        # for the status op, and the operator rollback lever
        self.on_outcome = on_outcome
        self.lineage_provider = lineage_provider
        self.rollback_handler = rollback_handler
        # extension seam (serving/shard_server.py): extra control ops
        # (op name -> handler(request dict) -> response dict, which MUST
        # echo the request's uid for routed demux) and an extra block
        # merged into every status payload (shard topology)
        self.extra_ops = dict(extra_ops or {})
        self.status_extra = status_extra
        self._completed = 0
        self._completed_lock = threading.Lock()
        self._conns: List[_Connection] = []
        self._conns_lock = threading.Lock()
        self._accepting = threading.Event()
        self._stopped = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
        )
        self._listener.bind((host, int(port)))
        self.port = self._listener.getsockname()[1]
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingFrontend":
        self._listener.listen(128)
        self._listener.settimeout(POLL_S)
        self._accepting.set()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="photon-fe-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def stop_accepting(self) -> None:
        """SIGTERM step 1: close the listener; established connections
        keep receiving responses for already-admitted work, but new
        score lines are refused with ``CLOSED``.

        The shutdown() wakes an accept() blocked in another thread —
        CPython defers the actual close until accept returns, so
        without it the port would keep accepting for up to one poll
        period after "stop"."""
        self._accepting.clear()
        self._stopped.set()
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # already shut down / never listened
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2 * POLL_S + 1.0)
        try:
            self._listener.close()
        except OSError:
            self._note("socket_close_errors")

    def close(self, timeout_s: float = DEFAULT_SEND_TIMEOUT_S) -> None:
        """Final teardown: flush + close every connection, join every
        thread (all bounded). Call after the batcher has drained so
        every pending future already holds its terminal outcome."""
        self.stop_accepting()
        with self._conns_lock:
            conns = list(self._conns)
        deadline = time.perf_counter() + max(timeout_s, 0.1)
        for c in conns:
            c.join(max(deadline - time.perf_counter(), 0.1))
        if self._accept_thread is not None:
            self._accept_thread.join(
                timeout=max(deadline - time.perf_counter(), 0.1)
            )
        with self._conns_lock:
            leaked = list(self._conns)
        for c in leaked:
            c._shutdown_socket()

    def open_connections(self) -> int:
        with self._conns_lock:
            return len(self._conns)

    def completed(self) -> int:
        with self._completed_lock:
            return self._completed

    @property
    def draining(self) -> bool:
        return self._stopped.is_set()

    def status_response(self, op: str = "status") -> Dict[str, object]:
        """Readiness + liveness in one payload: ``ready`` gates traffic
        (bank live, ladder warm), ``alive``/``heartbeat_age_s`` gate
        restarts (dispatcher beating). With a registry watcher attached
        the payload also carries generation LINEAGE (registry
        generation, parent chain, last swap/rollback outcome) — the
        operator's one-stop "what exactly is serving right now"."""
        out = {
            "status": "ok",
            "op": op,
            "ready": bool(
                self.serving_model.ready()
                and not self.batcher.draining
                and not self.batcher.closed
                and not self._stopped.is_set()
            ),
            "alive": self.batcher.alive(),
            "heartbeat_age_s": round(self.batcher.heartbeat_age_s(), 4),
            "draining": self._stopped.is_set() or self.batcher.draining,
            "generation": self.serving_model.generation,
            "queue_depth": self.batcher.queue_depth(),
            # the wire contract: what this frontend speaks (routers
            # negotiate the data plane from the same block in topology)
            # and the frame/line cap it enforces
            "wire": {
                "protocols": list(wire.WIRE_PROTOCOLS),
                "version": wire.WIRE_VERSION,
                "max_frame_bytes": self.max_frame_bytes,
            },
        }
        history = getattr(self.serving_model, "swap_history", None)
        if history:
            last = history[-1]
            out["last_swap"] = {
                "ok": last.ok,
                "generation": last.generation,
                "donated": last.donated,
                "rolled_back": last.rolled_back,
                "error": last.error,
            }
        if self.lineage_provider is not None:
            try:
                out["registry"] = self.lineage_provider()
            except Exception as e:
                # status must answer even when the watcher is wedged
                out["registry"] = {"error": str(e)}
        if self.status_extra is not None:
            try:
                out.update(self.status_extra())
            except Exception as e:
                out["status_extra_error"] = str(e)
        return out

    def metrics_response(self, obj: Dict) -> Dict[str, object]:
        """The ``{"op": "metrics"}`` payload: the live process registry
        when one is wired (driver ``--obs-dir`` / explicit ctor arg),
        otherwise the serving accumulator's snapshot — the op always
        answers. ``format: "prometheus"`` returns text exposition."""
        uid = obj.get("uid")
        fmt = str(obj.get("format") or "json").lower()
        reg = self.metrics_registry
        if fmt == "prometheus":
            if reg is None:
                return _error_response(
                    uid, "BAD_REQUEST",
                    "prometheus exposition needs a metrics registry "
                    "(--obs-dir)",
                )
            return {
                "uid": uid, "status": "ok", "op": "metrics",
                "format": "prometheus", "text": reg.prometheus(),
            }
        if reg is not None:
            payload = reg.snapshot()
        elif self.metrics is not None:
            payload = {"serving": self.metrics.snapshot()}
        else:
            payload = {}
        return {
            "uid": uid, "status": "ok", "op": "metrics",
            "metrics": payload,
        }

    def trace_response(self, obj: Dict) -> Dict[str, object]:  # photon: entropy(live trace-poll payload; epoch/now mapping is the protocol)
        """The ``{"op": "trace"}`` payload: the process tracer's spans
        AFTER the caller's cursor (contiguous seq run; evictions since
        the last poll are counted in ``dropped``), the process's
        ``(wall, perf)`` epoch, and an epoch-mapped ``now_perf`` so the
        caller can run one NTP-style offset estimate per poll. The
        cursor contract: pass ``cursor`` back verbatim on the next poll
        — no span is ever sent twice, and a cursor from before a ring
        reset restarts cleanly from the beginning."""
        uid = obj.get("uid")
        try:
            cursor = int(obj.get("cursor") or 0)
        except (TypeError, ValueError):
            return _error_response(
                uid, "BAD_REQUEST", "cursor must be an integer"
            )
        t = obs_trace.tracer()
        spans, new_cursor, dropped = t.read_since(cursor)
        epoch_wall, epoch_perf = obs_trace.epoch()
        return {
            "uid": uid,
            "status": "ok",
            "op": "trace",
            "pid": os.getpid(),
            "enabled": obs_trace.tracing_enabled(),
            "epoch_wall": epoch_wall,
            "epoch_perf": epoch_perf,
            "now_perf": time.perf_counter(),
            "cursor": new_cursor,
            "dropped": dropped,
            "max_spans": t.max_spans,
            "spans": [s.to_dict() for s in spans],
        }

    # -- internals -----------------------------------------------------------

    def _note(self, event: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.record_frontend(event, n)

    def _forget(self, conn: _Connection) -> None:
        with self._conns_lock:
            if conn in self._conns:
                self._conns.remove(conn)
        self._note("connections_closed")

    def _accept_loop(self) -> None:
        while self._accepting.is_set():
            try:
                sock, addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener closed (drain)
            if not self._accepting.is_set():
                try:
                    sock.close()
                except OSError:
                    self._note("socket_close_errors")
                break
            peer = f"{addr[0]}:{addr[1]}"
            conn = _Connection(self, sock, peer)
            with self._conns_lock:
                self._conns.append(conn)
            self._note("connections_opened")

    def _handle_score(self, conn: _Connection, record: Dict) -> None:
        uid = record.get("uid")
        if self._stopped.is_set():
            conn.send(_error_response(
                uid, "CLOSED", "front-end is draining"
            ))
            return
        try:
            req = request_from_record(
                record,
                self.serving_model.current(),
                self.shard_configs,
                has_response=self.has_response,
            )
        except (ValueError, KeyError, TypeError) as e:
            self._note("malformed")
            conn.send(_error_response(uid, "BAD_REQUEST", str(e)))
            return
        # trace ids are minted HERE, at the edge: a request arriving
        # with wire context joins the caller's trace (the router's
        # sub-request path), a bare one roots a fresh trace. The span
        # covers queue wait + dispatch + demux; the dispatch-window
        # child is stamped by the batcher under req.parent_span.
        wire_t, wire_p = wire_context(record)
        sp = start_span(
            "frontend.request", trace_id=wire_t, parent_id=wire_p,
            uid=str(uid) if uid is not None else "",
        )
        if sp.trace_id is not None:
            req.trace_id = sp.trace_id
            req.parent_span = sp.span_id
        else:
            # tracing off on this hop: still RELAY the caller's context
            # so downstream hops (and the response echo) stay connected
            req.trace_id, req.parent_span = wire_t, wire_p
        try:
            fut = self.batcher.submit(req)
        except ServingError as e:
            sp.end(status="refused", error=type(e).__name__)
            conn.send(_failure_response(uid, e))
            return
        conn._note_pending(+1)
        fut.add_done_callback(
            lambda f, c=conn, u=req.uid, t=req.trace_id, s=sp:
            self._on_done(c, u, f, trace_id=t, span=s)
        )

    def _on_done(
        self, conn: _Connection, uid: str, fut: Future,
        *, trace_id: Optional[str] = None, span=None,
    ) -> None:
        # runs on the dispatcher (or drain) thread: the future is
        # already terminal, so result(timeout=0) cannot block
        try:
            outcome = fut.result(timeout=0)
            resp = _outcome_response(
                uid, outcome, binary=conn.proto == "binary"
            )
            ok, degraded, failed = (
                True, bool(getattr(outcome, "degraded", False)), False,
            )
        except BaseException as e:
            resp = _failure_response(uid, e)
            ok, degraded, failed = False, False, True
        if trace_id is not None:
            # the response echoes the trace id so the client (router or
            # operator) can stitch both sides of the wire
            resp[TRACE_KEY] = trace_id
        if span is not None:
            span.end(status=str(resp.get("status")), degraded=degraded)
        hook = self.on_outcome
        if hook is not None:
            try:
                # the registry watcher's post-swap health feed: two
                # boolean ORs on the response path, never a swap
                hook(ok, degraded, failed)
            except Exception:
                self._note("completion_hook_errors")
        # ENQUEUE the response BEFORE decrementing pending: the writer
        # thread's drain check is "pending == 0 and queue empty", so a
        # decrement-first ordering opens a window where a closing
        # writer observes both true between our two steps and exits
        # with the final response still in hand — a silently dropped
        # response at drain time (pinned by the interleaving harness
        # test; the schedule is replayable from its seed)
        conn.send(resp)
        conn._note_pending(-1)
        with self._completed_lock:
            self._completed += 1
            n = self._completed
        hook = self.on_completion
        if hook is not None:
            try:
                hook(n)
            except Exception:
                # a completion hook (e.g. the driver's swap trigger)
                # must never take down the response path; failures are
                # visible in its own accounting
                self._note("completion_hook_errors")
