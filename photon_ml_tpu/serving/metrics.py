"""Serving-side observability: latency percentiles, QPS, batch
occupancy, pad waste, swap/compile accounting.

Everything here is host arithmetic over host timestamps — nothing in
this module may touch a device value (the request path's readback
budget is exactly one ``overlap.device_get`` per dispatch, owned by the
batcher). Latencies keep a bounded reservoir: full fidelity up to the
cap, then uniform-by-stride thinning so a week of traffic cannot grow
host memory — percentiles stay estimates over a deterministic subset,
never a stopped service.

``snapshot()`` is the metrics.json block; the driver merges it with the
reliability accounting (faults/retries/quarantines) so one artifact
answers both "how fast" and "what broke".
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Thread-safe accumulator shared by the batcher, the swap path and
    the driver."""

    def __init__(self, *, max_latency_samples: int = 1 << 20):
        self._lock = threading.Lock()
        self._max_samples = int(max_latency_samples)
        self._lat: List[float] = []
        self._stride = 1
        self._seen = 0
        self._dispatches = 0
        self._rows_real = 0
        self._rows_padded = 0
        self._queue_wait_s = 0.0
        self._device_s = 0.0
        self._shape_counts: Dict[int, int] = {}
        self._gen_dispatches: Dict[int, int] = {}
        self._first_t: Optional[float] = None
        self._last_t: Optional[float] = None

    # -- recording -----------------------------------------------------------

    def record_dispatch(
        self,
        *,
        shape: int,
        occupancy: int,
        queue_wait_s: float,
        device_s: float,
        generation: int,
    ) -> None:
        import time

        now = time.perf_counter()
        with self._lock:
            self._dispatches += 1
            self._rows_real += occupancy
            self._rows_padded += shape
            self._queue_wait_s += queue_wait_s
            self._device_s += device_s
            self._shape_counts[shape] = self._shape_counts.get(shape, 0) + 1
            self._gen_dispatches[generation] = (
                self._gen_dispatches.get(generation, 0) + 1
            )
            if self._first_t is None:
                self._first_t = now - device_s - queue_wait_s
            self._last_t = now

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self._stride == 0:
                self._lat.append(seconds)
                if len(self._lat) >= self._max_samples:
                    # thin deterministically: keep every 2nd sample,
                    # double the stride for future arrivals
                    self._lat = self._lat[::2]
                    self._stride *= 2

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            lat = np.asarray(self._lat, np.float64)
            elapsed = (
                (self._last_t - self._first_t)
                if self._first_t is not None and self._last_t is not None
                else 0.0
            )
            out: Dict[str, object] = {
                "requests": self._seen,
                "dispatches": self._dispatches,
                "qps": (
                    round(self._seen / elapsed, 3) if elapsed > 0 else None
                ),
                "batch_occupancy_mean": (
                    round(self._rows_real / self._rows_padded, 6)
                    if self._rows_padded
                    else None
                ),
                "pad_waste_frac": (
                    round(1.0 - self._rows_real / self._rows_padded, 6)
                    if self._rows_padded
                    else None
                ),
                "rows_per_dispatch_mean": (
                    round(self._rows_real / self._dispatches, 3)
                    if self._dispatches
                    else None
                ),
                "queue_wait_s_mean": (
                    round(self._queue_wait_s / self._dispatches, 9)
                    if self._dispatches
                    else None
                ),
                "device_s_mean": (
                    round(self._device_s / self._dispatches, 9)
                    if self._dispatches
                    else None
                ),
                "shape_counts": {
                    str(k): v for k, v in sorted(self._shape_counts.items())
                },
                "generation_dispatches": {
                    str(k): v
                    for k, v in sorted(self._gen_dispatches.items())
                },
                "latency_samples": int(lat.size),
                "latency_sample_stride": self._stride,
            }
            if lat.size:
                out.update(
                    {
                        "latency_p50_ms": round(
                            float(np.percentile(lat, 50)) * 1e3, 6
                        ),
                        "latency_p99_ms": round(
                            float(np.percentile(lat, 99)) * 1e3, 6
                        ),
                        "latency_max_ms": round(float(lat.max()) * 1e3, 6),
                        "latency_mean_ms": round(
                            float(lat.mean()) * 1e3, 6
                        ),
                    }
                )
            return out

    def write(self, path: str, extra: Optional[Dict] = None) -> None:
        """metrics.json: the serving block + reliability accounting +
        caller extras, atomically."""
        from photon_ml_tpu.reliability import (
            atomic_write_json,
            reliability_metrics,
        )

        atomic_write_json(
            path,
            {
                "serving": self.snapshot(),
                **(extra or {}),
                "reliability": reliability_metrics(),
            },
        )
