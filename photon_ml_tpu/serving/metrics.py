"""Serving-side observability: latency percentiles, QPS, batch
occupancy, pad waste, swap/compile accounting.

Everything here is host arithmetic over host timestamps — nothing in
this module may touch a device value (the request path's readback
budget is exactly one ``overlap.device_get`` per dispatch, owned by the
batcher). Latencies keep a bounded reservoir: full fidelity up to the
cap, then uniform-by-stride thinning so a week of traffic cannot grow
host memory — percentiles stay estimates over a deterministic subset,
never a stopped service.

``snapshot()`` is the metrics.json block; the driver merges it with the
reliability accounting (faults/retries/quarantines) so one artifact
answers both "how fast" and "what broke".
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

__all__ = ["ServingMetrics"]


class ServingMetrics:
    """Thread-safe accumulator shared by the batcher, the front-end,
    the swap path and the driver."""

    def __init__(self, *, max_latency_samples: int = 1 << 20):
        self._lock = threading.Lock()
        self._max_samples = int(max_latency_samples)
        self._lat: List[float] = []
        self._stride = 1
        self._seen = 0
        self._dispatches = 0
        self._rows_real = 0
        self._rows_padded = 0
        self._queue_wait_s = 0.0
        self._device_s = 0.0
        self._shape_counts: Dict[int, int] = {}
        self._gen_dispatches: Dict[int, int] = {}
        self._first_t: Optional[float] = None
        self._last_t: Optional[float] = None
        # overload/degradation/lifecycle accounting (ISSUE 8): sheds by
        # reason, deadline drops, degraded (FE-only) responses, RE
        # lookup failures/quarantines, front-end line/connection
        # counters, and the drain report. All host counters — the
        # one-readback-per-dispatch budget is untouched.
        self._sheds: Dict[str, int] = {}
        self._deadline_expired = 0
        self._degraded = 0
        self._re_resolution_failures: Dict[str, int] = {}
        self._re_quarantines: Dict[str, int] = {}
        self._frontend: Dict[str, int] = {}
        self._responses: Dict[str, int] = {}
        self._drain: Optional[Dict[str, object]] = None
        # live registry mirrors (SLO-engine inputs, obs/slo.py): bound
        # once by bind_registry before traffic, read bare on the record
        # paths — single-writer plain-reference publishes
        self._reg_total = None  # photon: guarded-by(atomic)
        self._reg_bad = None  # photon: guarded-by(atomic)
        self._reg_latency = None  # photon: guarded-by(atomic)

    def bind_registry(self, registry, *, prefix: str = "serving") -> None:
        """Mirror the request-path outcomes into live registry
        instruments: ``<prefix>_requests_total`` (every request that
        reached a terminal), ``<prefix>_bad_total`` (shed / deadline /
        degraded — the error-budget burners, labelled by reason) and
        the ``<prefix>_latency_seconds`` histogram. These are what
        declarative SLO specs (obs/slo.py) evaluate over; the mirrors
        feed OUTSIDE this accumulator's lock, so the request path gains
        one instrument-local lock per event and no nesting."""
        self._reg_total = registry.counter(
            f"{prefix}_requests_total",
            "requests that reached a terminal outcome",
        )
        self._reg_bad = registry.counter(
            f"{prefix}_bad_total",
            "requests that burned error budget, by reason",
        )
        self._reg_latency = registry.histogram(
            f"{prefix}_latency_seconds", "end-to-end request latency"
        )

    # -- recording -----------------------------------------------------------

    def record_dispatch(
        self,
        *,
        shape: int,
        occupancy: int,
        queue_wait_s: float,
        device_s: float,
        generation: int,
    ) -> None:
        import time

        now = time.perf_counter()
        with self._lock:
            self._dispatches += 1
            self._rows_real += occupancy
            self._rows_padded += shape
            self._queue_wait_s += queue_wait_s
            self._device_s += device_s
            self._shape_counts[shape] = self._shape_counts.get(shape, 0) + 1
            self._gen_dispatches[generation] = (
                self._gen_dispatches.get(generation, 0) + 1
            )
            if self._first_t is None:
                self._first_t = now - device_s - queue_wait_s
            self._last_t = now

    def record_shed(self, reason: str) -> None:
        """One refused request: ``predicted_wait`` (admission said no up
        front) or ``queue_full`` (the bounded full-queue wait expired)."""
        with self._lock:
            self._sheds[reason] = self._sheds.get(reason, 0) + 1
        if self._reg_total is not None:
            self._reg_total.inc()
            self._reg_bad.inc(reason="shed")

    def record_deadline_expired(self, n: int = 1) -> None:
        with self._lock:
            self._deadline_expired += int(n)
        if self._reg_total is not None:
            self._reg_total.inc(n)
            self._reg_bad.inc(n, reason="deadline")

    def record_degraded(self, n: int = 1) -> None:
        with self._lock:
            self._degraded += int(n)
        if self._reg_bad is not None:
            # degraded rows also pass record_latency, which counts them
            # in the total — only the budget burn is added here
            self._reg_bad.inc(n, reason="degraded")

    def record_re_resolution_failure(self, re_type: str) -> None:
        with self._lock:
            self._re_resolution_failures[re_type] = (
                self._re_resolution_failures.get(re_type, 0) + 1
            )

    def record_re_quarantine(self, re_type: str) -> None:
        with self._lock:
            self._re_quarantines[re_type] = (
                self._re_quarantines.get(re_type, 0) + 1
            )

    def record_frontend(self, event: str, n: int = 1) -> None:
        """Front-end counters: ``connections_opened`` / ``_closed`` /
        ``_dropped_slow``, ``lines`` / ``malformed`` / ``oversized`` /
        ``read_faults`` / ``control``."""
        with self._lock:
            self._frontend[event] = self._frontend.get(event, 0) + int(n)

    def record_response(self, status: str) -> None:
        """One wire response by terminal status (``ok`` / ``shed`` /
        ``deadline_exceeded`` / ``error`` / ``degraded`` rides on ok)."""
        with self._lock:
            self._responses[status] = self._responses.get(status, 0) + 1

    def record_drain(self, report) -> None:
        with self._lock:
            self._drain = report.to_dict()

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self._stride == 0:
                self._lat.append(seconds)
                if len(self._lat) >= self._max_samples:
                    # thin deterministically: keep every 2nd sample,
                    # double the stride for future arrivals
                    self._lat = self._lat[::2]
                    self._stride *= 2
        if self._reg_total is not None:
            self._reg_total.inc()
            self._reg_latency.observe(seconds)

    # -- reporting -----------------------------------------------------------

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            lat = np.asarray(self._lat, np.float64)
            elapsed = (
                (self._last_t - self._first_t)
                if self._first_t is not None and self._last_t is not None
                else 0.0
            )
            out: Dict[str, object] = {
                "requests": self._seen,
                "dispatches": self._dispatches,
                "qps": (
                    round(self._seen / elapsed, 3) if elapsed > 0 else None
                ),
                "batch_occupancy_mean": (
                    round(self._rows_real / self._rows_padded, 6)
                    if self._rows_padded
                    else None
                ),
                "pad_waste_frac": (
                    round(1.0 - self._rows_real / self._rows_padded, 6)
                    if self._rows_padded
                    else None
                ),
                "rows_per_dispatch_mean": (
                    round(self._rows_real / self._dispatches, 3)
                    if self._dispatches
                    else None
                ),
                "queue_wait_s_mean": (
                    round(self._queue_wait_s / self._dispatches, 9)
                    if self._dispatches
                    else None
                ),
                "device_s_mean": (
                    round(self._device_s / self._dispatches, 9)
                    if self._dispatches
                    else None
                ),
                "shape_counts": {
                    str(k): v for k, v in sorted(self._shape_counts.items())
                },
                "generation_dispatches": {
                    str(k): v
                    for k, v in sorted(self._gen_dispatches.items())
                },
                "latency_samples": int(lat.size),
                "latency_sample_stride": self._stride,
                "sheds": {
                    **{k: v for k, v in sorted(self._sheds.items())},
                    "total": sum(self._sheds.values()),
                },
                "deadline_expired": self._deadline_expired,
                "degraded_responses": self._degraded,
                "re_resolution_failures": dict(
                    sorted(self._re_resolution_failures.items())
                ),
                "re_quarantines": dict(
                    sorted(self._re_quarantines.items())
                ),
            }
            if self._frontend:
                out["frontend"] = dict(sorted(self._frontend.items()))
            if self._responses:
                out["responses"] = dict(sorted(self._responses.items()))
            if self._drain is not None:
                out["drain"] = dict(self._drain)
            if lat.size:
                out.update(
                    {
                        "latency_p50_ms": round(
                            float(np.percentile(lat, 50)) * 1e3, 6
                        ),
                        "latency_p99_ms": round(
                            float(np.percentile(lat, 99)) * 1e3, 6
                        ),
                        "latency_max_ms": round(float(lat.max()) * 1e3, 6),
                        "latency_mean_ms": round(
                            float(lat.mean()) * 1e3, 6
                        ),
                    }
                )
            return out

    def write(self, path: str, extra: Optional[Dict] = None) -> None:
        """metrics.json: the serving block + reliability accounting +
        caller extras, atomically."""
        from photon_ml_tpu.reliability import (
            atomic_write_json,
            reliability_metrics,
        )

        atomic_write_json(
            path,
            {
                "serving": self.snapshot(),
                **(extra or {}),
                "reliability": reliability_metrics(),
            },
        )
