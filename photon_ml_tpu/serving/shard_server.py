"""Shard-server extensions to the serving frontend: one scorer of ONE
entity shard, speaking the routing tier's control plane.

A shard-server is the EXISTING MicroBatcher/ServingModel/frontend stack
with three twists, all additive:

- its model bank holds one entity shard
  (``build_model_bank(entity_shard=(s, N))`` — the shared ownership
  rule, :mod:`photon_ml_tpu.ownership`), so its random-effect banks are
  ``1/N`` of the model and every off-shard entity resolves to the
  FE-only row;
- its batcher runs in PARTIAL mode (``ServingModel(partial=True)``):
  dispatches run the scatter/gather program family and score lines
  answer ``{"fe": …, "terms": {…}}`` halves instead of full margins;
- it exposes the router's control ops: ``topology`` (shard index/count,
  ownership rule, spec term entries, generation — everything the router
  needs to discover the fleet layout without out-of-band config) and
  the two-step flip ``stage_swap`` / ``commit_swap`` / ``abort_swap``
  (:meth:`~.swap.ServingModel.prepare_swap` /
  :meth:`~.swap.ServingModel.commit_prepared`), so the router can stage
  a new generation fleet-wide and only flip when EVERY shard staged OK.

The same topology block rides every ``status`` response and the
driver's ``frontend.json``, so operators discover the layout the same
way the router does.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from photon_ml_tpu import ownership
from photon_ml_tpu.serving import wire
from photon_ml_tpu.serving.batcher import MicroBatcher
from photon_ml_tpu.serving.frontend import ServingFrontend
from photon_ml_tpu.serving.metrics import ServingMetrics
from photon_ml_tpu.serving.programs import term_entries
from photon_ml_tpu.serving.swap import ServingModel

__all__ = [
    "shard_topology",
    "make_shard_ops",
    "ShardServer",
]


def shard_topology(
    serving_model: ServingModel,
    entity_shard: Tuple[int, int],
) -> Dict[str, object]:
    """The topology payload: everything a router (or operator) needs to
    place requests on this fleet without out-of-band configuration."""
    s, n = ownership.validate_entity_shard(entity_shard)
    bank = serving_model.current()
    return {
        "shard_index": s,
        "shard_count": n,
        "rule": ownership.OWNERSHIP_RULE,
        "generation": bank.generation,
        "entries": [
            [kind, name, list(types), shard]
            for kind, name, types, shard in term_entries(bank.spec)
        ],
        "re_types": list(bank.re_types),
        "partial": serving_model.partial,
        "ready": serving_model.ready(),
        # wire advertisement: the router negotiates the data plane from
        # this block at connect() — a shard without it is JSON-only
        "wire": {
            "protocols": list(wire.WIRE_PROTOCOLS),
            "version": wire.WIRE_VERSION,
        },
    }


def make_shard_ops(
    serving_model: ServingModel,
    entity_shard: Tuple[int, int],
    *,
    stager: Optional[Callable[[Dict], object]] = None,
    swap_kwargs: Optional[Dict[str, object]] = None,
) -> Dict[str, Callable[[Dict], Dict]]:
    """The extra control ops a shard-server frontend serves. Every
    handler echoes the request's uid (routed control responses demux by
    it). ``stager`` overrides how ``stage_swap`` builds the next
    generation (synthetic fleets in bench/chaos stage from arrays); the
    default loads ``model_dir`` through
    :meth:`~.swap.ServingModel.prepare_swap` — which re-slices the SAME
    entity shard this server owns."""
    kwargs = dict(swap_kwargs or {})

    def _swap_response(obj: Dict, op: str, res) -> Dict:
        return {
            "uid": obj.get("uid"),
            "status": "ok" if res.ok else "error",
            "op": op,
            "ok": res.ok,
            "generation": res.generation,
            "donated": res.donated,
            "error": res.error,
        }

    def topology(obj: Dict) -> Dict:
        out = shard_topology(serving_model, entity_shard)
        out.update({"uid": obj.get("uid"), "status": "ok",
                    "op": "topology"})
        return out

    def stage_swap(obj: Dict) -> Dict:
        if stager is not None:
            res = stager(obj)
        else:
            model_dir = obj.get("model_dir")
            if not model_dir:
                return {
                    "uid": obj.get("uid"),
                    "status": "error",
                    "error": "BAD_REQUEST",
                    "message": "stage_swap needs model_dir",
                }
            res = serving_model.prepare_swap(str(model_dir), **kwargs)
        return _swap_response(obj, "stage_swap", res)

    def commit_swap(obj: Dict) -> Dict:
        return _swap_response(
            obj, "commit_swap", serving_model.commit_prepared()
        )

    def abort_swap(obj: Dict) -> Dict:
        return {
            "uid": obj.get("uid"),
            "status": "ok",
            "op": "abort_swap",
            "aborted": serving_model.abort_prepared(),
        }

    return {
        "topology": topology,
        "stage_swap": stage_swap,
        "commit_swap": commit_swap,
        "abort_swap": abort_swap,
    }


class ShardServer:
    """One in-process shard-serving stack (tests, bench fleets, and the
    driver's ``--shard-index`` mode all assemble exactly this): a
    partial-mode batcher over one entity shard's bank, fronted by the
    TCP frontend with the shard control ops attached."""

    def __init__(
        self,
        serving_model: ServingModel,
        shard_configs,
        entity_shard: Tuple[int, int],
        *,
        metrics: Optional[ServingMetrics] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        stager: Optional[Callable[[Dict], object]] = None,
        swap_kwargs: Optional[Dict[str, object]] = None,
        has_response: bool = True,
        max_queue: int = 4096,
        default_deadline_ms: Optional[float] = None,
        on_outcome=None,
        recorder=None,
        max_frame_bytes: Optional[int] = None,
    ):
        if not serving_model.partial:
            raise ValueError(
                "a shard-server needs a partial-mode ServingModel "
                "(ServingModel(..., partial=True)): the router sums "
                "per-coordinate terms, not full margins"
            )
        self.entity_shard = ownership.validate_entity_shard(entity_shard)
        self.serving_model = serving_model
        self.metrics = metrics or ServingMetrics()
        # recorder: this shard's conservation ledger — in-process
        # fleets (tests/bench) give every member its OWN book so the
        # fleet-wide check can join them; subprocess shards default to
        # their process recorder
        self.batcher = MicroBatcher(
            serving_model.current,
            serving_model.programs,
            self.metrics,
            max_queue=max_queue,
            default_deadline_ms=default_deadline_ms,
            recorder=recorder,
        )
        self.frontend = ServingFrontend(
            self.batcher,
            serving_model,
            shard_configs,
            metrics=self.metrics,
            host=host,
            port=port,
            has_response=has_response,
            max_frame_bytes=max_frame_bytes,
            on_outcome=on_outcome,
            extra_ops=make_shard_ops(
                serving_model,
                self.entity_shard,
                stager=stager,
                swap_kwargs=swap_kwargs,
            ),
            status_extra=lambda: {
                "shard": shard_topology(serving_model, self.entity_shard)
            },
        )

    @property
    def port(self) -> int:
        return self.frontend.port

    def start(self) -> "ShardServer":
        self.frontend.start()
        return self

    def close(self, drain_timeout_s: float = 5.0):
        """Drain-ordered teardown (the frontend's SIGTERM protocol)."""
        self.frontend.stop_accepting()
        report = self.batcher.drain(drain_timeout_s)
        self.frontend.close()
        self.batcher.close()
        return report
