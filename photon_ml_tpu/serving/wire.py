"""photon-wire: the length-prefixed binary wire plane for the serving
fleet.

Every frame is ``MAGIC(1) | VERSION(1) | TYPE(1) | u32-LE LEN(4) |
PAYLOAD(LEN)``. The magic byte (0xF7) is not a legal first byte of any
JSON-lines request (JSON text starts with ``{``, whitespace, or other
ASCII), so the frontend sniffs the FIRST byte of a connection and keeps
speaking JSON-lines to old clients on the same port — the two protocols
coexist per-connection, never per-frame.

Payload shapes (all multi-byte integers little-endian, all float
buffers raw little-endian numpy — decoded with ``np.frombuffer``,
never per-float text):

- ``MSG_JSON``: a UTF-8 JSON object. Control ops, errors, and anything
  without a hot-path codec ride binary connections inside this frame
  type; the framing win (no ``\\n`` scanning, one length read) still
  applies.
- ``MSG_SCORE_REQUEST``: ``u32 hdr_len | JSON header | f64-LE values``.
  The header is the score record with each feature bag's ``value``
  floats stripped out (bag name -> count recorded under ``_wire_bags``,
  in header order); the tail carries every stripped value as raw f64 —
  the lossless twin of JSON's shortest-round-trip double, so decoded
  requests are byte-identical inputs to the batcher. Bags whose every
  entry is exactly ``{"name", "term", "value"}`` additionally go
  COLUMNAR (``_wire_cols``): names and terms ride as two
  ``\\x1f``-joined strings instead of per-entry JSON objects, so the
  header encode/decode never touches a per-feature dict on the hot
  path.
- ``MSG_SCORE_RESPONSE``: ``u32 hdr_len | JSON header | f32-LE[1]``.
  The header is the ok-response without ``score``; the tail is the
  score's exact f32 bits (``float(np.float32(x))`` round-trips to the
  same double the JSON path prints).
- ``MSG_PARTIAL_RESPONSE``: ``u32 hdr_len | JSON header |
  f32-LE[1 + n_terms]``. The header carries ``names`` (term order);
  the tail is ``[fe, terms...]`` — one buffer copy out of the
  vectorized :meth:`PartialScore.term_vector`, never a per-float dict.
- ``MSG_TRACE_RESPONSE``: ``u32 hdr_len | JSON header |
  f64-LE[2 * n_spans]``. The header is the trace-drain payload with
  each span's ``t0``/``t1`` stripped; the tail interleaves them
  (``NaN`` encodes an unfinished span's ``t1 = None``).

Decode failures raise :class:`WireError` with a ``kind`` the frontend
maps onto the SAME named refusals the JSON path uses (``oversized``
for giant lengths, ``malformed`` for everything else) — a lying length
is a BAD_REQUEST, never a crash or a stuck reader.
"""

from __future__ import annotations

import json
import math
import operator
import os
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "MAGIC",
    "WIRE_VERSION",
    "WIRE_PROTOCOLS",
    "DEFAULT_MAX_FRAME_BYTES",
    "MAX_FRAME_BYTES_ENV",
    "MSG_JSON",
    "MSG_SCORE_REQUEST",
    "MSG_SCORE_RESPONSE",
    "MSG_PARTIAL_RESPONSE",
    "MSG_TRACE_RESPONSE",
    "WireError",
    "FrameDecoder",
    "resolve_max_frame_bytes",
    "append_frame",
    "append_json",
    "append_score_request",
    "append_response",
    "decode_score_request",
    "decode_message",
]

MAGIC = 0xF7  # invalid UTF-8 lead byte: no JSON-lines request starts with it
WIRE_VERSION = 1
# What this build speaks, in preference order — advertised in topology
# blocks and status responses; routers negotiate the data plane from it.
WIRE_PROTOCOLS = ("json", "binary")

DEFAULT_MAX_FRAME_BYTES = 1 << 20
MAX_FRAME_BYTES_ENV = "PHOTON_MAX_FRAME_BYTES"

MSG_JSON = 0x01
MSG_SCORE_REQUEST = 0x02
MSG_SCORE_RESPONSE = 0x03
MSG_PARTIAL_RESPONSE = 0x04
MSG_TRACE_RESPONSE = 0x05

_HEADER = struct.Struct("<BBBI")
_U32 = struct.Struct("<I")
_BAGS_KEY = "_wire_bags"
_COLS_KEY = "_wire_cols"
# Column separator for the fast bag path: the ASCII unit separator —
# a feature name/term containing it falls back to the generic path.
_COL_SEP = "\x1f"
_GET_NAME = operator.itemgetter("name")
_GET_TERM = operator.itemgetter("term")
_GET_VALUE = operator.itemgetter("value")


class WireError(ValueError):
    """A frame or payload that cannot be decoded. ``kind`` is
    ``"oversized"`` for frame lengths over the cap (the binary twin of
    the JSON line-length refusal) and ``"malformed"`` for everything
    else; the frontend notes the matching counter either way."""

    def __init__(self, message: str, *, kind: str = "malformed"):
        super().__init__(message)
        self.kind = kind


def resolve_max_frame_bytes(value: Optional[int] = None) -> int:
    """Explicit value > ``PHOTON_MAX_FRAME_BYTES`` env > 1 MiB default.
    One resolution rule for both protocols: the SAME cap refuses a JSON
    line and a binary frame length."""
    if value is None:
        env = os.environ.get(MAX_FRAME_BYTES_ENV)
        if env:
            value = int(env)
        else:
            value = DEFAULT_MAX_FRAME_BYTES
    out = int(value)
    if out <= 0:
        raise ValueError(f"max_frame_bytes must be positive, got {out}")
    return out


class FrameDecoder:
    """Incremental frame splitter: ``feed(chunk)`` returns every
    complete ``(msg_type, payload)`` and buffers the tail. Raises
    :class:`WireError` the moment framing is provably lost (bad magic,
    unknown version) or a header announces a length over the cap —
    BEFORE buffering a giant payload."""

    def __init__(self, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self.max_frame_bytes = int(max_frame_bytes)
        self._buf = bytearray()

    @property
    def pending_bytes(self) -> int:
        """Bytes buffered mid-frame (nonzero at EOF == truncated frame)."""
        return len(self._buf)

    def feed(self, chunk: bytes) -> List[Tuple[int, bytes]]:
        self._buf += chunk
        out: List[Tuple[int, bytes]] = []
        while len(self._buf) >= _HEADER.size:
            magic, version, mtype, length = _HEADER.unpack_from(self._buf)
            if magic != MAGIC:
                raise WireError(
                    f"bad frame magic 0x{magic:02x} (want 0x{MAGIC:02x}): "
                    "framing lost"
                )
            if version != WIRE_VERSION:
                raise WireError(
                    f"unsupported wire version {version} "
                    f"(this build speaks {WIRE_VERSION})"
                )
            if length > self.max_frame_bytes:
                raise WireError(
                    f"frame length {length} exceeds {self.max_frame_bytes} "
                    "bytes",
                    kind="oversized",
                )
            end = _HEADER.size + length
            if len(self._buf) < end:
                break
            out.append((mtype, bytes(self._buf[_HEADER.size:end])))
            del self._buf[:end]
        return out


def append_frame(buf: bytearray, msg_type: int, *parts: bytes) -> None:
    """Append one frame to a caller-owned (reused) encode buffer."""
    buf += _HEADER.pack(
        MAGIC, WIRE_VERSION, msg_type, sum(len(p) for p in parts)
    )
    for p in parts:
        buf += p


def append_json(buf: bytearray, obj: Dict) -> None:
    append_frame(
        buf, MSG_JSON, json.dumps(obj, separators=(",", ":")).encode("utf-8")
    )


def _split_header(payload: bytes) -> Tuple[Dict, bytes]:
    if len(payload) < _U32.size:
        raise WireError("payload too short for its header length")
    (hdr_len,) = _U32.unpack_from(payload)
    end = _U32.size + hdr_len
    if end > len(payload):
        raise WireError(
            f"payload header length {hdr_len} overruns the frame"
        )
    try:
        head = json.loads(payload[_U32.size:end].decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError(f"payload header is not JSON: {e}") from e
    if not isinstance(head, dict):
        raise WireError("payload header must be a JSON object")
    return head, payload[end:]


def _json_header(head: Dict) -> Tuple[bytes, bytes]:
    hj = json.dumps(head, separators=(",", ":")).encode("utf-8")
    return _U32.pack(len(hj)), hj


def _floats(tail: bytes, dtype: str, want: int, what: str) -> np.ndarray:
    itemsize = np.dtype(dtype).itemsize
    if len(tail) != want * itemsize:
        raise WireError(
            f"{what}: float buffer is {len(tail)} bytes, header promised "
            f"{want} x {itemsize}"
        )
    return np.frombuffer(tail, dtype=dtype)


# ---------------------------------------------------------------------------
# score requests


def _strippable_bag(v: object) -> bool:
    # A feature bag whose every entry carries a numeric "value" — those
    # floats ride the raw f64 tail. Anything else stays in the header.
    if not isinstance(v, list) or not v:
        return False
    for f in v:
        if not isinstance(f, dict):
            return False
        fv = f.get("value")
        if isinstance(fv, bool) or not isinstance(fv, (int, float)):
            return False
    return True


def _columnar_bag(v: List[Dict]):
    # The fast shape: every entry is EXACTLY {"name","term","value"}
    # with string name/term free of the column separator and a numeric
    # non-bool value. Returns (names_col, terms_col, f64_array) or None
    # to fall back to the generic strip. Every check here is a C-level
    # pass (itemgetter/map/join/count/np.array) — the encoder never
    # runs Python bytecode per feature entry.
    try:
        names = _COL_SEP.join(map(_GET_NAME, v))
        terms = _COL_SEP.join(map(_GET_TERM, v))
        vlist = list(map(_GET_VALUE, v))
        if bool in map(type, vlist):
            return None
        arr = np.array(vlist, dtype="<f8")
    except (KeyError, TypeError, ValueError, OverflowError):
        return None
    n = len(v)
    if (
        arr.ndim != 1
        # all three lookups succeeded and total key count is 3n, so
        # every entry has exactly those three keys
        or sum(map(len, v)) != 3 * n
        or names.count(_COL_SEP) != n - 1
        or terms.count(_COL_SEP) != n - 1
    ):
        return None
    return names, terms, arr


def append_score_request(buf: bytearray, record: Dict) -> None:
    """Encode one score record: feature-bag values stripped into one
    raw f64-LE tail, everything else (uid, deadline, trace keys, the
    bags' name/term metadata) in the JSON header. Standard-shaped bags
    go columnar — two joined strings per bag, no per-entry objects."""
    head: Dict = {}
    bags: Dict[str, int] = {}
    cols: Dict[str, List[str]] = {}
    chunks: List[bytes] = []
    for k, v in record.items():
        if isinstance(v, list) and v:
            col = _columnar_bag(v)
            if col is not None:
                bags[k] = len(v)
                cols[k] = [col[0], col[1]]
                chunks.append(col[2].tobytes())
                continue
            if _strippable_bag(v):
                stripped = []
                values: List[float] = []
                for f in v:
                    g = dict(f)
                    values.append(float(g.pop("value")))
                    stripped.append(g)
                bags[k] = len(v)
                head[k] = stripped
                chunks.append(np.asarray(values, dtype="<f8").tobytes())
                continue
        head[k] = v
    if bags:
        head[_BAGS_KEY] = bags
    if cols:
        head[_COLS_KEY] = cols
    hdr_len, hj = _json_header(head)
    append_frame(buf, MSG_SCORE_REQUEST, hdr_len, hj, b"".join(chunks))


def decode_score_request(payload: bytes) -> Dict:
    head, tail = _split_header(payload)
    bags = head.pop(_BAGS_KEY, None)
    cols = head.pop(_COLS_KEY, None)
    if bags is None:
        if tail or cols is not None:
            raise WireError("score request has a tail but no _wire_bags")
        return head
    if not isinstance(bags, dict):
        raise WireError("_wire_bags must be an object")
    if cols is None:
        cols = {}
    elif not isinstance(cols, dict):
        raise WireError("_wire_cols must be an object")
    counts: List[Tuple[str, int]] = []
    total = 0
    for k, n in bags.items():
        if isinstance(n, bool) or not isinstance(n, int) or n < 0:
            raise WireError(f"_wire_bags[{k!r}] is not a count")
        counts.append((k, n))
        total += n
    for k in cols:
        if k not in bags:
            raise WireError(f"_wire_cols[{k!r}] has no _wire_bags count")
    vals = _floats(tail, "<f8", total, "score request values")
    off = 0
    for k, n in counts:
        # .tolist() materializes exact f64 Python floats — the same
        # doubles json.loads would have produced
        col = cols.get(k)
        if col is not None:
            if (
                not isinstance(col, list)
                or len(col) != 2
                or not isinstance(col[0], str)
                or not isinstance(col[1], str)
            ):
                raise WireError(f"_wire_cols[{k!r}] is not two columns")
            names = col[0].split(_COL_SEP)
            terms = col[1].split(_COL_SEP)
            if len(names) != n or len(terms) != n:
                raise WireError(
                    f"bag {k!r}: columns have {len(names)}/{len(terms)} "
                    f"entries, _wire_bags promised {n}"
                )
            head[k] = [
                {"name": nm, "term": tm, "value": v}
                for nm, tm, v in zip(
                    names, terms, vals[off:off + n].tolist()
                )
            ]
        else:
            entries = head.get(k)
            if not isinstance(entries, list) or len(entries) != n:
                raise WireError(
                    f"bag {k!r}: header has {len(entries) if isinstance(entries, list) else 'no'} "
                    f"entries, _wire_bags promised {n}"
                )
            for f, v in zip(entries, vals[off:off + n].tolist()):
                if not isinstance(f, dict):
                    raise WireError(f"bag {k!r}: entry is not an object")
                f["value"] = v
        off += n
    return head


# ---------------------------------------------------------------------------
# responses


def _append_score_response(buf: bytearray, resp: Dict) -> None:
    head = {k: v for k, v in resp.items() if k != "score"}
    hdr_len, hj = _json_header(head)
    tail = np.asarray([resp["score"]], dtype="<f4").tobytes()
    append_frame(buf, MSG_SCORE_RESPONSE, hdr_len, hj, tail)


def _decode_score_response(payload: bytes) -> Dict:
    head, tail = _split_header(payload)
    vals = _floats(tail, "<f4", 1, "score response")
    head["score"] = float(vals[0])
    return head


def _append_partial_response(buf: bytearray, resp: Dict, partial) -> None:
    """Single-pass vectorized PartialScore encode: the gather response's
    ``fe`` + term values ride ONE f32 buffer copy; no per-term dict is
    ever materialized on the shard."""
    names, vec = partial.term_vector()
    head = {k: v for k, v in resp.items()
            if k not in ("fe", "terms", "_wire_partial")}
    head["names"] = list(names)
    hdr_len, hj = _json_header(head)
    tail = np.empty(1 + len(names), dtype="<f4")
    tail[0] = np.float32(partial.fe)
    tail[1:] = vec
    append_frame(buf, MSG_PARTIAL_RESPONSE, hdr_len, hj, tail.tobytes())


def _decode_partial_response(payload: bytes) -> Dict:
    head, tail = _split_header(payload)
    names = head.pop("names", None)
    if not isinstance(names, list):
        raise WireError("partial response header lacks names")
    vals = _floats(tail, "<f4", 1 + len(names), "partial response")
    # .tolist() yields the exact f64 of each f32 — identical to the
    # float(np.float32(x)) the JSON path round-trips
    as_floats = vals.tolist()
    head["fe"] = as_floats[0]
    head["terms"] = dict(zip(names, as_floats[1:]))
    return head


def _append_trace_response(buf: bytearray, resp: Dict) -> None:
    spans = resp.get("spans") or []
    times = np.empty(2 * len(spans), dtype="<f8")
    meta = []
    for i, s in enumerate(spans):
        d = dict(s)
        t1 = d.pop("t1")
        times[2 * i] = d.pop("t0")
        times[2 * i + 1] = math.nan if t1 is None else t1
        meta.append(d)
    head = {k: v for k, v in resp.items() if k != "spans"}
    head["spans"] = meta
    hdr_len, hj = _json_header(head)
    append_frame(buf, MSG_TRACE_RESPONSE, hdr_len, hj, times.tobytes())


def _decode_trace_response(payload: bytes) -> Dict:
    head, tail = _split_header(payload)
    spans = head.get("spans")
    if not isinstance(spans, list):
        raise WireError("trace response header lacks spans")
    times = _floats(tail, "<f8", 2 * len(spans), "trace span times")
    as_floats = times.tolist()
    for i, s in enumerate(spans):
        if not isinstance(s, dict):
            raise WireError("trace span is not an object")
        t1 = as_floats[2 * i + 1]
        s["t0"] = as_floats[2 * i]
        s["t1"] = None if math.isnan(t1) else t1
    return head


def append_response(buf: bytearray, resp: Dict) -> None:
    """Encode one frontend response, picking the hot-path codec when
    one applies: PartialScore gather answers (marked by the writer-side
    ``_wire_partial`` carrier), trace drains, and plain score answers.
    Everything else — status, swap control, errors — is MSG_JSON."""
    partial = resp.get("_wire_partial")
    if partial is not None:
        _append_partial_response(buf, resp, partial)
    elif resp.get("op") == "trace" and isinstance(resp.get("spans"), list):
        _append_trace_response(buf, resp)
    elif resp.get("status") == "ok" and isinstance(
        resp.get("score"), float
    ):
        _append_score_response(buf, resp)
    else:
        append_json(buf, resp)


def decode_message(msg_type: int, payload: bytes) -> Dict:
    """Decode any frame back to the dict the JSON protocol would have
    carried (client / router-transport side)."""
    if msg_type == MSG_JSON:
        try:
            obj = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            raise WireError(f"MSG_JSON payload is not JSON: {e}") from e
        if not isinstance(obj, dict):
            raise WireError("MSG_JSON payload must be a JSON object")
        return obj
    if msg_type == MSG_SCORE_REQUEST:
        return decode_score_request(payload)
    if msg_type == MSG_SCORE_RESPONSE:
        return _decode_score_response(payload)
    if msg_type == MSG_PARTIAL_RESPONSE:
        return _decode_partial_response(payload)
    if msg_type == MSG_TRACE_RESPONSE:
        return _decode_trace_response(payload)
    raise WireError(f"unknown message type 0x{msg_type:02x}")
