"""Device-resident GAME model bank for the online scoring path.

The batch scorer (`cli/game_scoring_driver.py`) rebuilds dense
coefficient views per scoring DATASET (its entity codes come from the
data); a request path has no dataset — requests arrive one at a time
with raw entity ids. This module flips the layout to be model-centric:

- every fixed effect is ONE dense ``[d]`` device vector per shard;
- every random effect is a padded ``[E_pad, d]`` device bank whose row
  order is the model's own sorted entity ids, plus an O(1) host-side
  entity->row index (:class:`EntityRowIndex` — a dict for small banks,
  the ``utils/native_index`` mmap hash store above a size threshold:
  the PalDB-analog store is exactly the "millions of members" shape);
- matrix factorizations are two ``[E_pad, K]`` latent banks.

Row values are built with the same index-map remap the batch scorer
uses, so a request row's dot product is bitwise-identical to the batch
path's — the serving parity tests assert exactly that.

``E_pad`` rounds the entity axis up to ``entity_pad_to`` so a new model
generation with a few more entities keeps the SAME device shapes: the
hot-swap path (`serving/swap.py`) can then refresh the old generation's
buffers in place (donated) instead of holding two banks on device.

The ``spec`` tuple is the bank's program signature — coordinate kinds,
order and shapes — and keys the AOT program cache in
`serving/programs.py` the way the schedule cache keys tile schedules:
same signature, same compiled program, zero recompiles across
generations.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu import ownership

__all__ = [
    "EntityRowIndex",
    "ModelBank",
    "build_model_bank",
    "bank_from_arrays",
    "shard_entity_ids",
    "DEFAULT_ENTITY_PAD",
]

DEFAULT_ENTITY_PAD = 256
# Below this many entities a Python dict wins (no store build); above it
# the native mmap store keeps the host index O(1) without a GB-scale
# dict. Overridable for tests via the build functions' argument.
NATIVE_INDEX_THRESHOLD = 100_000
ENV_NATIVE_THRESHOLD = "PHOTON_SERVING_NATIVE_INDEX_MIN"


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _native_threshold(explicit: Optional[int]) -> int:
    if explicit is not None:
        return explicit
    env = os.environ.get(ENV_NATIVE_THRESHOLD, "").strip()
    return int(env) if env else NATIVE_INDEX_THRESHOLD


# One entity SHARD of a sorted entity-id list, by the shared ownership
# rule (photon_ml_tpu/ownership.py — the same placement game/pod.py
# trains with, so a server loading shard s of a pod-trained model holds
# exactly the rows device s trained). Re-exported here because the
# serving loaders are where callers historically found it.
shard_entity_ids = ownership.shard_entity_ids


class EntityRowIndex:
    """O(1) entity id -> bank row for one random-effect type.

    Small banks use a plain dict; banks at or above ``native_threshold``
    entities build a ``utils/native_index`` mmap store (hash-partitioned
    open addressing, the PalDB analog) so the host-side index costs mmap
    pages instead of a Python dict over millions of ids. Lookups are
    lock-free either way (both structures are immutable after build).

    ``shard``: when this index covers ONE entity shard of a sharded
    GAME model (``(shard_index, num_shards)``), ``ids`` is the owned
    subset and every other entity resolves to row -1 — those requests
    score FE-only through the batcher's existing masked-row semantics.
    """

    def __init__(
        self,
        ids: Sequence[str],
        *,
        native_threshold: Optional[int] = None,
        shard: Optional[Tuple[int, int]] = None,
    ):
        self.shard = shard
        self.ids: List[str] = list(ids)
        self.num_entities = len(self.ids)
        self._store = None
        self._dict: Optional[Dict[str, int]] = None
        if self.num_entities >= _native_threshold(native_threshold):
            try:
                self._store = _build_native_store(self.ids)
            except Exception:
                self._store = None  # toolchain missing: dict fallback
        if self._store is None:
            self._dict = {v: i for i, v in enumerate(self.ids)}

    @property
    def backend(self) -> str:
        return "native" if self._store is not None else "dict"

    def row_of(self, entity_id: str) -> int:
        """Bank row for an entity id; -1 when the model has no entity
        (the request scores 0 through that coordinate, matching the
        batch scorer's masked-code semantics)."""
        if self._store is not None:
            return int(self._store.get_index(entity_id))
        return self._dict.get(entity_id, -1)

    def rows_of(self, entity_ids: Sequence[str]) -> np.ndarray:
        if self._store is not None:
            return self._store.get_indices(entity_ids).astype(np.int32)
        d = self._dict
        return np.fromiter(
            (d.get(e, -1) for e in entity_ids),
            dtype=np.int32,
            count=len(entity_ids),
        )


_STORE_LOCK = threading.Lock()
_STORE_SEQ = 0


def _build_native_store(ids: Sequence[str]):
    """One mmap store whose local indices ARE the bank rows (build_store
    assigns 0..n-1 in the order given). Lives in a registered spill dir
    so driver exits/crashes sweep it like every other spill artifact."""
    import tempfile

    from photon_ml_tpu.io.streaming import register_spill_dir
    from photon_ml_tpu.utils.native_index import NativeIndexStore, build_store

    global _STORE_SEQ
    with _STORE_LOCK:
        _STORE_SEQ += 1
        seq = _STORE_SEQ
    d = tempfile.mkdtemp(prefix="photon-serving-eindex-")
    register_spill_dir(d)
    path = os.path.join(d, f"entity-rows-{seq}.pidx")
    build_store(path, ids)
    return NativeIndexStore(path)


@dataclass
class ModelBank:
    """One loaded model generation, device-resident and immutable.

    ``spec`` is the hashable program signature (kind, name, id types and
    shapes per coordinate, in scoring order); ``arrays`` maps coordinate
    name -> device array(s) in the exact layout the spec promises. Two
    banks with equal specs run the SAME compiled programs.
    """

    generation: int
    spec: tuple
    arrays: Dict[str, object]
    entity_rows: Dict[str, EntityRowIndex]
    index_maps: Mapping[str, object]
    shard_widths: Dict[str, int]
    # flipped by the swap path after this generation's buffers were
    # donated to its successor — using a retired bank is a bug
    retired: bool = False
    model_id: str = ""
    # random-effect id types whose bank is unusable for THIS generation
    # (poisoned artifact slice, repeated row-resolution failures):
    # requests touching them score FE-ONLY with a degraded flag instead
    # of failing. Per-generation by construction — a hot swap installs a
    # fresh bank with an empty set.
    quarantined_re_types: set = None  # set in __post_init__

    def __post_init__(self):
        if self.quarantined_re_types is None:
            self.quarantined_re_types = frozenset()
        # serializes quarantine WRITERS (operator op on a connection
        # thread vs the dispatcher's auto-quarantine): without it the
        # copy-on-write below could lose one of two racing updates
        self._quarantine_lock = threading.Lock()

    def quarantine_re(self, re_type: str) -> None:
        """Mark one random-effect coordinate unusable for this
        generation; the batcher degrades affected rows to FE-only.

        Copy-on-write publish under a writer lock: writers race
        (operator op on a connection thread, the dispatcher's
        auto-quarantine), while the dispatcher READS the set per batch
        — so writers serialize on ``_quarantine_lock`` and publish a
        fresh frozenset as one reference assignment. Readers take no
        lock: they see the old set or the new one, never a set
        mid-mutation (pinned by the interleaving harness)."""
        if re_type not in self.re_types:
            raise ValueError(
                f"unknown random-effect type {re_type!r}; "
                f"known: {self.re_types}"
            )
        with self._quarantine_lock:
            self.quarantined_re_types = (
                frozenset(self.quarantined_re_types) | {re_type}
            )

    @property
    def used_shards(self) -> Tuple[str, ...]:
        """Feature shards the spec actually scores. ``shard_widths``
        may cover MORE shards than the model references (an FE-only
        model served under a multi-shard request config): requests
        still carry those features, but the program pytree — and
        therefore batch assembly — must only see the spec's shards."""
        shards = []
        for entry in self.spec:
            sid = (
                entry[2] if entry[0] == "fe"
                else entry[3] if entry[0] == "re"
                else None
            )
            if sid is not None and sid not in shards:
                shards.append(sid)
        return tuple(shards)

    @property
    def re_types(self) -> Tuple[str, ...]:
        types = []
        for entry in self.spec:
            if entry[0] == "re" and entry[2] not in types:
                types.append(entry[2])
            elif entry[0] == "mf":
                for t in (entry[2], entry[3]):
                    if t not in types:
                        types.append(t)
        return tuple(types)

    def entity_row(self, re_type: str, entity_id: str) -> int:
        return self.entity_rows[re_type].row_of(entity_id)

    def device_bytes(self) -> int:
        total = 0
        for v in self.arrays.values():
            for a in v if isinstance(v, tuple) else (v,):
                total += a.size * a.dtype.itemsize
        return total


def _fe_weights(means: Mapping[str, float], imap) -> np.ndarray:
    """Dense [d] fixed-effect vector — the exact remap loop the batch
    scorer's per-dataset cache performs (model_io.LoadedGameModel.score),
    so serving weights are bitwise the batch weights."""
    w = np.zeros((imap.size,), np.float32)
    for key, v in means.items():
        i = imap.get_index(key)
        if i >= 0:
            w[i] = v
    return w


def _re_bank(
    per_entity: Mapping[str, Mapping[str, float]],
    entity_ids: Sequence[str],
    imap,
    e_pad: int,
) -> np.ndarray:
    bank = np.zeros((e_pad, imap.size), np.float32)
    for row, raw_id in enumerate(entity_ids):
        means = per_entity.get(raw_id)
        if not means:
            continue
        for key, v in means.items():
            i = imap.get_index(key)
            if i >= 0:
                bank[row, i] = v
    return bank


def build_model_bank(
    loaded,
    index_maps: Mapping[str, object],
    shard_widths: Mapping[str, int],
    *,
    generation: int = 1,
    entity_pad_to: int = DEFAULT_ENTITY_PAD,
    native_index_threshold: Optional[int] = None,
    device: bool = True,
    model_id: str = "",
    entity_shard: Optional[Tuple[int, int]] = None,
) -> ModelBank:
    """A `game.model_io.LoadedGameModel` -> device-resident ModelBank.

    ``entity_shard=(s, n)``: load ONE entity shard of a sharded GAME
    model — each random-effect bank keeps only the entities the pod
    hash rule assigns to shard ``s`` (:func:`shard_entity_ids`), its
    EntityRowIndex resolves every other entity to -1, and those
    requests score FE-only exactly like unknown entities do today.
    This is the serving seam for the ROADMAP's entity-sharded serving
    banks: N servers each load 1/N of the rows. Matrix factorizations
    are not sharded (their two latent banks pair row AND column
    entities per request).

    ``index_maps`` must cover every shard the model references (serving
    has no dataset to infer a vocabulary from — the same prebuilt-maps
    requirement as streaming batch scoring). ``shard_widths`` fixes the
    per-shard request nnz width ``k`` baked into the program shapes.

    Coordinate order is the batch scorer's (fixed effects, then random
    effects, then matrix factorizations, each in load order) so the
    per-row float adds happen in the identical sequence.

    ``device=False`` keeps host numpy arrays — the staging half of the
    hot-swap path, which device-places through the donating refresh
    program instead.
    """
    spec: List[tuple] = []
    arrays: Dict[str, object] = {}
    entity_rows: Dict[str, EntityRowIndex] = {}

    def _imap(shard_id: str):
        m = index_maps.get(shard_id)
        if m is None:
            raise ValueError(
                f"serving bank needs an index map for shard {shard_id!r} "
                "(prebuilt feature maps are required on the request path)"
            )
        return m

    def _width(shard_id: str) -> int:
        k = shard_widths.get(shard_id)
        if not k or k < 1:
            raise ValueError(
                f"serving bank needs a request nnz width for shard "
                f"{shard_id!r} (got {k!r})"
            )
        return int(k)

    for name, (shard_id, means) in loaded.fixed_effects.items():
        imap = _imap(shard_id)
        w = _fe_weights(means, imap)
        spec.append(("fe", name, shard_id, imap.size, _width(shard_id)))
        arrays[name] = w

    for name, (re_type, shard_id, per_entity) in loaded.random_effects.items():
        imap = _imap(shard_id)
        ids = shard_entity_ids(sorted(per_entity), entity_shard)
        e_pad = max(_round_up(max(len(ids), 1), entity_pad_to), entity_pad_to)
        bank = _re_bank(per_entity, ids, imap, e_pad)
        if re_type in entity_rows and entity_rows[re_type].ids != ids:
            raise ValueError(
                f"random-effect coordinates disagree on the {re_type!r} "
                "entity set; per-coordinate indexes are not supported"
            )
        entity_rows.setdefault(
            re_type,
            EntityRowIndex(
                ids, native_threshold=native_index_threshold,
                shard=entity_shard,
            ),
        )
        spec.append(
            ("re", name, re_type, shard_id, e_pad, imap.size,
             _width(shard_id))
        )
        arrays[name] = bank

    for name, (row_t, col_t, rows, cols) in loaded.matrix_factorizations.items():
        K = len(next(iter(rows.values()))) if rows else 0
        banks = []
        for id_type, latent in ((row_t, rows), (col_t, cols)):
            ids = sorted(latent)
            e_pad = max(
                _round_up(max(len(ids), 1), entity_pad_to), entity_pad_to
            )
            b = np.zeros((e_pad, max(K, 1)), np.float32)
            for row, rid in enumerate(ids):
                b[row] = latent[rid]
            if id_type in entity_rows and entity_rows[id_type].ids != ids:
                raise ValueError(
                    f"coordinates disagree on the {id_type!r} entity set"
                )
            entity_rows.setdefault(
                id_type,
                EntityRowIndex(ids, native_threshold=native_index_threshold),
            )
            banks.append(b)
        spec.append(
            ("mf", name, row_t, col_t,
             banks[0].shape[0], banks[1].shape[0], max(K, 1))
        )
        arrays[name] = (banks[0], banks[1])

    if device:
        arrays = place_on_device(arrays)
    return ModelBank(
        generation=generation,
        spec=tuple(spec),
        arrays=arrays,
        entity_rows=entity_rows,
        index_maps=dict(index_maps),
        shard_widths={k: int(v) for k, v in shard_widths.items()},
        model_id=model_id,
    )


def place_on_device(arrays: Dict[str, object]) -> Dict[str, object]:
    return {
        name: (
            tuple(jnp.asarray(a) for a in v)
            if isinstance(v, tuple)
            else jnp.asarray(v)
        )
        for name, v in arrays.items()
    }


def bank_from_arrays(
    *,
    generation: int = 1,
    fixed: Sequence[Tuple[str, str, np.ndarray]] = (),
    random: Sequence[Tuple[str, str, str, np.ndarray, Sequence[str]]] = (),
    shard_widths: Mapping[str, int],
    index_maps: Optional[Mapping[str, object]] = None,
    entity_pad_to: int = DEFAULT_ENTITY_PAD,
    native_index_threshold: Optional[int] = None,
    entity_shard: Optional[Tuple[int, int]] = None,
) -> ModelBank:
    """Assemble a bank directly from coefficient arrays — the synthetic/
    bench entry point (no Avro artifacts, same device layout).

    ``fixed``: (name, shard_id, w[d]); ``random``: (name, re_type,
    shard_id, bank[E, d], entity_ids). ``entity_shard=(s, n)`` keeps
    only shard ``s``'s rows of each random-effect bank (the pod hash
    rule over each bank's given row order — callers pass sorted ids,
    matching the artifact layout).
    """
    spec: List[tuple] = []
    arrays: Dict[str, object] = {}
    entity_rows: Dict[str, EntityRowIndex] = {}
    for name, shard_id, w in fixed:
        w = np.asarray(w, np.float32)
        spec.append(
            ("fe", name, shard_id, int(w.shape[0]),
             int(shard_widths[shard_id]))
        )
        arrays[name] = w
    for name, re_type, shard_id, bank, entity_ids in random:
        bank = np.asarray(bank, np.float32)
        ids = list(entity_ids)
        if bank.shape[0] != len(ids):
            raise ValueError(
                f"bank rows {bank.shape[0]} != entity ids {len(ids)}"
            )
        if entity_shard is not None:
            s, n_sh = ownership.validate_entity_shard(entity_shard)
            keep = list(ownership.owned_positions(len(ids), s, n_sh))
            ids = shard_entity_ids(ids, entity_shard)
            bank = bank[keep]
        e_pad = max(_round_up(max(len(ids), 1), entity_pad_to), entity_pad_to)
        padded = np.zeros((e_pad, bank.shape[1]), np.float32)
        padded[: bank.shape[0]] = bank
        entity_rows.setdefault(
            re_type,
            EntityRowIndex(
                ids, native_threshold=native_index_threshold,
                shard=entity_shard,
            ),
        )
        spec.append(
            ("re", name, re_type, shard_id, e_pad, int(bank.shape[1]),
             int(shard_widths[shard_id]))
        )
        arrays[name] = padded
    return ModelBank(
        generation=generation,
        spec=tuple(spec),
        arrays=place_on_device(arrays),
        entity_rows=entity_rows,
        index_maps=dict(index_maps or {}),
        shard_widths={k: int(v) for k, v in shard_widths.items()},
    )
