"""Admission control for the request path: named terminal outcomes,
deadline bookkeeping, and the queue-wait predictor behind load shedding.

The overload-control contract (the Orca-style continuous-batching
schedulers, PAPERS.md): a serving loop that is past capacity must say
"no" QUICKLY and KEEP its latency promise to the requests it admits —
an unbounded queue converts overload into unbounded p99 for everyone.
Three mechanisms, all host-side and allocation-free on the hot path:

- **Deadlines.** Every request may carry a client-propagated
  ``deadline_ms`` (milliseconds from enqueue). A request whose deadline
  passes while queued is dropped *before* dispatch — the device never
  scores dead work — and its future fails with
  :class:`DeadlineExceeded`.
- **Shedding.** ``MicroBatcher.submit`` consults
  :class:`AdmissionController` — an EWMA model of per-row service time
  — and refuses immediately (:class:`RequestShed`) when the predicted
  queue wait already exceeds the request's deadline. A full queue
  blocks only for the request's own remaining budget, never forever.
- **Named outcomes.** Every accepted request reaches EXACTLY ONE
  terminal state: a result, or one of the :class:`ServingError`
  subclasses below, each carrying a stable ``code`` the front-end maps
  onto the wire. Nothing on the request path hangs, and nothing fails
  anonymously.

:class:`ScoreOutcome` is a ``float`` subclass so existing callers (and
the bitwise parity tests) keep comparing scores as plain numbers while
the front-end reads the ``degraded``/``generation`` annotations off the
same object.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

__all__ = [
    "ServingError",
    "RequestShed",
    "DeadlineExceeded",
    "DrainTimeout",
    "BatcherClosed",
    "NoShardAvailable",
    "ScoreOutcome",
    "PartialScore",
    "AdmissionController",
]


class ServingError(RuntimeError):
    """Base of the request path's named terminal failures. ``code`` is
    the stable wire-level identifier (the front-end's ``error`` field
    and the metrics outcome key) — messages are for humans, codes are
    the contract."""

    code = "INTERNAL"

    def __init__(self, message: str):
        super().__init__(message)


class RequestShed(ServingError):
    """Admission refused the request up front: the predicted queue wait
    (or a bounded full-queue wait) already exceeds its deadline. Shed
    requests never occupy a queue slot past their budget and never reach
    the device. ``reason`` is the metrics key (``predicted_wait`` /
    ``queue_full``) — carried on the exception so the accounting can
    happen OUTSIDE the queue lock (PL010 atomicity-hygiene: no foreign
    critical section inside the Condition-backed submit lock)."""

    code = "SHED"

    def __init__(self, message: str, *, reason: str = "shed"):
        super().__init__(message)
        self.reason = reason


class DeadlineExceeded(ServingError):
    """The request's deadline passed while it sat in the queue; it was
    dropped before dispatch so the device never scored dead work."""

    code = "DEADLINE_EXCEEDED"


class DrainTimeout(ServingError):
    """The batcher was asked to drain and this request was still
    pending when the drain budget ran out. The named leftover-failure
    of the SIGTERM path — never a hung future."""

    code = "DRAIN_TIMEOUT"


class BatcherClosed(ServingError):
    """Submitted to a closed (or draining) batcher."""

    code = "CLOSED"


class NoShardAvailable(ServingError):
    """The routing tier could not reach ANY healthy shard-server for
    the fixed-effect half of a request — degradation needs at least one
    live shard to compute the FE score, so this is the router's only
    hard failure (one dead shard degrades, ALL dead shards refuse)."""

    code = "NO_SHARD"


class ScoreOutcome(float):
    """A score that is still a ``float`` (bitwise comparisons, numpy
    coercion and the existing parity tests all work unchanged) but
    carries the response annotations the front-end needs:

    - ``degraded`` — True when one or more random-effect coordinates
      could not be resolved (quarantined bank or a failed row lookup)
      and the request was scored FE-only instead of failed;
    - ``generation`` — the model-bank generation the batch ran on.
    """

    __slots__ = ("degraded", "generation")

    def __new__(
        cls, value: float, *, degraded: bool = False, generation: int = 0
    ) -> "ScoreOutcome":
        self = super().__new__(cls, value)
        self.degraded = bool(degraded)
        self.generation = int(generation)
        return self

    def __repr__(self) -> str:  # float repr + the annotations
        return (
            f"ScoreOutcome({float(self)!r}, degraded={self.degraded}, "
            f"generation={self.generation})"
        )


class PartialScore:
    """One shard-server's half of a routed score: the fixed-effect
    accumulation (every shard holds the full FE banks, so any shard can
    produce it — bitwise identical across shards) plus this shard's
    per-coordinate random-effect/MF terms, each an IEEE float32 the
    router re-sums in spec order. ``terms`` maps coordinate NAME ->
    term value; a coordinate whose entity this shard does not own (or
    the model does not know) contributes exactly ``0.0`` — the same
    zero the single-server program adds, which is what makes the
    routed recomposition bitwise-equal to the unrouted path.

    Immutable value object; the shard-mode batcher resolves futures
    with these instead of :class:`ScoreOutcome`. Two storage forms, one
    contract: the dict form (``__init__``) and the vectorized form
    (:meth:`from_vector` — name tuple + f32 vector straight out of the
    dispatch's gathered terms, no per-float dict build on the hot
    path). ``terms`` materializes the dict lazily; the binary wire's
    single-pass encoder reads :meth:`term_vector` and never pays for
    the dict at all.
    """

    __slots__ = ("fe", "offset", "degraded", "generation",
                 "_terms", "_names", "_vec")

    def __init__(
        self,
        fe: float,
        terms,
        *,
        offset: float = 0.0,
        degraded: bool = False,
        generation: int = 0,
    ):
        self.fe = float(fe)
        self._terms: Optional[dict] = dict(terms)
        self._names = None
        self._vec = None
        self.offset = float(offset)
        self.degraded = bool(degraded)
        self.generation = int(generation)

    @classmethod
    def from_vector(
        cls,
        fe: float,
        names,
        vec,
        *,
        offset: float = 0.0,
        degraded: bool = False,
        generation: int = 0,
    ) -> "PartialScore":
        """Build from the dispatcher's per-request term row: ``names``
        in spec order, ``vec`` the matching f32 values. O(1) — the
        vector is referenced, not copied, and no dict is built."""
        self = cls.__new__(cls)
        self.fe = float(fe)
        self._terms = None
        self._names = tuple(names)
        self._vec = np.asarray(vec, dtype=np.float32)
        self.offset = float(offset)
        self.degraded = bool(degraded)
        self.generation = int(generation)
        return self

    @property
    def terms(self) -> dict:
        """NAME -> float term value (exact f64 of each f32, identical
        to what the JSON wire round-trips). Materialized once on first
        access for vector-form instances."""
        t = self._terms
        if t is None:
            t = dict(zip(self._names, self._vec.tolist()))
            self._terms = t
        return t

    def term_vector(self):
        """``(names, f32 vector)`` in a stable order — the binary
        wire's single-copy encode source. Dict-form instances pay the
        conversion once, here, instead of per encode."""
        if self._names is None:
            names = tuple(self._terms)
            self._vec = np.fromiter(
                (self._terms[n] for n in names),
                dtype=np.float32,
                count=len(names),
            )
            self._names = names
        return self._names, self._vec

    def __repr__(self) -> str:
        return (
            f"PartialScore(fe={self.fe!r}, terms={self.terms!r}, "
            f"offset={self.offset!r}, degraded={self.degraded}, "
            f"generation={self.generation})"
        )


class AdmissionController:
    """EWMA service-time model -> predicted queue wait.

    ``note_dispatch`` feeds it one (rows, busy seconds) observation per
    dispatched micro-batch; ``predicted_wait_s(queue_len)`` is the
    expected time a request joining the back of the queue waits before
    its own dispatch starts. Deliberately simple and conservative:

    - per-ROW time (busy_s / rows) already amortizes batching, so the
      prediction scales with queue DEPTH, not dispatch count;
    - cold start (no observations yet) predicts 0 — admit everything
      until there is evidence of cost, so an idle service never sheds
      its first request;
    - the EWMA (default ``alpha=0.2``) tracks shape changes (a hot swap
      to a bigger model, a ladder rung change) within a few dispatches
      without oscillating on scheduler noise.
    """

    def __init__(self, alpha: float = 0.2):
        self._alpha = float(alpha)
        # single-writer atomic publish: only the dispatcher thread
        # writes (one plain reference assignment per dispatch), and
        # submit-side readers take a snapshot — so predicted_wait_s is
        # LOCK-FREE and safe to call inside the batcher's queue lock
        # (no foreign critical section under the Condition-backed lock,
        # PL010)
        self._per_row_s: Optional[float] = None  # photon: guarded-by(atomic)

    def note_dispatch(self, rows: int, busy_s: float) -> None:
        per_row = max(busy_s, 0.0) / max(int(rows), 1)
        cur = self._per_row_s
        self._per_row_s = (
            per_row if cur is None
            else cur + self._alpha * (per_row - cur)
        )

    def per_row_s(self) -> float:
        return self._per_row_s or 0.0

    def predicted_wait_s(self, queue_len: int) -> float:
        cur = self._per_row_s
        if cur is None:
            return 0.0
        return max(int(queue_len), 0) * cur
