"""Unified (data × feature × entity × grid) mesh-shape policy.

One mesh, four axis roles (parallel/mesh.py constants):

- ``data``    — example rows. On the unified mesh the ENTITY axis doubles
  as the row axis for row-aligned currency (residuals, scores): rows are
  sharded over the entity axis exactly like the pod path, so the
  two-hop residual exchange stays one all_to_all per CD iteration.
- ``model``   — feature/coefficient blocks (the feature-sharded FE solve).
  ``feature_blocks`` records the requested block count; the unified GAME
  grid program keeps the FE member solves replicated (feature_blocks=1)
  and the (data, model) mesh family covers the sharded-FE sweep.
- ``entity``  — hash-sharded random-effect banks (game/pod.py ownership
  rule: entity ``e`` lives on shard ``e % N`` at local row ``e // N``).
- ``grid``    — λ-grid members. A [G, ...] coefficient/optimizer bank is
  ``P(grid, entity)``-sharded so the whole regularization sweep runs as
  ONE shard_mapped program (game/unified.py), the tile schedule is
  walked once per grid, and the entity all_to_all is amortized across
  the grid axis.

:func:`resolve_mesh` is the one driver policy seam: given the device
pool, the grid size G, the requested entity shard count N and the
per-member bank footprint, it picks the (grid_rows, entity_shards) mesh
shape, preferring grid rows that divide G (no padding members) and
reporting the per-device bank bytes against the memory budget — the
entity-sharded twin of ``training.resolve_grid_mode``'s replicated
accounting.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.parallel.mesh import ENTITY_AXIS, GRID_AXIS, make_mesh

__all__ = ["MeshPlan", "resolve_mesh"]


@dataclass(frozen=True)
class MeshPlan:
    """Resolved unified-mesh shape for one λ-grid × entity-sharded run.

    ``mesh`` carries axes ``(grid, entity)`` with shape
    ``(grid_rows, entity_shards)`` over the first
    ``grid_rows * entity_shards`` devices. ``members_per_row`` is the
    per-grid-row member count G_loc = ceil(G / grid_rows); the bank's
    leading axis is padded to ``grid_padded = grid_rows * G_loc``
    (padding members run inert copies of the last λ and are dropped at
    unpack)."""

    mesh: Mesh
    grid_size: int
    grid_rows: int
    entity_shards: int
    feature_blocks: int
    members_per_row: int
    per_device_bank_bytes: int
    budget_bytes: Optional[int]

    @property
    def grid_padded(self) -> int:
        return self.grid_rows * self.members_per_row

    @property
    def fits_budget(self) -> bool:
        return (
            self.budget_bytes is None
            or self.per_device_bank_bytes <= self.budget_bytes
        )

    def grid_entity_sharding(self) -> NamedSharding:
        """Sharding of a [G_pad, n_shards * E_loc, ...] bank: members
        over the grid axis, bank rows over the entity axis."""
        return NamedSharding(self.mesh, P(GRID_AXIS, ENTITY_AXIS))

    def pad_members(self, values):
        """Pad a per-member list to ``grid_padded`` by repeating the
        last member (inert duplicates, dropped at unpack)."""
        values = list(values)
        if not values:
            raise ValueError("empty member list")
        while len(values) < self.grid_padded:
            values.append(values[-1])
        return values


def resolve_mesh(
    devices=None,
    grid_size: int = 1,
    entity_shards: Optional[int] = None,
    feature_blocks: Optional[int] = None,
    budget: Optional[int] = None,
    *,
    member_bank_bytes: int = 0,
) -> MeshPlan:
    """Pick the (grid, entity) mesh shape for a G-member λ-grid over an
    N-entity-sharded GAME model.

    Policy: the entity axis gets exactly ``entity_shards`` devices
    (default 1 — replicated-bank semantics on a 1-wide axis); the grid
    axis gets the largest row count that (a) fits the remaining device
    pool and (b) divides G when any divisor fits, so no padding members
    run. ``member_bank_bytes`` (one member's bank + optimizer state,
    e.g. ``training.grid_bank_bytes(1, dim, ...)``) feeds the per-device
    accounting: under P(grid, entity) each device holds
    ``G_loc * bytes / N`` — the ~1/(R·N) footprint the replicated budget
    check cannot see."""
    devices = list(devices if devices is not None else jax.devices())
    n_dev = len(devices)
    if grid_size < 1:
        raise ValueError(f"grid_size must be >= 1, got {grid_size}")
    n_ent = 1 if entity_shards is None or entity_shards == 0 else (
        n_dev if entity_shards == -1 else int(entity_shards)
    )
    if not 1 <= n_ent <= n_dev:
        raise ValueError(
            f"entity_shards {entity_shards} out of range for {n_dev} "
            "visible devices"
        )
    blocks = 1 if feature_blocks is None else int(feature_blocks)
    if blocks < 1:
        raise ValueError(f"feature_blocks must be >= 1, got {feature_blocks}")

    usable = max(1, n_dev // n_ent)
    divisors = [r for r in range(1, usable + 1) if grid_size % r == 0]
    grid_rows = max(divisors) if divisors else min(usable, grid_size)
    members_per_row = -(-grid_size // grid_rows)
    per_device = (members_per_row * int(member_bank_bytes)) // max(n_ent, 1)

    mesh = make_mesh(
        (grid_rows, n_ent),
        (GRID_AXIS, ENTITY_AXIS),
        devices[: grid_rows * n_ent],
    )
    return MeshPlan(
        mesh=mesh,
        grid_size=int(grid_size),
        grid_rows=int(grid_rows),
        entity_shards=int(n_ent),
        feature_blocks=blocks,
        members_per_row=int(members_per_row),
        per_device_bank_bytes=int(per_device),
        budget_bytes=None if budget is None else int(budget),
    )
