"""Multi-host orchestration: process coordination, IO guards, input shards.

The reference boots one Spark driver that owns all IO while executors
compute (SparkContextConfiguration.scala:44-108 builds the YARN client;
every write happens driver-side). The JAX SPMD analog inverts control —
EVERY process runs the same program over its local devices — so the
concerns become:

- joining the coordination service (``jax.distributed.initialize``), after
  which ``jax.devices()`` spans all hosts and a Mesh over it makes psum
  ride ICI within a host and DCN across hosts;
- electing process 0 for host-side effects (output files, checkpoints,
  logs-of-record) — the "driver" role;
- splitting the HOST-side input stream across processes (each process
  feeds only its local devices; device-side sharding then sees a globally
  sharded batch).

Single-process runs (including the one-chip dev loop) pass through
untouched: ``initialize_multihost(None)`` is a no-op and every guard
degenerates to "yes, you are process 0 of 1".
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, TypeVar

T = TypeVar("T")

_initialized = False


def initialize_multihost(
    coordinator_address: Optional[str],
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the JAX coordination service (the SparkContext-boot analog).

    No-op (returns False) when ``coordinator_address`` is None — the
    single-process path. Safe to call once per process, before any other
    JAX usage; ``num_processes``/``process_id`` fall back to the standard
    cluster-environment auto-detection when None.
    """
    global _initialized
    if coordinator_address is None:
        return False
    if _initialized:
        return True
    import jax

    kwargs = {"coordinator_address": coordinator_address}
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    _initialized = True
    return True


def process_index() -> int:
    import jax

    return jax.process_index()


def process_count() -> int:
    import jax

    return jax.process_count()


def is_coordinator() -> bool:
    """True on the process elected for host-side effects (process 0) —
    the Spark-driver role for writes."""
    return process_index() == 0


def coordinator_only(fn):
    """Decorator: run ``fn`` only on process 0; other processes get None.
    For output/model/checkpoint writes that must happen exactly once."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if is_coordinator():
            return fn(*args, **kwargs)
        return None

    return wrapper


def prepare_output_dir(
    path: str,
    *,
    delete_if_exists: bool,
    hint: str = "",
) -> None:
    """Deterministic multi-host output-dir guard.

    EVERY process runs the read-only non-empty check, so a refusal raises
    the same error everywhere (no process left hanging at a barrier while
    the coordinator dies — the failure-detection property Spark gets from
    driver-centric writes). Only the coordinator mutates the directory;
    the barrier orders that mutation before anyone proceeds.
    """
    import os
    import shutil

    if os.path.isdir(path) and os.listdir(path) and not delete_if_exists:
        suffix = f" ({hint})" if hint else ""
        raise ValueError(
            f"output directory {path} exists and is non-empty{suffix}"
        )
    if is_coordinator():
        if os.path.isdir(path) and delete_if_exists:
            shutil.rmtree(path)
        os.makedirs(path, exist_ok=True)
    sync_processes("output-dir-ready")


def shard_assignment(item: T, num_shards: int) -> int:
    """Deterministic CONTENT-keyed shard of one work item: a stable
    CRC32 over the item's string form, mod the shard count. This is the
    assignment contract entity-hash sharding (game/pod.py) and the
    streaming input split both lean on: it depends only on the item
    itself, never on list position, so two processes that enumerate the
    same set in different orders agree on every item's owner. (Python's
    builtin ``hash`` is salted per process — exactly the wrong tool.)"""
    import zlib

    return zlib.crc32(str(item).encode("utf-8")) % num_shards


def process_shard(items: Sequence[T]) -> List[T]:
    """This process's slice of a host-side work list (input files, daily
    paths). Single-process returns the list unchanged.

    Assignment is CONTENT-keyed (:func:`shard_assignment`), not
    positional: the pre-round-14 round-robin (``index % n``) silently
    depended on every process enumerating the list in the same order —
    a filesystem whose listing order differs across hosts would both
    drop and double-read files. Now any reordering of the same item set
    yields the same per-process shard (pinned by test_multihost).
    Balance is probabilistic (CRC32-uniform) rather than exact, which
    for file lists is the same property the entity hash gives banks.

    NOTE: feeding device_put with per-process DIFFERENT batch contents is
    wrong — cross-process device_put requires the same global value on all
    hosts. Use this only with a pre-built shared index map and global-array
    assembly (jax.make_array_from_process_local_data); the drivers load
    replicated until that streaming input path lands."""
    n = process_count()
    if n <= 1:
        return list(items)
    i = process_index()
    return [x for x in items if shard_assignment(x, n) == i]


def sync_processes(name: str = "photon-ml-barrier") -> None:
    """Barrier across processes (no-op single-process). Use between a
    coordinator-only write and a global read of its output."""
    if process_count() <= 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
