"""Asynchronous host-device overlap: deferred readbacks, background host
prep, and async artifact IO.

BENCH_r05's roofline put the fused kernels at ~0.99x their dispatched-step
bound, yet end-to-end GAME training still ran ~1.3x over device-busy time
(PERF_NOTES round 5): ~125 ms of host gaps between bucket dispatches,
~100 ms synchronous relay readbacks per bank update, and a host-serial
streaming populate pass. After kernel saturation the next lever is
decoupling the host from the device — the step the Podracer architectures
(arxiv 2104.06272) and the pjit/TPUv4 training report (arxiv 2204.06514)
both identify, and what Spark's lazy DAG gives the Photon ML reference
for free: nothing forces a result until an action needs it.

Three primitives, used across GLM/GAME training:

1. **Deferred readbacks** (:class:`Deferred` / :func:`fetch_all`): device
   scalars (objective terms, regularization terms, tracker stat vectors)
   stay device-resident; consumers hold futures and ONE batched
   ``device_get`` per outer iteration materializes them all. Over a
   relay-attached chip every fetch is a ~100 ms round trip — batching
   turns per-bucket/per-coordinate pulls into one.
2. **Background host prep** (:func:`submit` / :func:`wait`): coordinate
   k+1's host work (bucket stacking, device transfer, AOT warm, the next
   lambda's problem setup) runs on a worker thread under coordinate k's
   device solves. JAX dispatch is async and thread-safe, so the device
   never waits for host-side staging that could have happened earlier.
3. **Async artifact IO** (:func:`submit_io` / :func:`drain_io`):
   checkpoint and metrics writes leave the training loop's critical path;
   a single-worker queue preserves write order and :func:`drain_io` is
   the barrier before anything that needs the files on disk (preemption
   stop, run exit).

Every device->host fetch in the GAME layer routes through
:func:`device_get` — the counting seam the readback-discipline regression
tests assert against (one batched readback per CD iteration, zero
per-bucket readbacks).

Overlap is ON by default; ``--no-overlap`` on the drivers (or
``PHOTON_NO_OVERLAP=1``, or :func:`set_overlap`) falls back to fully
serial execution — the escape hatch, and the A/B baseline for
``dev-scripts/bench_overlap.sh``. With overlap off, ``submit`` runs
inline, ``submit_io`` writes synchronously and :class:`Deferred` values
fetch eagerly, so the serial path is byte-identical to the pre-overlap
code.
"""

from __future__ import annotations

import os
import threading
from contextlib import contextmanager
from typing import Any, Callable, List, Optional, Sequence

__all__ = [
    "overlap_enabled",
    "set_overlap",
    "overlap_scope",
    "Deferred",
    "fetch_all",
    "device_get",
    "readback_stats",
    "reset_readback_stats",
    "submit",
    "wait",
    "submit_io",
    "drain_io",
]


# -- configuration -----------------------------------------------------------

_LOCK = threading.Lock()
_ENABLED: Optional[bool] = None


def overlap_enabled() -> bool:
    """Whether host-device overlap is active (default True; disabled by
    ``PHOTON_NO_OVERLAP=1`` / ``set_overlap(False)`` / driver
    ``--no-overlap``)."""
    global _ENABLED
    with _LOCK:
        if _ENABLED is None:
            _ENABLED = os.environ.get(
                "PHOTON_NO_OVERLAP", ""
            ).strip().lower() not in ("1", "true", "yes")
        return _ENABLED


def set_overlap(enabled: bool) -> None:
    global _ENABLED
    with _LOCK:
        _ENABLED = bool(enabled)


@contextmanager
def overlap_scope(enabled: bool):
    """Temporarily force overlap on/off (A/B harnesses, parity tests)."""
    global _ENABLED
    with _LOCK:
        prev = _ENABLED
        _ENABLED = bool(enabled)
    try:
        yield
    finally:
        with _LOCK:
            _ENABLED = prev


# -- readback seam -----------------------------------------------------------
#
# ALL device->host fetches in the GAME layer go through device_get so the
# regression tests can count them. jax.profiler covers device time; this
# covers the transfer DISCIPLINE, which a relay-attached chip prices at
# ~100 ms per call regardless of payload.

_READBACK_CALLS = 0


def device_get(tree):
    """The one device->host fetch: ``jax.device_get`` plus the readback
    counter the discipline tests assert against."""
    global _READBACK_CALLS
    import jax

    with _LOCK:
        _READBACK_CALLS += 1
    return jax.device_get(tree)


def readback_stats() -> int:
    """Number of device_get calls since the last reset."""
    with _LOCK:
        return _READBACK_CALLS


def reset_readback_stats() -> None:
    global _READBACK_CALLS
    with _LOCK:
        _READBACK_CALLS = 0


# -- deferred readbacks ------------------------------------------------------


class Deferred:
    """A device-resident value plus a host-side ``finalize``: the future
    half of a batched readback.

    ``device_value`` may be any pytree of device arrays. ``finalize``
    (host_tree -> result) runs exactly once, after the fetch. ``result()``
    forces an INDIVIDUAL fetch when the value was never batch-fetched —
    correctness never depends on the batching, only latency does. With
    overlap disabled the fetch happens eagerly at construction, so serial
    runs see the exact pre-overlap readback order.
    """

    __slots__ = ("_device", "_finalize", "_result", "_done")

    def __init__(self, device_value, finalize: Optional[Callable] = None):
        self._device = device_value
        self._finalize = finalize
        self._result = None
        self._done = False
        if not overlap_enabled():
            self._deliver(device_get(device_value))

    def _deliver(self, host_value) -> None:
        if self._done:
            return
        self._result = (
            self._finalize(host_value) if self._finalize else host_value
        )
        self._done = True
        self._device = None  # release the device reference

    @property
    def done(self) -> bool:
        return self._done

    def result(self):
        if not self._done:
            self._deliver(device_get(self._device))
        return self._result


def fetch_all(deferreds: Sequence[Optional[Deferred]]) -> None:
    """Materialize every pending Deferred with ONE batched device_get
    (one transfer round trip for the whole list)."""
    import time

    from photon_ml_tpu.utils.profiling import record_host_timing

    pending = [d for d in deferreds if d is not None and not d.done]
    if not pending:
        return
    t0 = time.perf_counter()
    host = device_get([d._device for d in pending])
    record_host_timing("overlap_fetch_s", time.perf_counter() - t0)
    for d, h in zip(pending, host):
        d._deliver(h)


# -- background host prep ----------------------------------------------------
#
# One worker: prep tasks are already coarse (a whole coordinate's staging)
# and a single thread keeps cache mutations race-free by construction —
# the main thread only touches a coordinate AFTER wait()ing on its prep.

_PREP_POOL = None
_IO_POOL = None
_IO_PENDING: List = []
# (artifact, exception) of failed async writes, in submission order. The
# worker wrapper records instead of raising so the FIFO keeps draining
# the writes QUEUED BEHIND a failure; drain_io() re-raises the first one
# with its artifact name — a failed checkpoint/part-file write can never
# masquerade as success.
_IO_FAILURES: List = []


def _pool(which: str):
    global _PREP_POOL, _IO_POOL
    from concurrent.futures import ThreadPoolExecutor

    with _LOCK:
        if which == "prep":
            if _PREP_POOL is None:
                _PREP_POOL = ThreadPoolExecutor(
                    1, thread_name_prefix="photon-overlap-prep"
                )
            return _PREP_POOL
        if _IO_POOL is None:
            _IO_POOL = ThreadPoolExecutor(
                1, thread_name_prefix="photon-overlap-io"
            )
        return _IO_POOL


class _InlineFuture:
    """Future facade for the overlap-off path: runs eagerly on submit."""

    __slots__ = ("_result", "_exc")

    def __init__(self, fn, args, kwargs):
        self._exc = None
        self._result = None
        try:
            self._result = fn(*args, **kwargs)
        except BaseException as e:  # re-raised on result(), like a Future
            self._exc = e

    def result(self):
        if self._exc is not None:
            raise self._exc
        return self._result


def submit(fn: Callable, *args, **kwargs):
    """Run ``fn`` on the prep worker (overlap on) or inline (overlap
    off); returns a future either way."""
    if not overlap_enabled():
        return _InlineFuture(fn, args, kwargs)
    return _pool("prep").submit(fn, *args, **kwargs)


def wait(future) -> Any:
    """Block on a future from :func:`submit` (None passes through).
    Wait time accrues to the ``overlap_prep_wait_s`` host-timing bucket —
    ~0 means the prep fully hid under the device work."""
    if future is None:
        return None
    if isinstance(future, _InlineFuture):
        return future.result()
    import time

    from photon_ml_tpu.utils.profiling import record_host_timing

    t0 = time.perf_counter()
    try:
        return future.result()
    finally:
        record_host_timing(
            "overlap_prep_wait_s", time.perf_counter() - t0
        )


# -- async artifact IO -------------------------------------------------------


def submit_io(fn: Callable, *args, artifact: str = "", **kwargs) -> None:
    """Queue an artifact write (checkpoint step, metrics.json) on the IO
    worker; FIFO order is preserved. Overlap off -> synchronous write.

    ``artifact`` names what is being written — it travels with any
    failure to :func:`drain_io` so the error is attributable. The write
    runs behind the reliability layer's ``io_worker`` seam (fault
    injection + bounded retries, photon_ml_tpu/reliability)."""
    from photon_ml_tpu.reliability.retry import io_call

    if not overlap_enabled():
        io_call("io_worker", fn, *args, detail=artifact, **kwargs)
        return

    def _guarded() -> None:
        try:
            io_call("io_worker", fn, *args, detail=artifact, **kwargs)
        except BaseException as e:
            with _LOCK:
                _IO_FAILURES.append((artifact, e))

    pool = _pool("io")  # resolves OUTSIDE _LOCK (it takes _LOCK itself)
    with _LOCK:
        _IO_PENDING.append(pool.submit(_guarded))


def drain_io() -> None:
    """Barrier: every queued IO write is on disk (or raised) after this.
    Call before anything that requires the artifacts — preemption stop,
    checkpoint restore, run exit. The FIRST recorded worker failure
    re-raises here with its artifact name (later queued writes still
    drained first — write order stays FIFO even across a failure). Wait
    time accrues to the ``overlap_io_wait_s`` host-timing bucket."""
    import time

    from photon_ml_tpu.utils.profiling import record_host_timing

    t0 = time.perf_counter()
    try:
        while True:
            with _LOCK:
                if not _IO_PENDING:
                    break
                fut = _IO_PENDING.pop(0)
            fut.result()  # _guarded never raises; this waits completion
        with _LOCK:
            if not _IO_FAILURES:
                return
            artifact, exc = _IO_FAILURES[0]
            _IO_FAILURES.clear()
        raise RuntimeError(
            "async artifact write failed"
            + (f" for {artifact!r}" if artifact else "")
            + f": {exc}"
        ) from exc
    finally:
        record_host_timing("overlap_io_wait_s", time.perf_counter() - t0)
