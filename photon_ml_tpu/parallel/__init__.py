"""Distributed runtime: meshes, sharded objectives, feature-axis sharding.

The XLA-collective replacement for the reference's Spark layer (SURVEY
sect. 2.4): psum = treeAggregate, replicated sharding = broadcast,
all_to_all/sorts = shuffle.
"""

from photon_ml_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_sharding,
    make_mesh,
    replicate,
    replicated,
    shard_batch,
)
from photon_ml_tpu.parallel.multihost import (
    initialize_multihost,
    is_coordinator,
    process_count,
    process_index,
    process_shard,
    sync_processes,
)
from photon_ml_tpu.parallel.shuffle import (
    ShuffledRows,
    entity_all_to_all,
    reshard_capacity,
)
from photon_ml_tpu.parallel.distributed import (
    FeatureShardedSparseBatch,
    data_parallel_fit_lbfgs,
    data_parallel_value_and_grad,
    feature_shard_sparse_batch,
    feature_sharded_fit,
    feature_sharded_sparse_fit,
    feature_sharded_sparse_fit_owlqn,
    feature_sharded_value_and_grad,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "data_sharding",
    "make_mesh",
    "replicate",
    "replicated",
    "shard_batch",
    "initialize_multihost",
    "is_coordinator",
    "process_count",
    "process_index",
    "process_shard",
    "sync_processes",
    "ShuffledRows",
    "entity_all_to_all",
    "reshard_capacity",
    "FeatureShardedSparseBatch",
    "data_parallel_fit_lbfgs",
    "data_parallel_value_and_grad",
    "feature_shard_sparse_batch",
    "feature_sharded_fit",
    "feature_sharded_sparse_fit",
    "feature_sharded_sparse_fit_owlqn",
    "feature_sharded_value_and_grad",
]
