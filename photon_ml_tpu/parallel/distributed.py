"""Distributed GLM objectives and training steps under shard_map.

Reference mapping (SURVEY §2.3/§2.4):
- P1 data parallelism: examples sharded over the "data" axis, coefficients
  replicated, (value, grad, Hv) psum'ed — replaces
  DistributedGLMLossFunction + ValueAndGradientAggregator.treeAggregate
  (ValueAndGradientAggregator.scala:235-250).
- Feature/coefficient parallelism ("model" axis): for coefficient vectors
  too big to replicate, margins decompose over feature blocks
  (z = sum_blocks x_b . w_b -> psum over "model"), and each device keeps
  only its gradient/optimizer-state block — the reduce-scatter/all-gather
  recipe of sequence parallelism applied to the feature axis (the 10B-coef
  design addition; no literal analog exists in the reference).

Both run the UNMODIFIED optimizers from photon_ml_tpu.optim: the objective
closure psums, so LBFGS/OWLQN/TRON never know they are distributed —
exactly how the reference reuses one Optimizer against Distributed vs
SingleNode objectives (SURVEY L2/L3).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from photon_ml_tpu.data.batch import Batch
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.common import OptResult
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs
from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

Array = jnp.ndarray


def data_parallel_value_and_grad(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
) -> Callable:
    """(w, batch, l2) -> (value, grad), batch sharded over ``data_axis``,
    coefficients replicated. One psum per evaluation (the treeAggregate)."""
    obj = objective.with_axis(data_axis)

    # photon: sharding(axes=[data], in=[r,data,r], out=[r,r])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(data_axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def vg(w, batch, l2):
        return obj.value_and_gradient(w, batch, l2)

    return jax.jit(vg)


def data_parallel_fit_lbfgs(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    max_iter: int = 100,
    tol: float = 1e-7,
    history: int = 10,
) -> Callable[[Array, Batch, Array], OptResult]:
    """Whole L-BFGS fit inside ONE shard_map program: per-iteration psums
    ride ICI with no host round-trips (vs one treeAggregate round-trip per
    Breeze iteration in the reference, SURVEY §3.1)."""
    obj = objective.with_axis(data_axis)

    # photon: sharding(axes=[data], in=[r,data,r], out=[r])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(data_axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    def fit(w0, batch, l2):
        vg = lambda w: obj.value_and_gradient(w, batch, l2)
        return minimize_lbfgs(
            vg, w0, max_iter=max_iter, tol=tol, history=history
        )

    return jax.jit(fit)


# ---------------------------------------------------------------------------
# Feature-axis ("model") sharding for >HBM coefficient vectors
# ---------------------------------------------------------------------------


def feature_sharded_value_and_grad(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
) -> Callable:
    """2-D sharded objective over DENSE feature blocks.

    Layout: features [n, d] sharded P(data, model); w [d] sharded P(model);
    per-device partial margins psum over ``model_axis``; loss row-reductions
    psum over ``data_axis``; gradient blocks stay device-local (each device
    owns grad[d_block] — reduce-scatter-free by construction). Returns
    (value replicated, grad sharded P(model)).
    """
    loss = objective.loss

    # photon: sharding(axes=[data,model], in=[model,data+model,data,data,data,r], out=[r,model])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(model_axis), P(data_axis, model_axis), P(data_axis), P(data_axis), P(data_axis), P()),
        out_specs=(P(), P(model_axis)),
        check_vma=False,
    )
    def vg(w_block, x_block, labels, offsets, weights, l2):
        # partial margins from this feature block, summed across blocks
        z = jax.lax.psum(x_block @ w_block, model_axis) + offsets
        lv = loss.value(z, labels)
        ld = loss.d1(z, labels)
        c = weights * ld
        value = jax.lax.psum(jnp.sum(weights * lv), data_axis)
        # gradient for THIS feature block only; reduce over examples
        grad_block = jax.lax.psum(x_block.T @ c, data_axis)
        # L2 term: w stays sharded; psum the squared-norm contributions
        w_sq = jax.lax.psum(jnp.vdot(w_block, w_block), model_axis)
        value = value + 0.5 * l2 * w_sq
        grad_block = grad_block + l2 * w_block
        return value, grad_block

    return jax.jit(vg)


def _opt_result_specs(model_axis: str, track_models: bool = False) -> OptResult:
    """out_specs pytree for an OptResult whose coefficient vector is sharded
    over ``model_axis`` while every scalar/trace is replicated (scalars are
    psum'ed mesh-global inside the optimizer, so they agree on all ranks).
    With ``track_models`` the per-iteration coefficient stack is sharded
    over its feature axis like the coefficients themselves."""
    from photon_ml_tpu.optim.common import Tracker

    return OptResult(
        coefficients=P(model_axis),
        value=P(),
        grad_norm=P(),
        iterations=P(),
        reason=P(),
        tracker=Tracker(
            values=P(), grad_norms=P(), count=P(),
            coefs=P(None, model_axis) if track_models else None,
        ),
    )


def _opt_result_grid_specs(
    model_axis: str, track_models: bool = False
) -> OptResult:
    """Grid-batched variant of :func:`_opt_result_specs`: every field
    carries a leading [G] grid axis (replicated — the grid members live
    on every device), with the coefficient banks still sharded over
    ``model_axis`` on their feature axis."""
    from photon_ml_tpu.optim.common import Tracker

    return OptResult(
        coefficients=P(None, model_axis),
        value=P(),
        grad_norm=P(),
        iterations=P(),
        reason=P(),
        tracker=Tracker(
            values=P(), grad_norms=P(), count=P(),
            coefs=P(None, None, model_axis) if track_models else None,
        ),
    )


def feature_sharded_fit(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    max_iter: int = 50,
    tol: float = 1e-7,
    history: int = 10,
) -> Callable:
    """L-BFGS over a feature-sharded coefficient vector: optimizer state
    ([m, d_block] memories, w block) lives SHARDED on every device; the only
    cross-block traffic per iteration is the margin psum and the scalar
    reductions inside the two-loop recursion (vdots psum over model axis).

    Runs the UNMODIFIED ``minimize_lbfgs`` with ``axis_name=model_axis`` —
    the same program as the replicated/single-chip path, so convergence
    rules, trackers, and cautious updates cannot diverge. Returns a full
    OptResult (coefficients sharded over ``model_axis``).
    """
    loss = objective.loss

    # photon: sharding(axes=[data,model], in=[model,data+model,data,data,data,r], out=?)
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(model_axis), P(data_axis, model_axis), P(data_axis), P(data_axis), P(data_axis), P()),
        out_specs=_opt_result_specs(model_axis),
        check_vma=False,
    )
    def fit(w0_block, x_block, labels, offsets, weights, l2):
        def vg(w_block):
            z = jax.lax.psum(x_block @ w_block, model_axis) + offsets
            c = weights * loss.d1(z, labels)
            value = jax.lax.psum(jnp.sum(weights * loss.value(z, labels)), data_axis)
            grad_block = jax.lax.psum(x_block.T @ c, data_axis)
            w_sq = jax.lax.psum(jnp.vdot(w_block, w_block), model_axis)
            return value + 0.5 * l2 * w_sq, grad_block + l2 * w_block

        return minimize_lbfgs(
            vg, w0_block, max_iter=max_iter, tol=tol, history=history,
            axis_name=model_axis,
        )

    return jax.jit(fit)


# ---------------------------------------------------------------------------
# Sparse feature sharding (the 10B-coefficient layout)
# ---------------------------------------------------------------------------


class FeatureShardedSparseBatch(NamedTuple):
    """A SparseBatch re-laid-out for 2-D (data x model) sharding.

    At the 10B-coefficient north star the data is sparse by definition
    (SURVEY §2.3 "coefficient parallelism"); the dense [n, d] layout above
    cannot even be materialized. Here each feature block owns the entries
    whose feature id falls in its slice of the (padded) vocabulary:

    - ``indices[M, n, kb]`` int32 — BLOCK-LOCAL feature ids (global id
      minus block offset); slot (m, i, :) holds row i's entries landing in
      block m, zero-padded.
    - ``values[M, n, kb]`` — matching values, zero-padded (a padded slot
      contributes 0 * w_block[0]).
    - ``labels/offsets/weights[n]`` — row metadata, sharded over "data".

    Leading axis M shards over "model", rows shard over "data", so the
    shard_map block is [1, n/Dd, kb]. kb is the max per-(row, block) entry
    count — for hashed/uniform feature ids kb ~ k/M; worst case k.
    """

    indices: Array  # int32 [M, n, kb] block-local
    values: Array  # float [M, n, kb]
    labels: Array  # [n]
    offsets: Array  # [n]
    weights: Array  # [n]

    @property
    def num_blocks(self) -> int:
        return self.indices.shape[0]

    @property
    def num_rows(self) -> int:
        return self.indices.shape[1]


def feature_shard_sparse_batch(
    batch,
    dim: int,
    num_blocks: int,
    *,
    rows_multiple: int = 1,
    pad_nnz_to: int = 8,
) -> Tuple[FeatureShardedSparseBatch, int]:
    """Host-side re-layout of a SparseBatch into per-feature-block slabs.

    Returns (sharded_batch, block_dim) with block_dim = ceil(dim /
    num_blocks) rounded so every block covers an equal slice; the sharded
    coefficient vector has length num_blocks * block_dim (callers pad /
    slice against ``dim``). The partition is the static analog of the
    reference's hash-partitioned feature vocabulary
    (FeatureIndexingJob.scala:90-136) — but by contiguous range, so a
    block's ids gather from a dense local window.
    """
    import numpy as np

    idx = np.asarray(batch.indices)
    val = np.asarray(batch.values)
    n, k = idx.shape
    n_pad = ((n + rows_multiple - 1) // rows_multiple) * rows_multiple
    block_dim = -(-dim // num_blocks)

    block_of = idx // block_dim  # [n, k]
    local = idx - block_of * block_dim
    # Entries with value exactly 0 (padding) are inert wherever they land;
    # route them to block 0 so kb reflects real entries only.
    real = val != 0.0
    block_of = np.where(real, block_of, 0)

    # Vectorized routing: rank each real entry within its (block, row)
    # group via a stable sort; one scatter builds all slabs at once.
    rows_b = np.broadcast_to(np.arange(n)[:, None], (n, k))
    flat_key = (block_of * n + rows_b).ravel()  # group id per entry
    order = np.argsort(flat_key + (~real).ravel() * (num_blocks * n), kind="stable")
    sorted_key = flat_key[order]
    n_real = int(real.sum())
    group_start = np.searchsorted(sorted_key[:n_real], sorted_key[:n_real], side="left")
    slot = np.arange(n_real) - group_start  # rank within group

    counts = np.bincount(flat_key[real.ravel()], minlength=num_blocks * n)
    kb = int(max(counts.max(initial=0), 1))
    kb = ((kb + pad_nnz_to - 1) // pad_nnz_to) * pad_nnz_to

    out_idx = np.zeros((num_blocks, n_pad, kb), np.int32)
    out_val = np.zeros((num_blocks, n_pad, kb), val.dtype)
    sel = order[:n_real]
    b_sel = block_of.ravel()[sel]
    r_sel = rows_b.ravel()[sel]
    out_idx[b_sel, r_sel, slot] = local.ravel()[sel]
    out_val[b_sel, r_sel, slot] = val.ravel()[sel]

    def pad_rows(a):
        if n_pad == n:
            return a
        return np.concatenate([a, np.zeros((n_pad - n,), a.dtype)])

    sharded = FeatureShardedSparseBatch(
        indices=jnp.asarray(out_idx),
        values=jnp.asarray(out_val),
        labels=jnp.asarray(pad_rows(np.asarray(batch.labels))),
        offsets=jnp.asarray(pad_rows(np.asarray(batch.offsets))),
        weights=jnp.asarray(pad_rows(np.asarray(batch.weights))),
    )
    return sharded, block_dim


def _sparse_shard_specs(model_axis: str, data_axis: str):
    return (
        P(model_axis),
        FeatureShardedSparseBatch(
            indices=P(model_axis, data_axis),
            values=P(model_axis, data_axis),
            labels=P(data_axis),
            offsets=P(data_axis),
            weights=P(data_axis),
        ),
        P(),
    )


def _sparse_block_vg(loss, b, l2, model_axis: str, data_axis: str,
                     shift=None, factor=None):
    """Block-local (value, grad) closure shared by the sparse-sharded
    value_and_grad and fit entry points. ``b`` is this device's shard:
    one feature block x its rows.

    ``shift``/``factor``: this block's slice of the lazy normalization
    vectors (NormalizationContext.scala:119-157) — margins use
    w_eff = factor * w and subtract the psum'd shift.w_eff scalar; the
    gradient un-shifts with the data-psum'd prefactor."""
    assert b.indices.shape[0] == 1, (
        f"got {b.indices.shape[0]} feature blocks per device; "
        "num_blocks passed to feature_shard_sparse_batch must equal the "
        "mesh's model-axis size"
    )
    idx = b.indices[0]  # [n_loc, kb] block-local
    val = b.values[0]

    def vg(w_block):
        w_eff = w_block if factor is None else w_block * factor
        raw = jnp.sum(val * w_eff[idx], axis=-1)
        if shift is not None:
            raw = raw - jnp.vdot(shift, w_eff)
        z = jax.lax.psum(raw, model_axis) + b.offsets
        c = b.weights * loss.d1(z, b.labels)
        value = jax.lax.psum(
            jnp.sum(b.weights * loss.value(z, b.labels)), data_axis
        )
        grad_block = jax.lax.psum(
            jnp.zeros_like(w_block).at[idx].add(c[:, None] * val), data_axis
        )
        if shift is not None or factor is not None:
            prefactor = jax.lax.psum(jnp.sum(c), data_axis)
            if shift is not None:
                grad_block = grad_block - shift * prefactor
            if factor is not None:
                grad_block = grad_block * factor
        w_sq = jax.lax.psum(jnp.vdot(w_block, w_block), model_axis)
        return value + 0.5 * l2 * w_sq, grad_block + l2 * w_block

    return vg


def _sparse_block_hvp_factory(loss, b, l2, model_axis: str, data_axis: str,
                              shift=None, factor=None):
    """Block-local Hessian-vector FACTORY over one device's shard — the
    distributed HessianVectorAggregator analog
    (HessianVectorAggregator.scala:137-152). The w-only pieces (margins
    psum, second-derivative coefficients) are computed once per outer
    TRON iteration; each CG step then costs one psum of the direction's
    partial margins over "model" plus one psum of the block product over
    "data"."""
    idx = b.indices[0]
    val = b.values[0]

    def _z(x_block):
        raw = jnp.sum(val * x_block[idx], axis=-1)
        if shift is not None:
            raw = raw - jnp.vdot(shift, x_block)
        return raw

    def _eff(x_block):
        return x_block if factor is None else x_block * factor

    def factory(w_block):
        z = jax.lax.psum(_z(_eff(w_block)), model_axis) + b.offsets
        d2c = b.weights * loss.d2(z, b.labels)

        def hvp(d_block):
            zd = jax.lax.psum(_z(_eff(d_block)), model_axis)
            c = d2c * zd
            h_block = jax.lax.psum(
                jnp.zeros_like(d_block).at[idx].add(c[:, None] * val),
                data_axis,
            )
            if shift is not None or factor is not None:
                prefactor = jax.lax.psum(jnp.sum(c), data_axis)
                if shift is not None:
                    h_block = h_block - shift * prefactor
                if factor is not None:
                    h_block = h_block * factor
            return h_block + l2 * d_block

        return hvp

    return factory


def _sparse_block_hdiag(loss, b, l2, model_axis: str, data_axis: str,
                        shift=None, factor=None):
    """Block-local Hessian-diagonal closure (the variance computation of
    DistributedOptimizationProblem.scala:79-93 on the sharded layout):
    diag_j only touches feature j's entries, so it shards trivially —
    one scatter of c * val^2 psum'd over "data" (plus S1/S0 terms in the
    shifted space when normalization is active)."""
    idx = b.indices[0]
    val = b.values[0]

    def hdiag(w_block):
        w_eff = w_block if factor is None else w_block * factor
        raw = jnp.sum(val * w_eff[idx], axis=-1)
        if shift is not None:
            raw = raw - jnp.vdot(shift, w_eff)
        z = jax.lax.psum(raw, model_axis) + b.offsets
        c = b.weights * loss.d2(z, b.labels)
        s2 = jax.lax.psum(
            jnp.zeros_like(w_block).at[idx].add(c[:, None] * val**2),
            data_axis,
        )
        if shift is not None:
            s1 = jax.lax.psum(
                jnp.zeros_like(w_block).at[idx].add(c[:, None] * val),
                data_axis,
            )
            s0 = jax.lax.psum(jnp.sum(c), data_axis)
            diag = s2 - 2.0 * shift * s1 + (shift**2) * s0
        else:
            diag = s2
        if factor is not None:
            diag = diag * factor**2
        return diag + l2

    return hdiag


def feature_sharded_sparse_fit_tron(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_cg: int = 20,
) -> Callable:
    """TRON over a feature-sharded coefficient vector with sparse data:
    the reference's hottest distributed loop (one treeAggregate round-trip
    per CG iteration, SURVEY §3.2) becomes a while_loop whose every CG
    step is two psums over ICI. L2/none only (TRON+L1 is rejected by the
    optimizer factory, matching OptimizerFactory.scala:49-86).

    Thin wrapper over :func:`feature_sharded_glm_fit` (the one sharded
    program family) preserving this entry point's historical defaults."""
    return feature_sharded_glm_fit(
        objective, mesh, layout="sparse", optimizer="tron",
        data_axis=data_axis, model_axis=model_axis,
        max_iter=max_iter, tol=tol, max_cg=max_cg,
    )


def feature_sharded_sparse_value_and_grad(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
) -> Callable:
    """(w, sharded_batch, l2) -> (value, grad) over the sparse 2-D layout;
    value replicated, grad sharded over ``model_axis``."""
    loss = objective.loss

    # photon: sharding(axes=[data,model], in=?, out=[r,model])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=_sparse_shard_specs(model_axis, data_axis),
        out_specs=(P(), P(model_axis)),
        check_vma=False,
    )
    def vg(w_block, b, l2):
        return _sparse_block_vg(loss, b, l2, model_axis, data_axis)(w_block)

    return jax.jit(vg)


def feature_sharded_sparse_hessian_vector(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
) -> Callable:
    """(w, direction, sharded_batch, l2) -> H(w) @ d over the sparse 2-D
    layout, direction/result sharded over ``model_axis`` — the per-chunk
    building block of the STREAMED feature-sharded TRON (one streamed
    pass per CG step, accumulated chunk by chunk, exactly the
    HessianVectorAggregator.scala:137-152 aggregate with the chunk loop
    standing in for the executor partitions)."""
    loss = objective.loss

    # photon: sharding(axes=[data,model], in=?, out=[model])
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(model_axis), P(model_axis),
        ) + _sparse_shard_specs(model_axis, data_axis)[1:],
        out_specs=P(model_axis),
        check_vma=False,
    )
    def hv(w_block, d_block, b, l2):
        factory = _sparse_block_hvp_factory(
            loss, b, l2, model_axis, data_axis
        )
        return factory(w_block)(d_block)

    return jax.jit(hv)


def feature_sharded_sparse_fit(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    max_iter: int = 50,
    tol: float = 1e-7,
    history: int = 10,
) -> Callable:
    """L-BFGS over a feature-sharded coefficient vector with SPARSE data.

    ``fit(w0, sharded_batch, l2) -> OptResult``; ``w0`` is the full
    [num_blocks * block_dim] vector (sharded over ``model_axis`` by
    shard_map), the batch comes from :func:`feature_shard_sparse_batch`.
    Per evaluation: one psum of partial margins over the model axis + one
    psum of the block gradient over the data axis; gradient and optimizer
    state never leave their block's devices.

    Thin wrapper over :func:`feature_sharded_glm_fit` (the one sharded
    program family) preserving this entry point's historical defaults.
    """
    return feature_sharded_glm_fit(
        objective, mesh, layout="sparse", optimizer="lbfgs",
        data_axis=data_axis, model_axis=model_axis,
        max_iter=max_iter, tol=tol, history=history,
    )


def feature_sharded_tiled_fit(
    objective: GLMObjective,
    mesh: Mesh,
    meta,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    max_iter: int = 50,
    tol: float = 1e-7,
    history: int = 10,
    interpret: Optional[bool] = None,
    owlqn: bool = False,
) -> Callable:
    """L-BFGS (or OWL-QN with ``owlqn=True``) over a feature-sharded
    coefficient vector with the TILED Pallas kernels — the 10B-coefficient
    layout at full kernel speed (round 2 ran this path on ~7ns/element
    scatters; VERDICT r2 weak #2/3).

    ``fit(w0, batch, l2[, l1, l1_mask]) -> OptResult`` with ``batch`` a
    FeatureShardedTiledBatch built by
    ops.tiled_sparse.feature_shard_tiled_batch for this mesh's
    (data, model) shape; ``meta`` is that batch's static meta. Collective
    pattern per evaluation: one psum of partial margins over "model", one
    psum of the block gradient over "data" — identical to the scatter
    layout, so the optimizer and convergence rules are unchanged.

    Thin wrapper over :func:`feature_sharded_glm_fit` (the one sharded
    program family) preserving this entry point's historical defaults.
    """
    return feature_sharded_glm_fit(
        objective, mesh, meta, layout="tiled",
        optimizer="owlqn" if owlqn else "lbfgs",
        data_axis=data_axis, model_axis=model_axis,
        max_iter=max_iter, tol=tol, history=history, interpret=interpret,
    )


def feature_sharded_tiled_fit_tron(
    objective: GLMObjective,
    mesh: Mesh,
    meta,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    max_iter: int = 15,
    tol: float = 1e-5,
    max_cg: int = 20,
    interpret: Optional[bool] = None,
) -> Callable:
    """TRON over a feature-sharded coefficient vector with the TILED
    Pallas kernels: the reference's hottest distributed loop (one
    treeAggregate Hv per CG iteration, TRON.scala:259-341 +
    HessianVectorAggregator.scala:137-152) at full kernel speed on the
    10B-coefficient layout. Collective pattern per CG step: one psum of
    the direction's partial margins over "model" + one psum of the block
    Hv over "data" — identical to the scatter TRON, so convergence rules
    are unchanged. L2/none only (TRON+L1 rejected by the factory).

    Thin wrapper over :func:`feature_sharded_glm_fit` (the one sharded
    program family) preserving this entry point's historical defaults."""
    return feature_sharded_glm_fit(
        objective, mesh, meta, layout="tiled", optimizer="tron",
        data_axis=data_axis, model_axis=model_axis,
        max_iter=max_iter, tol=tol, max_cg=max_cg, interpret=interpret,
    )


# Jitted feature-sharded fit programs shared across builder calls: a
# GAME combo grid builds fresh coordinates (and fresh fit closures) per
# combo, and without sharing each pays a multi-second re-trace of the
# optimizer while_loop over the schedule pytrees (the round-2 lesson
# problem.py's _FIT_CACHE already encodes for the replicated path).
# Keyed by mesh CONTENT — shardings over content-equal meshes are
# interchangeable. FIFO-bounded; unhashable keys (e.g. array-carrying
# normalization contexts inside the objective) skip the cache.
_FS_FIT_CACHE: dict = {}
_FS_FIT_CACHE_MAX = 16


def _mesh_content_key(mesh: Mesh):
    # platform included: device ids are only unique PER platform, and a
    # process can hold both a CPU mesh (interpret fallback) and an
    # accelerator mesh with identical axes/ids
    return (
        tuple(mesh.axis_names),
        tuple(int(n) for n in mesh.devices.shape),
        tuple((d.platform, d.id) for d in mesh.devices.flat),
    )


def feature_sharded_glm_fit(
    objective: GLMObjective,
    mesh: Mesh,
    meta=None,
    *,
    layout: str = "sparse",  # "sparse" | "tiled"
    optimizer: str = "lbfgs",  # "lbfgs" | "owlqn" | "tron"
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    max_iter: int = 50,
    tol: float = 1e-7,
    history: int = 10,
    max_cg: int = 20,
    with_norm: bool = False,
    with_box: bool = False,
    track_models: bool = False,
    interpret: Optional[bool] = None,
    grid: bool = False,
) -> Callable:
    """Unified feature-sharded fit builder: every optimizer x layout x
    feature combination the replicated path supports, on the 2-D
    (data, model) mesh. The reference composes normalization
    (NormalizationContext.scala:119-157, applied inside aggregators),
    variances (DistributedOptimizationProblem.scala:79-93), and box
    projection (LBFGS.scala:77) freely with distribution; so do we —
    Hdiag and the box projection are block-local/elementwise, and the
    lazy shift/factor algebra shards along the feature axis with one
    extra psum'd scalar.

    Returns ``fit(w0, batch, l2, *extras)`` where extras are, in order:
    ``l1, l1_mask`` (owlqn), ``shift, factor`` (with_norm; full [d_pad]
    vectors, sharded over the model axis), ``lower, upper`` (with_box;
    full [d_pad] vectors). ``meta`` is required for the tiled layout.

    ``grid=True`` builds the batched λ-grid variant: ``w0`` becomes a
    [G, d_pad] coefficient bank, ``l2`` (and owlqn's ``l1``) become [G]
    vectors, and the shard_map body runs ``vmap(optimizer)`` over the
    grid axis — every member's block solve shares ONE compiled program
    and, on the tiled layout, one fused schedule walk per data pass
    (ops.tiled_sparse._bilinear_pass_auto's custom_vmap rule). The
    returned OptResult carries a leading grid axis on every field.
    """
    if optimizer not in ("lbfgs", "owlqn", "tron"):
        raise ValueError(f"unknown optimizer {optimizer!r}")
    if layout not in ("sparse", "tiled"):
        raise ValueError(f"unknown layout {layout!r}")
    if layout == "tiled":
        if meta is None:
            raise ValueError("tiled layout requires the batch meta")
        from photon_ml_tpu.utils.backend import effective_platform

        if interpret is None:
            interpret = effective_platform() == "cpu"
    cache_key = (
        objective, _mesh_content_key(mesh), meta, layout, optimizer,
        data_axis, model_axis, max_iter, tol, history, max_cg,
        with_norm, with_box, track_models, interpret, grid,
    )
    from photon_ml_tpu.utils.memo import get_or_build

    return get_or_build(
        _FS_FIT_CACHE, _FS_FIT_CACHE_MAX, cache_key,
        lambda: _build_feature_sharded_glm_fit(
            objective, mesh, meta, layout=layout, optimizer=optimizer,
            data_axis=data_axis, model_axis=model_axis, max_iter=max_iter,
            tol=tol, history=history, max_cg=max_cg, with_norm=with_norm,
            with_box=with_box, track_models=track_models,
            interpret=interpret, grid=grid,
        ),
    )


def _build_feature_sharded_glm_fit(
    objective: GLMObjective,
    mesh: Mesh,
    meta,
    *,
    layout: str,
    optimizer: str,
    data_axis: str,
    model_axis: str,
    max_iter: int,
    tol: float,
    history: int,
    max_cg: int,
    with_norm: bool,
    with_box: bool,
    track_models: bool,
    interpret: Optional[bool],
    grid: bool = False,
) -> Callable:
    from photon_ml_tpu.optim.common import BoxConstraints
    from photon_ml_tpu.optim.lbfgs import minimize_owlqn
    from photon_ml_tpu.optim.tron import minimize_tron

    loss = objective.loss
    owlqn = optimizer == "owlqn"
    tron = optimizer == "tron"

    extra_specs = []
    if owlqn:
        extra_specs += [P(), P(model_axis)]  # l1, l1_mask
    if with_norm:
        extra_specs += [P(model_axis), P(model_axis)]  # shift, factor
    if with_box:
        extra_specs += [P(model_axis), P(model_axis)]  # lower, upper

    def _unpack(extras):
        i = 0
        l1 = l1_mask = shift = factor = box = None
        if owlqn:
            l1, l1_mask = extras[0], extras[1]
            i = 2
        if with_norm:
            shift, factor = extras[i], extras[i + 1]
            i += 2
        if with_box:
            box = BoxConstraints(lower=extras[i], upper=extras[i + 1])
        return l1, l1_mask, shift, factor, box

    def _dispatch(vg, hvp_factory, w0_block, l1, l1_mask, box):
        if tron:
            return minimize_tron(
                vg, None, w0_block, max_iter=max_iter, tol=tol,
                max_cg=max_cg, box=box, axis_name=model_axis,
                hvp_factory=hvp_factory, track_coefficients=track_models,
            )
        if owlqn:
            return minimize_owlqn(
                vg, w0_block, l1, max_iter=max_iter, tol=tol,
                history=history, l1_mask=l1_mask, box=box,
                axis_name=model_axis, track_coefficients=track_models,
            )
        return minimize_lbfgs(
            vg, w0_block, max_iter=max_iter, tol=tol, history=history,
            box=box, axis_name=model_axis, track_coefficients=track_models,
        )

    def _solve(make_vg, make_factory, w0_block, l1, l2, l1_mask, box):
        """One block solve (grid=False) or the vmapped bank of G solves
        (grid=True: w0_block is [G, d_block], l1/l2 are [G] — one
        program, per-member convergence masked by the batched
        while_loop)."""
        if not grid:
            vg = make_vg(l2)
            factory = make_factory(l2) if tron else None
            return _dispatch(vg, factory, w0_block, l1, l1_mask, box)
        l1_vec = (
            l1 if l1 is not None
            else jnp.zeros((w0_block.shape[0],), w0_block.dtype)
        )

        def run_one(w0_b, l1_, l2_):
            vg = make_vg(l2_)
            factory = make_factory(l2_) if tron else None
            return _dispatch(vg, factory, w0_b, l1_, l1_mask, box)

        return jax.vmap(run_one)(w0_block, l1_vec, l2)

    w0_spec = P(None, model_axis) if grid else P(model_axis)
    out_specs = (
        _opt_result_grid_specs(model_axis, track_models)
        if grid else _opt_result_specs(model_axis, track_models)
    )

    if layout == "tiled":
        from photon_ml_tpu.ops.tiled_sparse import (
            FeatureShardedTiledBatch,
            tiled_block_local_hvp_factory,
            tiled_block_local_vg,
        )

        sched_spec = P((data_axis, model_axis))

        # photon: sharding(axes=[data,model], in=?, out=?)
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                w0_spec, sched_spec, sched_spec,
                P(data_axis), P(data_axis), P(data_axis), P(),
                tuple(extra_specs),
            ),
            out_specs=out_specs,
            check_vma=False,
        )
        def _fit(w0_block, z_sched, g_sched, labels, offsets, weights, l2,
                 extras):
            l1, l1_mask, shift, factor, box = _unpack(extras)
            cell = FeatureShardedTiledBatch(
                meta, z_sched, g_sched, labels, offsets, weights
            )

            def make_vg(l2_):
                return tiled_block_local_vg(
                    loss, cell, data_axis, model_axis, l2_,
                    shift=shift, factor=factor, interpret=interpret,
                )

            def make_factory(l2_):
                return tiled_block_local_hvp_factory(
                    loss, cell, data_axis, model_axis, l2_,
                    shift=shift, factor=factor, interpret=interpret,
                )

            return _solve(
                make_vg, make_factory, w0_block, l1, l2, l1_mask, box
            )

        def fit(w0, batch, l2, *extras):
            return _fit(
                w0, batch.z_sched, batch.g_sched, batch.labels,
                batch.offsets, batch.weights, l2, tuple(extras),
            )
    else:

        # photon: sharding(axes=[data,model], in=?, out=?)
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(w0_spec,)
            + _sparse_shard_specs(model_axis, data_axis)[1:]
            + (tuple(extra_specs),),
            out_specs=out_specs,
            check_vma=False,
        )
        def _fit(w0_block, b, l2, extras):
            l1, l1_mask, shift, factor, box = _unpack(extras)

            def make_vg(l2_):
                return _sparse_block_vg(
                    loss, b, l2_, model_axis, data_axis,
                    shift=shift, factor=factor,
                )

            def make_factory(l2_):
                return _sparse_block_hvp_factory(
                    loss, b, l2_, model_axis, data_axis,
                    shift=shift, factor=factor,
                )

            return _solve(
                make_vg, make_factory, w0_block, l1, l2, l1_mask, box
            )

        def fit(w0, batch, l2, *extras):
            return _fit(w0, batch, l2, tuple(extras))

    return jax.jit(fit)


def feature_sharded_extras(
    dim: int,
    d_pad: int,
    *,
    normalization=None,
    box=None,
    use_owlqn: bool = False,
    intercept_index: Optional[int] = None,
):
    """Assemble feature_sharded_glm_fit's positional extras protocol in
    ONE place (fit call order: [l1, l1_mask] from the caller, then this
    tail = [shift, factor] when normalization is active, then
    [lower, upper] when a box is given — all padded to [d_pad] with inert
    fills). Returns ``(extras_tail, l1_mask, with_norm)``; ``l1_mask`` is
    None unless ``use_owlqn`` (intercept exempt, like the replicated
    GLMOptimizationProblem._l1_mask). Both train_feature_sharded and the
    GAME FixedEffectCoordinate build their calls from here so the
    protocol cannot silently diverge."""
    with_norm = normalization is not None and not normalization.is_identity

    def _pad(v, fill):
        v = jnp.asarray(v, jnp.float32)
        if v.shape[0] == d_pad:
            return v
        return jnp.concatenate(
            [v, jnp.full((d_pad - v.shape[0],), fill, jnp.float32)]
        )

    extras_tail = []
    if with_norm:
        # padded slots are inert: shift 0, factor 1
        extras_tail += [
            _pad(
                normalization.shift
                if normalization.shift is not None
                else jnp.zeros((dim,), jnp.float32),
                0.0,
            ),
            _pad(
                normalization.factor
                if normalization.factor is not None
                else jnp.ones((dim,), jnp.float32),
                1.0,
            ),
        ]
    if box is not None:
        # padded slots unconstrained so padding coefficients stay at 0
        extras_tail += [_pad(box.lower, -jnp.inf), _pad(box.upper, jnp.inf)]
    l1_mask = None
    if use_owlqn:
        l1_mask = jnp.ones((d_pad,), jnp.float32)
        if intercept_index is not None:
            l1_mask = l1_mask.at[intercept_index].set(0.0)
    return extras_tail, l1_mask, with_norm


def feature_sharded_hessian_diagonal(
    objective: GLMObjective,
    mesh: Mesh,
    meta=None,
    *,
    layout: str = "sparse",
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    with_norm: bool = False,
    interpret: Optional[bool] = None,
) -> Callable:
    """Hessian diagonal over the feature-sharded layouts — the variance
    computation (DistributedOptimizationProblem.scala:79-93) composed with
    feature sharding. Returns ``hdiag(w, batch, l2[, shift, factor])``
    producing the full [d_pad] diagonal (gathered across blocks)."""
    loss = objective.loss
    if layout == "tiled":
        if meta is None:
            raise ValueError("tiled layout requires the batch meta")
        from photon_ml_tpu.utils.backend import effective_platform

        if interpret is None:
            interpret = effective_platform() == "cpu"
    norm_specs = (P(model_axis), P(model_axis)) if with_norm else ()

    if layout == "tiled":
        from photon_ml_tpu.ops.tiled_sparse import (
            FeatureShardedTiledBatch,
            tiled_block_local_hdiag,
        )

        sched_spec = P((data_axis, model_axis))

        # photon: sharding(axes=[data,model], in=?, out=[model])
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(
                P(model_axis), sched_spec, sched_spec,
                P(data_axis), P(data_axis), P(data_axis), P(),
                tuple(norm_specs),
            ),
            out_specs=P(model_axis),
            check_vma=False,
        )
        def _hdiag(w_block, z_sched, g_sched, labels, offsets, weights, l2,
                   extras):
            shift, factor = extras if with_norm else (None, None)
            cell = FeatureShardedTiledBatch(
                meta, z_sched, g_sched, labels, offsets, weights
            )
            return tiled_block_local_hdiag(
                loss, cell, data_axis, model_axis, l2,
                shift=shift, factor=factor, interpret=interpret,
            )(w_block)

        def hdiag(w, batch, l2, *extras):
            return _hdiag(
                w, batch.z_sched, batch.g_sched, batch.labels,
                batch.offsets, batch.weights, l2, tuple(extras),
            )
    else:

        # photon: sharding(axes=[data,model], in=?, out=[model])
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=_sparse_shard_specs(model_axis, data_axis)
            + (tuple(norm_specs),),
            out_specs=P(model_axis),
            check_vma=False,
        )
        def _hdiag(w_block, b, l2, extras):
            shift, factor = extras if with_norm else (None, None)
            return _sparse_block_hdiag(
                loss, b, l2, model_axis, data_axis,
                shift=shift, factor=factor,
            )(w_block)

        def hdiag(w, batch, l2, *extras):
            return _hdiag(w, batch, l2, tuple(extras))

    return jax.jit(hdiag)


def feature_sharded_sparse_fit_owlqn(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    max_iter: int = 50,
    tol: float = 1e-7,
    history: int = 10,
) -> Callable:
    """OWL-QN over the sparse feature-sharded layout: the L1/elastic-net
    path for >HBM coefficient vectors. ``fit(w0, sharded_batch, l2, l1,
    l1_mask)`` (L2 first, matching the smooth objective; ``l1_mask`` a
    full [d_pad] 0/1 vector — 0 exempts a slot, e.g. the intercept — split
    over the model axis like w); the L1 term lives in the optimizer
    (pseudo-gradient/orthant rules are elementwise over the local block,
    scalars psum — same recipe as L-BFGS).

    Thin wrapper over :func:`feature_sharded_glm_fit` (the one sharded
    program family) preserving this entry point's historical defaults."""
    return feature_sharded_glm_fit(
        objective, mesh, layout="sparse", optimizer="owlqn",
        data_axis=data_axis, model_axis=model_axis,
        max_iter=max_iter, tol=tol, history=history,
    )
