"""Distributed GLM objectives and training steps under shard_map.

Reference mapping (SURVEY §2.3/§2.4):
- P1 data parallelism: examples sharded over the "data" axis, coefficients
  replicated, (value, grad, Hv) psum'ed — replaces
  DistributedGLMLossFunction + ValueAndGradientAggregator.treeAggregate
  (ValueAndGradientAggregator.scala:235-250).
- Feature/coefficient parallelism ("model" axis): for coefficient vectors
  too big to replicate, margins decompose over feature blocks
  (z = sum_blocks x_b . w_b -> psum over "model"), and each device keeps
  only its gradient/optimizer-state block — the reduce-scatter/all-gather
  recipe of sequence parallelism applied to the feature axis (the 10B-coef
  design addition; no literal analog exists in the reference).

Both run the UNMODIFIED optimizers from photon_ml_tpu.optim: the objective
closure psums, so LBFGS/OWLQN/TRON never know they are distributed —
exactly how the reference reuses one Optimizer against Distributed vs
SingleNode objectives (SURVEY L2/L3).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from photon_ml_tpu.data.batch import Batch, DenseBatch
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.common import OptResult
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs
from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

Array = jnp.ndarray


def data_parallel_value_and_grad(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
) -> Callable:
    """(w, batch, l2) -> (value, grad), batch sharded over ``data_axis``,
    coefficients replicated. One psum per evaluation (the treeAggregate)."""
    obj = objective.with_axis(data_axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(data_axis), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    def vg(w, batch, l2):
        return obj.value_and_gradient(w, batch, l2)

    return vg


def data_parallel_fit_lbfgs(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    max_iter: int = 100,
    tol: float = 1e-7,
    history: int = 10,
) -> Callable[[Array, Batch, Array], OptResult]:
    """Whole L-BFGS fit inside ONE shard_map program: per-iteration psums
    ride ICI with no host round-trips (vs one treeAggregate round-trip per
    Breeze iteration in the reference, SURVEY §3.1)."""
    obj = objective.with_axis(data_axis)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(data_axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    def fit(w0, batch, l2):
        vg = lambda w: obj.value_and_gradient(w, batch, l2)
        return minimize_lbfgs(
            vg, w0, max_iter=max_iter, tol=tol, history=history
        )

    return fit


# ---------------------------------------------------------------------------
# Feature-axis ("model") sharding for >HBM coefficient vectors
# ---------------------------------------------------------------------------


def feature_sharded_value_and_grad(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
) -> Callable:
    """2-D sharded objective over DENSE feature blocks.

    Layout: features [n, d] sharded P(data, model); w [d] sharded P(model);
    per-device partial margins psum over ``model_axis``; loss row-reductions
    psum over ``data_axis``; gradient blocks stay device-local (each device
    owns grad[d_block] — reduce-scatter-free by construction). Returns
    (value replicated, grad sharded P(model)).
    """
    loss = objective.loss

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(model_axis), P(data_axis, model_axis), P(data_axis), P(data_axis), P(data_axis), P()),
        out_specs=(P(), P(model_axis)),
        check_vma=False,
    )
    def vg(w_block, x_block, labels, offsets, weights, l2):
        # partial margins from this feature block, summed across blocks
        z = jax.lax.psum(x_block @ w_block, model_axis) + offsets
        lv = loss.value(z, labels)
        ld = loss.d1(z, labels)
        c = weights * ld
        value = jax.lax.psum(jnp.sum(weights * lv), data_axis)
        # gradient for THIS feature block only; reduce over examples
        grad_block = jax.lax.psum(x_block.T @ c, data_axis)
        # L2 term: w stays sharded; psum the squared-norm contributions
        w_sq = jax.lax.psum(jnp.vdot(w_block, w_block), model_axis)
        value = value + 0.5 * l2 * w_sq
        grad_block = grad_block + l2 * w_block
        return value, grad_block

    return vg


def feature_sharded_fit(
    objective: GLMObjective,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    max_iter: int = 50,
    tol: float = 1e-7,
    history: int = 10,
) -> Callable:
    """L-BFGS over a feature-sharded coefficient vector: optimizer state
    ([m, d_block] memories, w block) lives SHARDED on every device; the only
    cross-block traffic per iteration is the margin psum and the scalar
    reductions inside the two-loop recursion (vdots psum over model axis).
    """
    loss = objective.loss

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(model_axis), P(data_axis, model_axis), P(data_axis), P(data_axis), P(data_axis), P()),
        out_specs=P(model_axis),
        check_vma=False,
    )
    def fit(w0_block, x_block, labels, offsets, weights, l2):
        def vg(w_block):
            z = jax.lax.psum(x_block @ w_block, model_axis) + offsets
            c = weights * loss.d1(z, labels)
            value = jax.lax.psum(jnp.sum(weights * loss.value(z, labels)), data_axis)
            grad_block = jax.lax.psum(x_block.T @ c, data_axis)
            w_sq = jax.lax.psum(jnp.vdot(w_block, w_block), model_axis)
            return value + 0.5 * l2 * w_sq, grad_block + l2 * w_block

        return _block_lbfgs(vg, w0_block, model_axis, max_iter, tol, history)

    return fit


def _block_lbfgs(vg, w0, model_axis, max_iter, tol, history):
    """L-BFGS whose inner products psum over the model axis — numerically
    identical to replicated L-BFGS, state fully sharded."""
    from jax import lax

    def gdot(a, b):
        return lax.psum(jnp.vdot(a, b), model_axis)

    def gnorm(a):
        return jnp.sqrt(gdot(a, a))

    m = history
    d = w0.shape[0]
    f0, g0 = vg(w0)
    g0_norm = gnorm(g0)

    def two_loop(g, s_h, y_h, rho, length, ptr):
        alphas = jnp.zeros((m,), g.dtype)

        def backward(i, carry):
            q, alphas = carry
            idx = jnp.mod(ptr - 1 - i, m)
            valid = i < length
            a = jnp.where(valid, rho[idx] * gdot(s_h[idx], q), 0.0)
            return q - a * y_h[idx], alphas.at[idx].set(a)

        q, alphas = lax.fori_loop(0, m, backward, (g, alphas))
        last = jnp.mod(ptr - 1, m)
        ys = gdot(s_h[last], y_h[last])
        yy = gdot(y_h[last], y_h[last])
        gamma = jnp.where(length > 0, ys / jnp.maximum(yy, 1e-30), 1.0)
        r = gamma * q

        def forward(i, r):
            idx = jnp.mod(ptr - length + i, m)
            valid = i < length
            b = jnp.where(valid, rho[idx] * gdot(y_h[idx], r), 0.0)
            return r + jnp.where(valid, alphas[idx] - b, 0.0) * s_h[idx]

        return -lax.fori_loop(0, m, forward, r)

    def line_search(w, f, g, direction, t0):
        def trial(t):
            w_t = w + t * direction
            f_t, g_t = vg(w_t)
            return w_t, f_t, g_t

        def ok_fn(w_t, f_t):
            return (f_t <= f + 1e-4 * gdot(g, w_t - w)) & jnp.isfinite(f_t)

        def cond(state):
            _, w_t, f_t, _, k = state
            return (~ok_fn(w_t, f_t)) & (k < 24)

        def body(state):
            t, _, _, _, k = state
            t2 = t * 0.5
            w_n, f_n, g_n = trial(t2)
            return (t2, w_n, f_n, g_n, k + 1)

        w1, f1, g1 = trial(t0)
        t, w_t, f_t, g_t, _ = lax.while_loop(
            cond, body, (t0, w1, f1, g1, jnp.zeros((), jnp.int32))
        )
        ok = ok_fn(w_t, f_t)
        return (
            jnp.where(ok, 1.0, 0.0),
            jnp.where(ok, w_t, w),
            jnp.where(ok, f_t, f),
            jnp.where(ok, g_t, g),
        )

    def cond(st):
        (w, f, g, s_h, y_h, rho, length, ptr, it, done) = st
        return ~done

    def body(st):
        (w, f, g, s_h, y_h, rho, length, ptr, it, done) = st
        direction = two_loop(g, s_h, y_h, rho, length, ptr)
        descent = gdot(direction, g) < 0
        direction = jnp.where(descent, direction, -g)
        t0 = jnp.where(length > 0, 1.0, 1.0 / jnp.maximum(gnorm(direction), 1.0))
        ok, w2, f2, g2 = line_search(w, f, g, direction, t0)
        s = w2 - w
        y = g2 - g
        ys = gdot(y, s)
        store = ys > 1e-10
        s_h2 = jnp.where(store, s_h.at[ptr].set(s), s_h)
        y_h2 = jnp.where(store, y_h.at[ptr].set(y), y_h)
        rho2 = jnp.where(store, rho.at[ptr].set(1.0 / jnp.maximum(ys, 1e-30)), rho)
        length2 = jnp.where(store, jnp.minimum(length + 1, m), length)
        ptr2 = jnp.where(store, jnp.mod(ptr + 1, m), ptr)
        it2 = it + 1
        converged = (
            (jnp.abs(f2 - f) <= tol * jnp.abs(f0))
            | (gnorm(g2) <= tol * g0_norm)
            | (it2 >= max_iter)
            | (ok == 0.0)
        )
        return (w2, f2, g2, s_h2, y_h2, rho2, length2, ptr2, it2, converged)

    init = (
        w0, f0, g0,
        jnp.zeros((m, d), w0.dtype), jnp.zeros((m, d), w0.dtype),
        jnp.zeros((m,), w0.dtype),
        jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
        jnp.zeros((), jnp.int32),
        g0_norm == 0.0,
    )
    final = jax.lax.while_loop(cond, body, init)
    return final[0]
