"""Entity re-sharding: the all_to_all analog of the reference's shuffles.

Reference: RandomEffectDataSet groups rows by entity with a groupByKey/
partitionBy shuffle over netty (RandomEffectDataSet.scala:169-243;
SURVEY §2.4 "shuffle ops"). On TPU the same re-keying is an in-jit
``lax.all_to_all`` over ICI: each device routes its resident rows to the
device that owns the row's entity, with static send/receive capacities.

Ownership is ``entity_code % num_devices`` — the LongHashPartitioner
analog (util/LongHashPartitioner.scala): stable, stateless, and balanced
for hashed entity ids. Rows with code < 0 (padding) are dropped.

Static-shape contract: every device sends exactly ``cap`` rows to every
other device (weight-0 padding fills the gaps). If more than ``cap`` real
rows on one device map to one target, the overflow rows are DROPPED and
reported in the returned counts — callers size ``cap`` from host-side
entity statistics (the RandomEffectDataSetPartitioner's load counts) and
assert no overflow.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from photon_ml_tpu import ownership
from photon_ml_tpu.parallel.mesh import DATA_AXIS

Array = jnp.ndarray


class ShuffledRows(NamedTuple):
    """Result of an entity re-shard, rows grouped by owning device.

    Per device (leading axis sharded over the mesh axis):
    - ``entity_codes [n_out]``: re-sharded codes, -1 on padding slots
    - ``payload``: pytree of [n_out, ...] arrays aligned with the codes
    - ``received [1]``: number of real rows that landed on this device
    - ``dropped [1]``: rows lost to capacity overflow ON THE SEND side
      (sum over devices = global drops; 0 means the re-shard is lossless)
    """

    entity_codes: Array
    payload: object
    received: Array
    dropped: Array


def entity_all_to_all(
    mesh: Mesh,
    entity_codes: Array,
    payload,
    *,
    cap: int,
    axis: str = DATA_AXIS,
) -> ShuffledRows:
    """Re-shard rows to their owning device (code % n_devices).

    ``entity_codes [n]`` and every payload leaf ``[n, ...]`` are sharded
    over ``axis``; n must divide the axis size. Each device receives
    ``n_devices * cap`` row slots (its share from every peer).
    """
    n_dev = int(mesh.shape[axis])

    # photon: sharding(axes=[data], in=?, out=?)
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(axis), jax.tree.map(lambda _: P(axis), payload)),
        out_specs=ShuffledRows(
            entity_codes=P(axis),
            payload=jax.tree.map(lambda _: P(axis), payload),
            received=P(axis),
            dropped=P(axis),
        ),
        check_vma=False,
    )
    def reshard(codes, data):
        n_loc = codes.shape[0]
        # pad rows -> pseudo-owner n_dev (the trash slot); real rows go
        # to the shared ownership rule's shard
        owner = jnp.where(
            codes >= 0, ownership.owner_of(codes, n_dev), n_dev
        )
        # Slot of each row within its (this-device -> owner) send buffer:
        # rank among same-owner rows, computed via a stable sort.
        order = jnp.argsort(owner)  # pads sort last
        sorted_owner = owner[order]
        # rank within group = position - first position of the group
        first_of_group = jnp.searchsorted(sorted_owner, sorted_owner)
        rank_sorted = jnp.arange(n_loc) - first_of_group
        rank = jnp.zeros((n_loc,), jnp.int32).at[order].set(
            rank_sorted.astype(jnp.int32)
        )
        keep = (codes >= 0) & (rank < cap)
        # send buffers: [n_dev, cap] slots; dropped rows scatter to a trash
        # row appended at index n_dev*cap.
        slot = jnp.where(keep, owner * cap + rank, n_dev * cap)
        send_codes = jnp.full((n_dev * cap + 1,), -1, codes.dtype)
        send_codes = send_codes.at[slot].set(
            jnp.where(keep, codes, -1), mode="drop"
        )[:-1]

        def route(leaf):
            buf = jnp.zeros((n_dev * cap + 1,) + leaf.shape[1:], leaf.dtype)
            masked = jnp.where(
                keep.reshape((-1,) + (1,) * (leaf.ndim - 1)), leaf, 0
            )
            return buf.at[slot].set(masked, mode="drop")[:-1]

        send_payload = jax.tree.map(route, data)
        dropped = jnp.sum((codes >= 0) & ~keep).reshape(1)

        # all_to_all: split axis 0 (per-target blocks) across devices,
        # concat received blocks along axis 0.
        def exchange(buf):
            blocks = buf.reshape((n_dev, cap) + buf.shape[1:])
            out = lax.all_to_all(
                blocks, axis, split_axis=0, concat_axis=0, tiled=False
            )
            return out.reshape((n_dev * cap,) + buf.shape[1:])

        recv_codes = exchange(send_codes)
        recv_payload = jax.tree.map(exchange, send_payload)
        received = jnp.sum(recv_codes >= 0).reshape(1)
        return ShuffledRows(
            entity_codes=recv_codes,
            payload=recv_payload,
            received=received,
            dropped=dropped,
        )

    return reshard(entity_codes, payload)


def reshard_capacity(
    entity_codes, n_devices: int, *, slack: float = 1.25
) -> int:
    """Host-side capacity sizing from actual entity statistics (the
    RandomEffectDataSetPartitioner's count pass): max rows any (source
    shard, target device) pair must carry, times ``slack``, rounded to 8.
    """
    import numpy as np

    codes = np.asarray(entity_codes)
    n = codes.shape[0]
    per_src = n // n_devices
    worst = 0
    for s in range(n_devices):
        local = codes[s * per_src : (s + 1) * per_src]
        local = local[local >= 0]
        if local.size:
            counts = np.bincount(
                ownership.owner_of(local, n_devices), minlength=n_devices
            )
            worst = max(worst, int(counts.max()))
    cap = int(np.ceil(worst * slack))
    return max(((cap + 7) // 8) * 8, 8)
