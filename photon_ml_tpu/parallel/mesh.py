"""Device mesh construction + batch sharding helpers.

This layer replaces the reference's entire "distributed runtime" — Spark
partitioning/broadcast/treeAggregate over netty (SURVEY §2.4; photon-ml
RDDLike.scala:26-61, BroadcastLike.scala:26) — with a jax.sharding.Mesh and
XLA collectives over ICI:

- treeAggregate(depth)        -> lax.psum over the "data" axis
- sc.broadcast(coefficients)  -> replicated sharding (PartitionSpec())
- feature-dimension scale-out -> coefficient sharding over the "model" axis
  (the design addition for >HBM models, SURVEY §2.3 row "absent")
- entity re-sharding shuffle  -> all_to_all / sorted gathers ("entity" axis)

Axis names: "data" (examples), "model" (features/coefficients); the
random-effect bank shards entities over "data" as well (entities are the
expert-parallel analog, SURVEY P2).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices=None,
) -> Mesh:
    """Build a Mesh over available devices; default 1-D data mesh."""
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != #devices {len(devices)}")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def data_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Leading-axis (example) sharding."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a batch pytree with rows sharded over ``axis``; row counts must
    divide the mesh axis (pad first — make_sparse_batch pads to multiples)."""
    sharding = data_sharding(mesh, axis)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def replicate(tree, mesh: Mesh):
    return jax.tree.map(lambda a: jax.device_put(a, replicated(mesh)), tree)
