"""Device mesh construction + batch sharding helpers.

This layer replaces the reference's entire "distributed runtime" — Spark
partitioning/broadcast/treeAggregate over netty (SURVEY §2.4; photon-ml
RDDLike.scala:26-61, BroadcastLike.scala:26) — with a jax.sharding.Mesh and
XLA collectives over ICI:

- treeAggregate(depth)        -> lax.psum over the "data" axis
- sc.broadcast(coefficients)  -> replicated sharding (PartitionSpec())
- feature-dimension scale-out -> coefficient sharding over the "model" axis
  (the design addition for >HBM models, SURVEY §2.3 row "absent")
- entity re-sharding shuffle  -> all_to_all / sorted gathers ("entity" axis)

Axis names: "data" (examples), "model" (features/coefficients); the
random-effect bank shards entities over "data" as well (entities are the
expert-parallel analog, SURVEY P2).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
# Pod-scale GAME (game/pod.py): random-effect banks + their optimizer/
# tracker state shard entities over this axis by entity hash
# (code % n_shards — the LongHashPartitioner analog), residuals ride
# all_to_alls instead of host gathers. Distinct from DATA_AXIS so an
# entity mesh can coexist with a (data, model) FE mesh in one driver.
ENTITY_AXIS = "entity"
# Unified-mesh λ-grid axis (parallel/unified_mesh.py): grid members
# (regularization weights) shard over this axis so a [G, ...] coefficient
# bank / optimizer-state bank is P(grid, entity)-sharded and the whole
# sweep runs as ONE shard_mapped program. Orthogonal to the other three:
# a (grid, entity) mesh trains G entity-sharded GAME members at once.
GRID_AXIS = "grid"


def make_mesh(
    shape: Optional[Tuple[int, ...]] = None,
    axis_names: Sequence[str] = (DATA_AXIS,),
    devices=None,
) -> Mesh:
    """Build a Mesh over available devices; default 1-D data mesh."""
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
    if int(np.prod(shape)) != len(devices):
        raise ValueError(f"mesh shape {shape} != #devices {len(devices)}")
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, tuple(axis_names))


def data_sharding(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """Leading-axis (example) sharding."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh, axis: str = DATA_AXIS):
    """Place a batch pytree with rows sharded over ``axis``; row counts must
    divide the mesh axis (pad first — make_sparse_batch pads to multiples)."""
    sharding = data_sharding(mesh, axis)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def pad_batch_rows(batch, multiple: int):
    """Pad a Sparse/Dense batch's row axis up to a multiple (weight-0
    padding rows are inert in every objective — the Spark-partition-
    remainder analog). Returns the batch unchanged if already aligned."""
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import DenseBatch, SparseBatch

    n = batch.labels.shape[0]
    n_pad = ((n + multiple - 1) // multiple) * multiple
    if n_pad == n:
        return batch
    extra = n_pad - n

    def pad1(a):
        return jnp.concatenate([a, jnp.zeros((extra,), a.dtype)])

    if isinstance(batch, SparseBatch):
        return SparseBatch(
            indices=jnp.concatenate(
                [batch.indices,
                 jnp.zeros((extra, batch.indices.shape[1]), batch.indices.dtype)]
            ),
            values=jnp.concatenate(
                [batch.values,
                 jnp.zeros((extra, batch.values.shape[1]), batch.values.dtype)]
            ),
            labels=pad1(batch.labels),
            offsets=pad1(batch.offsets),
            weights=pad1(batch.weights),
        )
    if isinstance(batch, DenseBatch):
        return DenseBatch(
            features=jnp.concatenate(
                [batch.features,
                 jnp.zeros((extra, batch.features.shape[1]), batch.features.dtype)]
            ),
            labels=pad1(batch.labels),
            offsets=pad1(batch.offsets),
            weights=pad1(batch.weights),
        )
    raise TypeError(f"cannot row-pad {type(batch).__name__}")


def replicate(tree, mesh: Mesh):
    return jax.tree.map(lambda a: jax.device_put(a, replicated(mesh)), tree)


def ensure_data_sharded(batch, mesh: Mesh, axis: str = DATA_AXIS):
    """Idempotent pad+shard: returns the batch unchanged when its rows are
    already sharded over ``axis`` on this mesh (so a lambda-grid loop pays
    the host->device transfer once, not once per regularization weight)."""
    sharding = data_sharding(mesh, axis)
    if getattr(batch.labels, "sharding", None) == sharding:
        return batch
    n_shards = int(mesh.shape[axis])
    return shard_batch(pad_batch_rows(batch, n_shards), mesh, axis)


def entity_mesh(
    num_shards: Optional[int] = None, devices=None
) -> Mesh:
    """1-D mesh over the ``entity`` axis for hash-sharded random-effect
    banks (game/pod.py). ``num_shards`` defaults to every visible
    device; fewer shards use the first ``num_shards`` devices (the
    virtual-mesh weak-scaling harness runs N in {1, 2, 4, 8} on an
    8-device host)."""
    devices = list(devices if devices is not None else jax.devices())
    if num_shards is None:
        num_shards = len(devices)
    if not 1 <= num_shards <= len(devices):
        raise ValueError(
            f"entity shards {num_shards} out of range for "
            f"{len(devices)} visible devices"
        )
    return make_mesh((num_shards,), (ENTITY_AXIS,), devices[:num_shards])


def maybe_make_mesh(
    distributed: str, model_shards: Optional[int] = None
) -> Optional[Mesh]:
    """Shared driver policy.

    "auto" -> 1-D data mesh over all devices when more than one is
    visible, else None; "off" -> None; "feature" -> 2-D (data, model)
    mesh for feature-sharded coefficients (model axis = ``model_shards``,
    default 2; data axis = remaining devices).
    """
    if distributed not in ("auto", "off", "feature"):
        raise ValueError(
            f"unknown distributed mode {distributed!r}; "
            "expected auto | off | feature"
        )
    n = len(jax.devices())
    if distributed == "off" or n < 2:
        return None
    if distributed == "feature":
        m = model_shards if model_shards is not None else 2
        if n % m != 0:
            raise ValueError(
                f"model_shards={m} does not divide the {n} visible devices"
            )
        return make_mesh((n // m, m), (DATA_AXIS, MODEL_AXIS))
    return make_mesh()
